"""Continuous-batching decode engine — slot-based KV-cache serving.

New capability relative to the reference, which serves single-shot vision
models only (SURVEY.md §7 stage 7; the reference's executor takes one batch,
runs one forward, returns — ``293-project/src/scheduler.py:435-472``).
Autoregressive decode for the BASELINE.json GPT-2/Llama configs needs a
different hot loop: requests *join and leave* a long-running batch between
steps (Orca-style continuous batching).

TPU-first design — everything is static-shape so exactly TWO kinds of
compiled programs serve the whole stream:

- ``prefill[T]``: one per prompt-length bucket T. Runs the prompt on a fresh
  single-row cache, scatters the full row into the big decode cache at a
  *traced* slot index (``lax.dynamic_update_slice`` — no recompile per slot),
  and returns the first sampled token.
- ``decode_step``: one program for all ``num_slots`` slots, every step.
  Inactive slots are masked, their scatters dropped. Greedy sampling happens
  *in-program* (argmax over vocab) so only ``[B]`` token ids — not ``[B, V]``
  logits — cross the device→host boundary per step.

The big cache is **donated** through both programs, so XLA updates it in
place in HBM — zero realloc, zero copy per token (SURVEY.md §7 hard part (e)).
Admission between steps pulls from the shared :class:`RequestQueue`, keeping
the Nexus staleness-discard and SLO accounting on the decode path too.

Two throughput levers on the hot loop:

- **Decode horizon**: when the batch is full (or nothing is waiting), the
  engine runs ``decode_horizon`` steps in ONE compiled ``lax.scan`` program
  per host round-trip, so the per-token device→host sync (the dominant
  non-FLOP cost of continuous batching) is amortized h-fold. Slots that hit
  EOS mid-horizon produce discarded tokens for the remainder — bounded waste
  traded for sync amortization. With free slots and a non-empty queue the
  engine drops to single steps so admissions stay prompt.
- **Token-budgeted chunked admission** (paged engines; slab opt-in via
  ``chunked_prefill=True``): EVERY admission is a chunk train — the
  prompt split into compiled ``<=C``-token chunk programs whose k/v
  scatter straight through the slot's page table (pages granted per
  chunk from the shared allocator, CoW-borrowed prefix pages skipped) —
  and the engine's own step loop spends at most
  ``prefill_token_budget`` tokens advancing pending trains between
  decode turns. A burst of arrivals therefore never stalls an active
  slot behind a serial prefill train (head-of-line blocking): the stall
  bound is ONE chunk program per decode turn, regardless of how much
  prefill is queued. The final chunk program samples the first token
  in-program, so TTFT ends at a ``[B]`` ids fetch — never a logits
  round-trip. Engines running the legacy monolithic path instead ration
  admissions by count (``max_admissions_per_step`` prefills between
  decode steps), which merely bounds how MANY full-prompt programs
  stall each round.

Streaming: requests carrying a :class:`~.request.TokenStream` get every
token pushed as it reaches the host, before the sequence finishes (ref
generator batches, ``serve/batching.py:209-276``).
"""

from __future__ import annotations

import collections
import math
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_dynamic_batching_tpu.engine.request import (
    BadRequest,
    Request,
    RequestDropped,
    now_ms,
)
from ray_dynamic_batching_tpu.engine.paging import (
    HostSpillTier,
    OutOfPages,
    PageAllocator,
    PagedPrefixCache,
    PagedSessionCache,
    PageEventJournal,
    digest_chain,
    table_array,
)
from ray_dynamic_batching_tpu.engine.pagefabric import (
    PREFIX,
    STREAM,
    PageParcel,
    export_prefix_parcel,
    export_stream_parcel,
)
from ray_dynamic_batching_tpu.engine.queue import RequestQueue
from ray_dynamic_batching_tpu.ops import jit_model
from ray_dynamic_batching_tpu.ops.tile_math import (
    lane_aligned_page,
    pages_for,
    spec_scratch_pages,
)
from ray_dynamic_batching_tpu.utils.compile_ledger import (
    PHASE_WARMUP,
    get_ledger,
    instrument,
)
from ray_dynamic_batching_tpu.profiles.table import bucket_up
from ray_dynamic_batching_tpu.utils.concurrency import OrderedLock
from ray_dynamic_batching_tpu.utils.logging import get_logger
from ray_dynamic_batching_tpu.utils import metrics as m
from ray_dynamic_batching_tpu.utils.tracing import link_to as _link_to
from ray_dynamic_batching_tpu.utils.tracing import tracer as _tracer

logger = get_logger("decode")

TOKENS_TOTAL = m.Counter(
    "rdb_decode_tokens_total", "Generated tokens", tag_keys=("model",)
)
DECODE_STEPS = m.Counter(
    "rdb_decode_steps_total", "Decode steps executed", tag_keys=("model",)
)
PREFILLS_TOTAL = m.Counter(
    "rdb_decode_prefills_total", "Prompts prefilled", tag_keys=("model",)
)
TTFT_MS = m.Histogram(
    "rdb_decode_ttft_ms", "Time to first token", tag_keys=("model",)
)
TTFT_QUEUE_MS = m.Histogram(
    "rdb_decode_ttft_queue_ms",
    "Arrival->dequeue share of TTFT (includes waiting out in-flight scans)",
    tag_keys=("model",),
)
TTFT_PREFILL_MS = m.Histogram(
    "rdb_decode_ttft_prefill_ms",
    "Dequeue->first-token share of TTFT",
    tag_keys=("model",),
)
ACTIVE_SLOTS = m.Gauge(
    "rdb_decode_active_slots", "Slots currently decoding", tag_keys=("model",)
)


@dataclass
class DecodeResult:
    """Fulfilled into the request future when a sequence finishes."""

    tokens: List[int]
    finish_reason: str            # "eos" | "length" | "capacity"
    ttft_ms: float
    total_ms: float


@dataclass
class _Slot:
    request: Optional[Request] = None
    generated: List[int] = field(default_factory=list)
    max_new_tokens: int = 0
    prefill_done_ms: float = 0.0
    last_token: int = 0
    stop: frozenset = frozenset()  # per-request stop token ids
    session_id: Optional[str] = None        # store row on finish
    prompt_tokens: Optional[np.ndarray] = None  # session history head
    # Paged mode: physical page ids in logical order; the first
    # ``shared_pages`` of them are borrowed (refcounted) from a
    # prefix/session entry and are never written by this slot.
    pages: List[int] = field(default_factory=list)
    shared_pages: int = 0

    @property
    def free(self) -> bool:
        return self.request is None


@dataclass
class _ChunkTrain:
    """One admission mid-chunked-prefill: the unit the token-budget
    scheduler advances between decode turns. The train HOLDS its slot
    (``_free_slots`` excludes it) and — paged — the pages granted so
    far (``opts['_pages']``: CoW-borrowed head + per-chunk grants);
    ``pos`` is the next global position to prefill, ``base`` the first
    position this train computes (positions below it were seeded from
    borrowed prefix/session pages, or a slab session row)."""

    req: Request
    prompt: np.ndarray
    opts: Dict
    slot_idx: int
    C: int                 # chunk width (compiled program shape)
    pos: int = 0           # next global position to prefill
    base: int = 0          # first computed position (CoW/session skip)
    total: int = 0         # prompt length (prefill ends here)
    row: Any = None        # slab mode: private row cache
    last: Any = None       # slab mode: last chunk's take-row logits
    insert_prefix: bool = False  # slab: publish chunk 0 on completion
    started_ms: float = 0.0


# Speculation observability (ISSUE 13 satellite): the ``paged`` tag
# ("true"/"false") splits the slab and paged spec arms so an A/B capture
# can never conflate them; accepted + rejected == drafted is a per-round
# conservation invariant pinned in tier-1 (tests/test_spec_paged.py).
SPEC_ROUNDS = m.Counter(
    "rdb_decode_spec_rounds_total", "Speculative verify rounds",
    tag_keys=("model", "paged"),
)
SPEC_ACCEPTED = m.Counter(
    "rdb_decode_spec_accepted_total", "Draft tokens accepted by verify",
    tag_keys=("model", "paged"),
)
SPEC_DRAFTED = m.Counter(
    "rdb_decode_spec_drafted_total", "Draft tokens proposed to verify",
    tag_keys=("model", "paged"),
)
SPEC_REJECTED = m.Counter(
    "rdb_decode_spec_rejected_total", "Draft tokens rejected by verify",
    tag_keys=("model", "paged"),
)
SPEC_ACCEPTANCE = m.Gauge(
    "rdb_decode_spec_acceptance",
    "Rolling draft-token acceptance rate (accepted/drafted, bounded "
    "window)", tag_keys=("model", "paged"),
)
PREFIX_HITS = m.Counter(
    "rdb_decode_prefix_hits_total", "Prompt-prefix KV cache hits",
    # granularity: "chunk" = slab whole-segment byte equality, "page" =
    # paged longest-shared-page-prefix (ISSUE 7 satellite).
    tag_keys=("model", "granularity"),
)
PREFIX_MISSES = m.Counter(
    "rdb_decode_prefix_misses_total", "Prompt-prefix KV cache misses",
    tag_keys=("model", "granularity"),
)
KV_PAGES_FREE = m.Gauge(
    "rdb_decode_kv_pages_free", "Free pages in the paged KV pool",
    tag_keys=("model",),
)
KV_PAGE_OCCUPANCY = m.Gauge(
    "rdb_decode_kv_page_occupancy",
    "Allocated fraction of the paged KV pool", tag_keys=("model",),
)
PAGE_EVICTIONS = m.Counter(
    "rdb_decode_page_evictions_total",
    "Slots capacity-finished to reclaim pages (over-subscribed pool)",
    tag_keys=("model",),
)
# Token-budget prefill scheduler (ISSUE 15): chunk programs dispatched,
# trains parked on page starvation, and the live pending-train depth.
PREFILL_CHUNKS = m.Counter(
    "rdb_decode_prefill_chunks_total",
    "Chunk programs dispatched by the token-budget prefill scheduler",
    tag_keys=("model",),
)
PREFILL_STARVED = m.Counter(
    "rdb_decode_prefill_starved_total",
    "Chunk dispatches deferred by page starvation (train parked)",
    tag_keys=("model",),
)
PREFILL_PENDING = m.Gauge(
    "rdb_decode_prefill_pending_trains",
    "Chunk trains awaiting prefill budget", tag_keys=("model",),
)


def copy_rows_into(cache, rows, slots):
    """Scatter a row-cache's per-request rows into the shared cache at
    ``slots`` (static unroll — row count is a compile-time constant).
    Shared by the target and draft prefill programs so the write rule
    cannot diverge between them. Quantized caches carry their scale
    planes through the same scatter — dropping them would reconstruct
    garbage KV for every admitted prompt."""
    nB = rows.lengths.shape[0]
    k, v, lengths = cache.k, cache.v, cache.lengths
    ks, vs = cache.k_scale, cache.v_scale
    for i in range(nB):
        k = jax.lax.dynamic_update_slice(
            k, rows.k[:, i : i + 1], (0, slots[i], 0, 0, 0)
        )
        v = jax.lax.dynamic_update_slice(
            v, rows.v[:, i : i + 1], (0, slots[i], 0, 0, 0)
        )
        if ks is not None:
            ks = jax.lax.dynamic_update_slice(
                ks, rows.k_scale[:, i : i + 1], (0, slots[i], 0, 0)
            )
            vs = jax.lax.dynamic_update_slice(
                vs, rows.v_scale[:, i : i + 1], (0, slots[i], 0, 0)
            )
        lengths = jax.lax.dynamic_update_slice(
            lengths, rows.lengths[i : i + 1], (slots[i],)
        )
    return cache.replace(k=k, v=v, lengths=lengths,
                         k_scale=ks, v_scale=vs)


def commit_row(cache, row, slot):
    """Copy a single finished row cache into the shared cache at ``slot``,
    slicing the (whole-chunk-rounded, possibly longer) row down to shared
    capacity. Shared by the target and draft chunked-prefill commits."""
    S = cache.capacity
    k = jax.lax.dynamic_update_slice(
        cache.k, row.k[:, :, :S], (0, slot, 0, 0, 0)
    )
    v = jax.lax.dynamic_update_slice(
        cache.v, row.v[:, :, :S], (0, slot, 0, 0, 0)
    )
    ks, vs = cache.k_scale, cache.v_scale
    if ks is not None:
        ks = jax.lax.dynamic_update_slice(
            ks, row.k_scale[:, :, :S], (0, slot, 0, 0)
        )
        vs = jax.lax.dynamic_update_slice(
            vs, row.v_scale[:, :, :S], (0, slot, 0, 0)
        )
    lengths = jax.lax.dynamic_update_slice(
        cache.lengths, row.lengths, (slot,)
    )
    return cache.replace(k=k, v=v, lengths=lengths,
                         k_scale=ks, v_scale=vs)


def _row_as_pages(arr, S: int, ps: int):
    """[L, nB, rowcap, ...] row-cache array -> [L, nB*NP, ps, ...] page
    stack covering the first ``S`` positions (rowcap >= S by the paged
    row-capacity rule; S is a whole number of pages)."""
    L, nB = arr.shape[0], arr.shape[1]
    sliced = arr[:, :, :S]
    return sliced.reshape((L, nB * (S // ps), ps) + arr.shape[3:])


def copy_rows_into_paged(cache, rows, slots, write_pids):
    """Scatter per-request row caches into the PAGED pool: each row is
    cut into page-size pieces and lands at the physical pages
    ``write_pids`` names ([nB, NP] int32; sentinel entries — shared
    CoW pages and unallocated tail — steer out of bounds and DROP, so
    a borrowed prefix page is never rewritten). ``slots`` places the
    per-slot lengths. The paged analogue of :func:`copy_rows_into`;
    duplicate pad rows write identical data to identical pages, which
    stays idempotent."""
    S = cache.page_table.shape[1] * cache.page_size
    ps = cache.page_size
    flat = write_pids.reshape(-1)
    k = cache.k.at[:, flat].set(_row_as_pages(rows.k, S, ps), mode="drop")
    v = cache.v.at[:, flat].set(_row_as_pages(rows.v, S, ps), mode="drop")
    ks, vs = cache.k_scale, cache.v_scale
    if ks is not None:
        ks = ks.at[:, flat].set(
            _row_as_pages(rows.k_scale, S, ps), mode="drop"
        )
        vs = vs.at[:, flat].set(
            _row_as_pages(rows.v_scale, S, ps), mode="drop"
        )
    lengths = cache.lengths.at[slots].set(rows.lengths)
    return cache.replace(k=k, v=v, lengths=lengths,
                         k_scale=ks, v_scale=vs)


def run_chunked(chunk_fn, params, prompt, C, row, start_chunk=0,
                between=None, after_first=None, base=0):
    """Host loop driving a compiled chunk program over a (tail of a)
    prompt: full-width chunks, right-padded tail, optional ``between``
    callback after every non-final chunk (the decode-interleave hook) and
    ``after_first`` on chunk 0 (the prefix-cache insert hook). ``base`` is
    the global position of ``prompt[0]`` — nonzero when earlier positions
    were seeded from cached KV (session continuation), and need not be
    chunk-aligned (the chunk program takes a traced start). Returns
    (last_logits, row)."""
    L = int(prompt.size)
    n_chunks = (L + C - 1) // C
    last = None
    for ci in range(start_chunk, n_chunks):
        piece = prompt[ci * C : (ci + 1) * C]
        tokens = np.zeros((1, C), dtype=np.int32)
        mask = np.zeros((1, C), dtype=np.int32)
        tokens[0, : piece.size] = piece
        mask[0, : piece.size] = 1
        last, row = chunk_fn(
            params,
            jnp.asarray(tokens),
            jnp.asarray(mask),
            row,
            jnp.int32(base + ci * C),
            jnp.int32(piece.size - 1),
        )
        if ci == 0 and after_first is not None:
            after_first(row)
        if ci < n_chunks - 1 and between is not None:
            between()
    return last, row


class _DeviceLRU:
    """Bounded LRU whose values hold DEVICE arrays: dropping the last
    reference on eviction frees the HBM on GC. Shared mechanics for the
    prefix and session caches so the eviction/touch invariants cannot
    diverge."""

    def __init__(self, capacity: int):
        from collections import OrderedDict

        self.capacity = int(capacity)
        self._entries = OrderedDict()

    def _get(self, key):
        entry = self._entries.get(key)
        if entry is not None:
            self._entries.move_to_end(key)
        return entry

    def _put(self, key, value) -> None:
        self._entries[key] = value
        self._entries.move_to_end(key)
        while len(self._entries) > self.capacity:
            self._entries.popitem(last=False)  # device buffers freed on GC

    def clear(self) -> None:
        self._entries.clear()

    def __len__(self) -> int:
        return len(self._entries)


class PrefixCache(_DeviceLRU):
    """Device-resident LRU of prompt-prefix KV segments.

    Long prompts often share a fixed head (system prompt, few-shot
    preamble). Each entry stores one chunk-width's worth of computed k/v
    (``[L, 1, C, K, H]`` pair, device arrays) keyed by the EXACT first-C
    token ids; a hit seeds the admission's row cache with a copy instead of
    recomputing the chunk — pure HBM traffic versus a full forward pass.
    Fixed segment width keeps every shape static (one compiled seed
    program). vLLM-style paged prefix trees need dynamic block tables; this
    is the static-shape TPU rendition, deliberately chunk-granular.
    """

    def __init__(self, capacity: int, width: int):
        super().__init__(capacity)
        self.width = int(width)

    def _key(self, prompt: np.ndarray) -> bytes:
        return np.ascontiguousarray(prompt[: self.width]).tobytes()

    def lookup(self, prompt: np.ndarray) -> Optional[Tuple]:
        """(k, v, k_scale, v_scale) — scales None for bf16 caches."""
        return self._get(self._key(prompt))

    def insert(self, prompt: np.ndarray, k: jax.Array, v: jax.Array,
               k_scale=None, v_scale=None) -> None:
        key = self._key(prompt)
        if key not in self._entries:
            self._put(key, (k, v, k_scale, v_scale))


SESSION_HITS = m.Counter(
    "rdb_decode_session_hits_total", "Session KV continuations",
    tag_keys=("model",),
)
SESSION_MISSES = m.Counter(
    "rdb_decode_session_misses_total",
    "Session requests without reusable KV", tag_keys=("model",),
)


class SessionCache(_DeviceLRU):
    """Device-resident LRU of finished conversation turns, keyed by
    session id.

    Multi-turn chat resends the whole history each turn; KV depends only
    on token ids, so the previous turn's cache row (prompt + generated
    tokens) is exactly the prefix KV of the next turn's prompt. A hit
    seeds the admission with the stored row and prefills ONLY the new
    tail — turn-N TTFT stops scaling with conversation length. Entries
    hold one full cache row ([L,1,S,K,H] k/v, device arrays) plus the
    token history for the prefix check; sampling temperature is
    irrelevant to reuse (KV is deterministic in the tokens)."""

    def lookup(self, session_id: str, prompt: np.ndarray):
        """Return (k, v, k_scale, v_scale, history_len) when the stored
        turn is a strict prefix of ``prompt`` (leaving >= 1 tail token
        to prefill); scales are None for bf16 caches."""
        entry = self._get(session_id)
        if entry is None:
            return None
        seg, history = entry
        n = int(history.size)
        if n >= prompt.size or not np.array_equal(history, prompt[:n]):
            return None
        return (*seg, n)

    def store(self, session_id: str, seg: Tuple,
              history: np.ndarray) -> None:
        """``seg`` is _extract_row_impl's (k, v, k_scale, v_scale)."""
        self._put(session_id, (seg, np.asarray(history, np.int32)))


class DecodeEngine:
    """Continuous-batching executor for one CausalLM on one chip/mesh slice.

    ``model`` must provide the decode interface of
    :class:`~ray_dynamic_batching_tpu.models.causal_lm.CausalLM`:
    ``make_cache``, ``prefill``, ``decode_step``, and ``cfg``.
    """

    def __init__(
        self,
        model: Any,
        params: Any,
        queue: RequestQueue,
        num_slots: int = 8,
        max_len: int = 256,
        prompt_buckets: Optional[Sequence[int]] = None,
        eos_token_id: Optional[int] = None,
        default_max_new_tokens: int = 64,
        idle_wait_s: float = 0.005,
        sample_fn: Optional[Callable[[jax.Array], jax.Array]] = None,
        decode_horizon: int = 8,
        ttft_horizon: Optional[int] = None,
        max_admissions_per_step: int = 2,
        prefix_cache_size: int = 0,
        session_cache_size: int = 0,
        draft_model: Optional[Any] = None,
        draft_params: Optional[Any] = None,
        spec_tokens: int = 4,
        quantize_weights: bool = False,
        device: Optional[jax.Device] = None,
        mesh: Optional[Any] = None,
        base_seed: int = 0,
        paged: bool = False,
        page_size: int = 128,
        kv_pool_pages: Optional[int] = None,
        host_spill_pages: int = 0,
        chunked_prefill: Optional[bool] = None,
        prefill_token_budget: Optional[int] = None,
    ):
        from ray_dynamic_batching_tpu.utils.compile_cache import maybe_enable

        maybe_enable()  # prefill/decode program compiles become disk hits
        self.model = model
        self.device = device
        self.mesh = mesh
        if paged and draft_model is not None and mesh is not None:
            # Loud, like the draft-model conflict ISSUE 13 lifted (and
            # the PR 10 TP-paged pattern): the spec verify window would
            # need the scratch-page scatter AND the staircase kernel
            # runnable per-shard under shard_map — neither is wired yet,
            # and a silent slab/plain fallback would mislabel every A/B
            # capture stamped from the config. Checked BEFORE any
            # sharding work so a misconfigured replica fails in
            # microseconds, not after a multi-GB param reshard.
            raise ValueError(
                "speculative decoding over a TP-mesh paged pool is not "
                "supported yet: run paged+spec on single-chip replicas, "
                "or drop the draft model for mesh slices"
            )
        # Weight-only int8: decode streams the whole weight set per step,
        # so weight BYTES set tokens/s; kernels live in HBM as int8 and
        # dequantize inside each program (convert+scale fused into the
        # consuming matmul by XLA).
        self.quantized = bool(quantize_weights)
        if self.quantized:
            if mesh is not None:
                raise ValueError(
                    "quantize_weights with a TP mesh is not supported yet: "
                    "sharding rules key on kernel paths, which quantization "
                    "rewrites into QTensor q/scale leaves"
                )
            from ray_dynamic_batching_tpu.models.quant import quantize_tree

            # Idempotent: a pre-quantized tree (the deployment quantizes
            # ONCE and hands the same tree to every length-bucket engine)
            # passes through shared, no fresh int8 copy per engine.
            params = quantize_tree(params)
        if mesh is not None:
            # TP-sharded replica (BASELINE.json config 4): params sharded by
            # the model's Megatron-style rules, KV cache sharded over kv
            # heads (cache_pspec), decode collectives ride ICI via GSPMD —
            # the serving analogue of the reference's NCCL allreduce swap.
            from ray_dynamic_batching_tpu.parallel.mesh import shard_params

            params = shard_params(mesh, model, params)
        elif device is not None:
            # Chip pinning (placement-group bundle): params live on the
            # reserved chip; every dispatch runs under default_device so the
            # cache and all uploads land there too.
            params = jax.device_put(params, device)
        self.params = params
        self.queue = queue
        self.num_slots = num_slots
        self.max_len = max_len
        self.prompt_buckets = sorted(prompt_buckets or [16, 32, 64, 128])
        self.prompt_buckets = [b for b in self.prompt_buckets if b <= max_len]
        self.eos_token_id = eos_token_id
        self.default_max_new_tokens = default_max_new_tokens
        self.idle_wait_s = idle_wait_s
        # Legacy whole-batch override; when None the parametric per-request
        # sampler (temperature / top-k / seed) runs in-program.
        self._sample_custom = sample_fn
        self.base_seed = int(base_seed)

        self._slots = [_Slot() for _ in range(num_slots)]
        # Host mirror of per-slot cache lengths (updated from each scan's
        # packed result): drives paged page-headroom math and the
        # kv_occupancy() residency metric in BOTH modes.
        self._len_host = np.zeros((num_slots,), dtype=np.int32)
        # --- paged KV pool (ISSUE 7 tentpole) ---------------------------
        # Slab mode gives every slot a private max_len run; paged mode
        # backs all slots with one pool of lane-aligned pages gathered
        # through per-slot page tables, so HBM occupancy follows cached
        # tokens (freed at EOS mid-cycle) and prefix/session reuse
        # shares pages copy-on-write instead of copying rows.
        self.paged = bool(paged)
        self.page_size = int(page_size)
        self._page_journal: Optional[PageEventJournal] = None
        if self.paged:
            if not lane_aligned_page(self.page_size):
                raise ValueError(
                    f"page_size {self.page_size} must be a 128-lane "
                    "multiple (ops/tile_math.lane_aligned_page): the int8 "
                    "scale tile streams the page as its lane dim"
                )
            # Logical per-slot capacity: whole pages covering max_len.
            # The engine still enforces max_len (token-exactness vs the
            # slab path); the partial last page is headroom that is
            # never attended past max_len.
            self._n_table_entries = pages_for(max_len, self.page_size)
            self._paged_capacity = self._n_table_entries * self.page_size
            full_backing = num_slots * self._n_table_entries
            self.num_pages = int(kv_pool_pages or full_backing)
            # The pool may be over-subscribed (num_pages < full backing:
            # the occupancy win) but must hold at least one slot's worth
            # or nothing can ever decode.
            if self.num_pages < self._n_table_entries:
                raise ValueError(
                    f"kv_pool_pages {self.num_pages} cannot back even one "
                    f"slot ({self._n_table_entries} pages at page_size "
                    f"{self.page_size}, max_len {max_len})"
                )
            # Allocator event journal (bounded ring): alloc/free land
            # from the allocator itself, CoW borrows / cache reclaims /
            # capacity evictions from their decision sites below —
            # rendered as Perfetto instant events + a page-occupancy
            # counter track by utils/trace_export, surfaced by
            # ``snapshot()``.
            self._page_journal = PageEventJournal()
            self._allocator = PageAllocator(self.num_pages,
                                            journal=self._page_journal)
            self._table_host = np.full(
                (num_slots, self._n_table_entries), self.num_pages,
                dtype=np.int32,
            )
            self._table_dirty = True
            if mesh is not None and not hasattr(model, "paged_cache_pspec"):
                # Loud, like the draft-model conflict: silently
                # allocating the pool on ONE chip under a TP mesh would
                # reshard it through ICI every step and mislabel every
                # measurement stamped from the config (the PR-7 silent-
                # fallback class).
                raise ValueError(
                    f"{getattr(model, 'name', type(model).__name__)}: "
                    "paged=True on a TP mesh needs the model to define "
                    "paged_cache_pspec (the pool's sharding layout) — "
                    "see CausalLM.paged_cache_pspec"
                )
            if mesh is not None:
                # TP serving slice over the paged pool (ROADMAP item 2):
                # pages shard on the kv-head dim exactly like the slab
                # TP cache (codes + scales planes included); the page
                # table, lengths, and the host-side free-list allocator
                # stay replica-global — page indices are shard-
                # invariant. The decode kernel runs per-shard head
                # slices under the mesh (ops/attention.tensor_parallel
                # -> paged_decode_attention's shard_map wrapper); the
                # CPU/XLA gather fallback partitions from the pool's
                # NamedSharding under plain GSPMD, so both read paths
                # stay token-exact vs the single-chip pool.
                from ray_dynamic_batching_tpu.parallel.mesh import (
                    make_sharded_paged_cache,
                )

                self._cache = make_sharded_paged_cache(
                    mesh, model, num_slots, self.num_pages,
                    self.page_size, self._paged_capacity,
                )
            else:
                with self._device_ctx():
                    self._cache = model.make_paged_cache(
                        num_slots, self.num_pages, self.page_size,
                        self._paged_capacity,
                    )
        elif mesh is not None and hasattr(model, "cache_pspec"):
            from ray_dynamic_batching_tpu.parallel.mesh import (
                make_sharded_cache,
            )

            self._cache = make_sharded_cache(mesh, model, num_slots, max_len)
        else:
            with self._device_ctx():
                self._cache = model.make_cache(num_slots, max_len)
        self._tokens = np.zeros((num_slots, 1), dtype=np.int32)
        self._active_mask = np.zeros((num_slots,), dtype=bool)
        # Per-slot sampling params (temperature 0 == greedy).
        self._temps = np.zeros((num_slots,), dtype=np.float32)
        self._topk = np.zeros((num_slots,), dtype=np.int32)
        self._topp = np.ones((num_slots,), dtype=np.float32)
        self._seeds = np.zeros((num_slots,), dtype=np.int32)
        # Per-slot presence/frequency penalties over GENERATED tokens
        # (repetition control; the prompt is not counted — documented
        # variant of the OpenAI semantics). Counts live ON DEVICE so the
        # horizon scan updates them in-carry without host syncs.
        self._pres = np.zeros((num_slots,), dtype=np.float32)
        self._freq = np.zeros((num_slots,), dtype=np.float32)
        V = getattr(getattr(model, "cfg", None), "vocab_size", 0)
        with self._device_ctx():
            self._counts = jnp.zeros((num_slots, max(V, 1)), jnp.int32)
        # Per-slot sparse logit bias (OpenAI-style logit_bias; banned
        # tokens ride as -inf bias): fixed K entries keep shapes static,
        # padding rows are (id 0, value 0) — an add of 0, not a mask.
        self.max_bias_entries = 16
        self._bias_ids = np.zeros((num_slots, self.max_bias_entries),
                                  dtype=np.int32)
        self._bias_vals = np.zeros((num_slots, self.max_bias_entries),
                                   dtype=np.float32)

        self.decode_horizon = max(1, int(decode_horizon))
        # Bound on admission latency while slots are free: an arrival during
        # a compiled scan cannot be admitted until the scan returns, so the
        # idle-queue horizon caps TTFT at ttft_horizon * per-step latency
        # instead of decode_horizon * per-step latency (~4x shorter by
        # default). Full horizon still runs when the batch is full, where
        # admission is impossible anyway and throughput is the constraint.
        if ttft_horizon is None:
            ttft_horizon = max(1, self.decode_horizon // 4)
        self.ttft_horizon = min(max(1, int(ttft_horizon)),
                                self.decode_horizon)
        self.max_admissions_per_step = max(1, int(max_admissions_per_step))
        # --- token-budget chunked admission (ISSUE 15 tentpole) ---------
        # Chunked prefill is the UNIVERSAL admission path on the paged
        # engine (pages-direct chunk k/v, first-token fusion); slab
        # engines opt in (row-cache chunks + fused commit) — the A/B arm
        # the exactness matrix compares. ``prefill_token_budget`` is the
        # most prefill tokens one scheduler round may spend between
        # decode turns; clamped to >= one chunk width so a full-width
        # chunk can always dispatch (otherwise nothing would ever
        # admit). With the default budget of exactly one chunk, no
        # running stream ever waits more than ONE chunk program between
        # its turns — the stall bound tier-1 pins.
        if chunked_prefill is None:
            chunked_prefill = self.paged
        self.chunked_prefill = bool(chunked_prefill)
        _chunk_w = self.prompt_buckets[-1] if self.prompt_buckets \
            else max_len
        self.prefill_token_budget = max(
            int(prefill_token_budget or _chunk_w), _chunk_w
        )
        self._trains: List[_ChunkTrain] = []   # FIFO (arrival order)
        self._train_slots: set = set()
        # Interleave cadence log (bounded): ("chunk", tokens) /
        # ("turn", horizon) events, the stall-bound pin's observable.
        self.interleave_log: collections.deque = collections.deque(
            maxlen=4096
        )
        # TTFT decomposition: (queue_wait, scan_wait, prefill) per admission
        # over a rolling window — queue_wait is arrival->dequeue (slot
        # starvation + waiting out in-flight scans), scan_wait the portion
        # of that spent inside the scan that was running at arrival, and
        # prefill is dequeue->first token. Consumed by ttft_breakdown();
        # the bench LLM row publishes it so an on-chip run shows where the
        # TTFT milliseconds live (BASELINE.json north star: p50 < 150 ms).
        self._scan_start_ms = 0.0
        self._scan_end_ms = 0.0
        self._ttft_parts: collections.deque = collections.deque(maxlen=1024)
        # Prompt-prefix KV reuse for chunked admissions (0 = off). Paged
        # engines reuse by page REFERENCE (longest shared page-prefix,
        # copy-on-write at the partial boundary page); slab engines keep
        # the chunk-granular device-copy caches.
        self.prefix_cache: Optional[PrefixCache] = None
        self.paged_prefix: Optional[PagedPrefixCache] = None
        if prefix_cache_size > 0 and self.prompt_buckets:
            if self.paged:
                self.paged_prefix = PagedPrefixCache(
                    prefix_cache_size, self.page_size, self._allocator
                )
            else:
                self.prefix_cache = PrefixCache(
                    prefix_cache_size, self.prompt_buckets[-1]
                )
        # HBM -> host-RAM spill tier (0 = off): prefix-cache entries shed
        # under pool pressure spill their page CONTENTS to host RAM and
        # reload on the next matching prompt — hot system prompts survive
        # pool churn instead of recomputing (ISSUE 11; paged-only, and
        # pointless without a prefix cache to spill from).
        self.host_spill: Optional[HostSpillTier] = None
        if host_spill_pages > 0 and self.paged_prefix is not None:
            self.host_spill = HostSpillTier(
                host_spill_pages, self._read_pages, self._write_pages,
                journal=self._page_journal,
            )
        # Multi-turn session KV continuation (0 = off). Paged store pins
        # the finished slot's pages (O(1), no row copy).
        self.session_cache: Optional[SessionCache] = None
        self.paged_sessions: Optional[PagedSessionCache] = None
        if session_cache_size > 0:
            if self.paged:
                self.paged_sessions = PagedSessionCache(
                    session_cache_size, self.page_size, self._allocator
                )
            else:
                self.session_cache = SessionCache(session_cache_size)
        self._prefill_fns: Dict[int, Callable] = {}
        # Donations: cache (arg 1) and counts (arg 8 — params=0,
        # cache=1, step_state=2, horizon=3, samp_f=4, samp_i=5,
        # bias_ids=6, bias_vals=7, counts=8).
        self._decode_fn = instrument("decode_step", jax.jit(
            self._decode_impl, donate_argnums=(1, 8), static_argnums=(3,)
        ))
        # Pages-direct chunk program (chunked paged admission): one jit,
        # retraced per (group, width) shape; the pool cache (arg 2) is
        # donated across chunks.
        self._chunk_paged_fn = instrument("chunk_prefill", jax.jit(
            self._chunk_group_paged_impl, donate_argnums=(2,)
        ))
        # Speculative decoding (greedy rows only): a small draft proposes
        # spec_tokens continuations per slot, the target verifies the whole
        # window in ONE forward, and the accepted prefix + the target's
        # correction land at once — n tokens per target dispatch instead of
        # one, with EXACT greedy equivalence (rejected tails are garbage
        # past ``lengths``, the same invariant every other path relies on).
        self.draft_model = draft_model
        self.spec_tokens = max(1, int(spec_tokens))
        self._dcache = None
        # Rolling (accepted, drafted) pairs per spec round: feeds the
        # rdb_decode_spec_acceptance gauge, spec_acceptance(), the bench
        # row's acceptance stamp, and the sim's profiled-acceptance
        # input. Bounded so a long-lived engine tracks the incident, not
        # the healthy morning.
        self._spec_acc_window: collections.deque = collections.deque(
            maxlen=512
        )
        # Per-round scratch bookkeeping (paged spec): slot -> (first
        # table index, scratch page ids). ALWAYS resolved (spliced or
        # freed) before the round's harvest, so no scratch page can
        # outlive its round or leak through a finish.
        self._spec_scratch: Dict[int, Tuple[int, List[int]]] = {}
        if draft_model is not None:
            if draft_params is None:
                raise ValueError("draft_model requires draft_params")
            if mesh is not None:
                from ray_dynamic_batching_tpu.parallel.mesh import (
                    shard_params as _shard,
                )

                draft_params = _shard(mesh, draft_model, draft_params)
            elif device is not None:
                draft_params = jax.device_put(draft_params, device)
            self.draft_params = draft_params
            with self._device_ctx():
                # Headroom past max_len: the draft drafts spec_tokens+1
                # ahead of the verified length near the end of the cache.
                # The draft cache stays a SLAB even on paged engines: the
                # shared pool's pages are target-geometry tensors (K, H
                # of the big model), so the small draft would need a
                # second pool of its own shape for a footprint that is a
                # rounding error next to the target's — the TARGET-side
                # KV of drafted tokens is what pages (scratch pages,
                # spliced on accept).
                self._dcache = draft_model.make_cache(
                    num_slots, max_len + self.spec_tokens + 1
                )
            self._spec_fn = instrument("spec_verify", jax.jit(
                self._spec_impl, donate_argnums=(1, 2)
            ))
            self._draft_catchup_fn = instrument("draft_catchup", jax.jit(
                self._draft_catchup_impl, donate_argnums=(1,)
            ))
        def _reset_counts(counts, slot, first_tok):
            # Fresh tenant: zero the reused row, then count the PREFILL-
            # sampled first token (the scan only counts tokens it samples
            # itself — without this, the first token repeats once free).
            counts = jax.lax.dynamic_update_slice(
                counts,
                jnp.zeros((1, counts.shape[1]), jnp.int32),
                (slot, 0),
            )
            return counts.at[slot, first_tok].set(1)

        self._zero_counts_fn = instrument(
            "zero_counts", jax.jit(_reset_counts, donate_argnums=(0,))
        )
        # Device copies of the per-slot sampling arrays: they change only
        # at admission/finish, but _step dispatches every few ms — without
        # the cache every dispatch re-uploads seven small host arrays
        # (temps/topk/topp/seeds/bias/pres/freq), pure per-step overhead
        # on a tunneled chip.
        self._sampling_dev = None
        # Installed by a colocation executor: called between chunk
        # dispatches of long admissions so co-tenants aren't stalled.
        self.interleave_hook: Optional[Callable[[], None]] = None
        # Requests mid-admission (dequeued, not yet slotted) — see _admit.
        self._admitting = 0
        self._admitting_batch: List[Request] = []
        # --- page-fabric mailboxes (live migration + prefix push) ---
        # Slots are engine-thread-owned; the controller/courier request
        # work through these thread-safe mailboxes and the loop services
        # them between decode turns (_service_fabric). The lock reuses
        # the "allocator" rank (100) — its reserved purpose — and must
        # NEVER be held across queue (80) or request-fulfil (90) calls:
        # _service_fabric pops under the lock into locals, releases,
        # then processes.
        self._fabric_lock = OrderedLock("allocator")
        self._migrate_out_q: List[Tuple[str, Callable[[PageParcel], bool]]] = []
        self._push_out_q: List[Tuple[bytes, Callable[[PageParcel], bool]]] = []
        self._parcel_in_q: List[PageParcel] = []
        self.migrated_out = 0
        self.migrated_in = 0
        self.pushes_out = 0
        self.pushes_in = 0
        self._thread: Optional[threading.Thread] = None
        self._run = threading.Event()
        self.steps = 0
        self.completed = 0
        # Progress heartbeat for replica health checks: refreshed only by
        # SUCCESSFUL loop iterations, so a perpetually-failing _step (device
        # OOM, corrupt params) reads as a stall even though the thread lives.
        self.last_heartbeat = time.monotonic()

    def _device_ctx(self):
        """jax.default_device scope for the pinned chip (no-op unpinned)."""
        import contextlib

        if self.device is None:
            return contextlib.nullcontext()
        return jax.default_device(self.device)

    # --- compiled programs -------------------------------------------------
    def _mp(self, params):
        """Model-ready params: dequantize INSIDE the program when the
        resident tree is int8 (no-op otherwise)."""
        if not self.quantized:
            return params
        from ray_dynamic_batching_tpu.models.quant import dequantize_tree

        return dequantize_tree(
            params, getattr(self.model, "dtype", jnp.bfloat16)
        )

    @staticmethod
    def _apply_bias(logits, bias_ids, bias_vals):
        """Sparse per-row logit bias: logits[b, ids[b, j]] += vals[b, j].
        Padding entries are (0, 0.0) — a no-op add. Runs before BOTH
        greedy argmax and sampling so biased greedy stays deterministic
        (the speculative verify path applies the same bias)."""
        B = logits.shape[0]
        rows = jnp.arange(B)[:, None]
        return logits.at[rows, bias_ids].add(
            bias_vals.astype(logits.dtype)
        )

    def _sample_tokens(self, logits, temps, topk, seeds, tok_idx,
                       bias_ids=None, bias_vals=None, topp=None):
        """In-program per-request sampling: temperature 0 → greedy argmax;
        otherwise top-k-masked categorical, keyed by (base_seed, request
        seed, TOKEN INDEX within the request) — so a request's stream is
        reproducible regardless of slot, batch neighbors, or how much
        traffic the engine served before it, and no two positions of one
        request reuse a key. One compiled program covers every sampling
        configuration; a ``lax.cond`` skips the full-vocab sort + draws at
        RUNTIME when the whole batch is greedy (the default hot path).

        logits [B, V]; temps [B] f32; topk [B] i32; seeds [B] i32;
        tok_idx [B] i32 (index of the token being sampled per request).
        """
        logits = logits.astype(jnp.float32)
        if bias_ids is not None:
            # Before BOTH built-in and custom samplers: a ban the caller
            # was told is enforced must bind regardless of sampler.
            logits = self._apply_bias(logits, bias_ids, bias_vals)
        if self._sample_custom is not None:
            return self._sample_custom(logits).astype(jnp.int32)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

        if topp is None:
            topp = jnp.ones(logits.shape[:1], jnp.float32)

        def draw(args):
            lg, tm, tk, tp, sd, ti = args
            V = lg.shape[-1]
            # top-k mask (k<=0 means no truncation)
            k_eff = jnp.where(tk > 0, jnp.minimum(tk, V), V)
            sorted_desc = -jnp.sort(-lg, axis=-1)
            kth = jnp.take_along_axis(
                sorted_desc, (k_eff - 1)[:, None], axis=-1
            )
            masked = jnp.where(lg < kth, -jnp.inf, lg)
            scaled = masked / jnp.maximum(tm, 1e-6)[:, None]
            # top-p (nucleus): keep the smallest prefix of the sorted
            # distribution whose mass reaches p; the cutoff token itself
            # stays (cum - prob < p). p >= 1 or <= 0 disables. The sorted
            # view derives from the top-k sort above (mask + positive
            # scale are monotone) — no second full-vocab sort.
            p_eff = jnp.where((tp > 0.0) & (tp < 1.0), tp, 1.0)[:, None]
            ranks = jnp.arange(V)[None, :]
            sorted_scaled = jnp.where(
                ranks < k_eff[:, None], sorted_desc, -jnp.inf
            ) / jnp.maximum(tm, 1e-6)[:, None]
            probs = jax.nn.softmax(sorted_scaled, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            keep_sorted = (cum - probs) < p_eff
            # Smallest KEPT logit value = the nucleus threshold per row.
            kept_min = jnp.min(
                jnp.where(keep_sorted, sorted_scaled, jnp.inf), axis=-1,
                keepdims=True,
            )
            scaled = jnp.where(scaled < kept_min, -jnp.inf, scaled)
            base = jax.random.PRNGKey(self.base_seed)

            def one(seed, idx, row):
                key = jax.random.fold_in(jax.random.fold_in(base, seed), idx)
                return jax.random.categorical(key, row)

            return jax.vmap(one)(sd, ti, scaled).astype(jnp.int32)

        sampled = jax.lax.cond(
            jnp.any(temps > 0.0),
            draw,
            lambda args: greedy,
            (logits, temps, topk, topp, seeds, tok_idx),
        )
        return jnp.where(temps > 0.0, sampled, greedy)

    def _prefill_impl(self, params, tokmask, cache, meta_i, meta_f,
                      bias_ids, bias_vals):
        """``nB`` prompts → cache rows at ``slots`` + first sampled tokens.

        Inputs arrive PACKED by dtype — ``tokmask`` [2, nB, T] stacks
        tokens + attention mask, ``meta_i`` [4, nB] stacks
        slots/top_k/seeds/tok_idx, ``meta_f`` [2, nB] stacks
        temperature/top_p — so an admission group costs 5 host→device
        transfers instead of 10; unpacking inside the program is free.
        One compiled program per (prompt bucket, group size) serves every
        slot combination (dynamic start indices, static shapes). Batching
        admissions into one program means ONE host round-trip per
        admission group instead of per request — on hosts where dispatch
        latency dominates (e.g. a tunneled chip) this is the TTFT lever.
        """
        tokens, attn_mask = tokmask[0], tokmask[1]
        slots, topk, seeds, tok_idx = (
            meta_i[0], meta_i[1], meta_i[2], meta_i[3]
        )
        temps, topp = meta_f[0], meta_f[1]
        params = self._mp(params)
        nB = tokens.shape[0]
        row_cache = self.model.make_cache(nB, self.max_len)
        last_logits, rows = self.model.prefill(
            params, tokens, attn_mask, row_cache
        )
        cache = copy_rows_into(cache, rows, slots)
        first = self._sample_tokens(
            last_logits, temps, topk, seeds, tok_idx, bias_ids, bias_vals,
            topp,
        )  # [nB]
        return first, cache

    def _prefill_paged_impl(self, params, tokmask, cache, meta_i, meta_f,
                            bias_ids, bias_vals, write_pids):
        """Paged mirror of :meth:`_prefill_impl`: the prompt runs on a
        private row cache exactly as on the slab path (prefill math is
        untouched), then the row is cut into pages and scattered at the
        physical pages ``write_pids`` names — sentinel entries (shared
        CoW pages, unallocated tail) drop. Same packed-transfer layout,
        same sampling."""
        tokens, attn_mask = tokmask[0], tokmask[1]
        slots, topk, seeds, tok_idx = (
            meta_i[0], meta_i[1], meta_i[2], meta_i[3]
        )
        temps, topp = meta_f[0], meta_f[1]
        params = self._mp(params)
        nB = tokens.shape[0]
        row_cache = self.model.make_cache(nB, self._paged_capacity)
        last_logits, rows = self.model.prefill(
            params, tokens, attn_mask, row_cache
        )
        cache = copy_rows_into_paged(cache, rows, slots, write_pids)
        first = self._sample_tokens(
            last_logits, temps, topk, seeds, tok_idx, bias_ids, bias_vals,
            topp,
        )
        return first, cache

    def _chunk_group_paged_impl(self, params, tokmask, cache, tables,
                                meta_i, meta_f, bias_ids, bias_vals):
        """One chunk program for a GROUP of chunk trains, pages-direct
        (ISSUE 15 tentpole): each row is one train's next ``<=W``-token
        chunk, scattered straight through its own page-table row
        (``tables`` [g, NP] — CoW-borrowed head pages sit below the
        row's ``start`` and are never written; the unallocated tail is
        sentinel-steered and drops, like the spec verify scatter), with
        the staircase read bounded by the row's own start. The cache
        argument is DONATED across chunks — XLA updates the pool in
        place, no row cache, no commit copy.

        First-token fusion: ``_sample_tokens`` runs in-program on every
        row's take-row logits, so a FINAL chunk's admission ends at a
        ``[g]`` ids fetch — never a logits round-trip. Final rows also
        scatter their verified prompt length into ``cache.lengths``;
        non-final rows are steered to the sentinel slot (``mode="drop"``
        voids both). ``meta_i`` [6, g] packs slot-or-sentinel / start /
        take_idx / top_k / seed / new_len; ``meta_f`` [2, g] packs
        temperature / top_p — the admission-group packed-transfer
        convention."""
        tokens, attn_mask = tokmask[0], tokmask[1]
        slots, starts, take_idx, topk, seeds, new_len = (
            meta_i[0], meta_i[1], meta_i[2], meta_i[3], meta_i[4],
            meta_i[5],
        )
        temps, topp = meta_f[0], meta_f[1]
        params = self._mp(params)
        taken, pools = self.model.prefill_chunk_paged(
            params, tokens, attn_mask, cache, tables, starts, take_idx
        )
        lengths = cache.lengths.at[slots].set(new_len, mode="drop")
        cache = cache.replace(
            k=pools.k, v=pools.v, lengths=lengths,
            k_scale=pools.k_scale, v_scale=pools.v_scale,
        )
        first = self._sample_tokens(
            taken, temps, topk, seeds, jnp.zeros_like(slots), bias_ids,
            bias_vals, topp,
        )
        return first, cache

    def _decode_impl(self, params, cache, step_state, horizon: int,
                     samp_f, samp_i, bias_ids, bias_vals, counts):
        """``horizon`` chained decode steps in one program (one host sync).

        The per-DISPATCH state arrives as ONE packed [3, B] int32 upload —
        rows = pending tokens / active mask / next sample index — instead
        of three separate transfers; per-slot sampling state arrives
        packed by dtype — ``samp_f`` [4, B] stacks
        temperature/top_p/presence/frequency, ``samp_i`` [2, B] stacks
        top_k/seeds — so a sampling-state refresh costs two transfers
        instead of eight (tunnel RTTs are the unit of cost).

        Rows already at capacity produce garbage logits (decode_step masks
        their scatter); fold the in-bounds check into the mask so their
        "sampled" token is never surfaced, and return the per-substep
        effective masks so the host knows which slots actually advanced.

        Everything the host needs comes back PACKED in one int32 array
        [2h+1, B] (h token rows, h advanced rows, 1 lengths row) so the
        device→host boundary is crossed once per dispatch, not three times.
        """
        if self.paged and self.mesh is not None:
            # TP paged decode: bake the slice into the trace so the
            # Pallas paged kernel runs per-shard under shard_map (GSPMD
            # cannot partition a pallas_call). Entered inside the traced
            # function, the sequence_parallel contract.
            from ray_dynamic_batching_tpu.ops.attention import (
                tensor_parallel,
            )

            with tensor_parallel(self.mesh):
                return self._decode_body(params, cache, step_state,
                                         horizon, samp_f, samp_i,
                                         bias_ids, bias_vals, counts)
        return self._decode_body(params, cache, step_state, horizon,
                                 samp_f, samp_i, bias_ids, bias_vals,
                                 counts)

    def _decode_body(self, params, cache, step_state, horizon: int,
                     samp_f, samp_i, bias_ids, bias_vals, counts):
        tokens = step_state[0][:, None]
        active = step_state[1].astype(bool)
        tok_idx0 = step_state[2]
        # Mask sampling state to the ACTIVE rows in-program: freed slots
        # keep stale device values (completions no longer re-upload), and
        # a stale temperature>0 would otherwise hold _sample_tokens'
        # runtime all-greedy lax.cond on the expensive branch for a whole
        # traffic lull's worth of greedy-only dispatches.
        temps = jnp.where(active, samp_f[0], 0.0)
        topp, pres, freq = samp_f[1], samp_f[2], samp_f[3]
        topk, seeds = samp_i[0], samp_i[1]
        rows = jnp.arange(tokens.shape[0])

        def substep(carry, j):
            cache, tokens, counts = carry
            # Paged pools round capacity up to whole pages; the engine's
            # max_len stays the generation bound so paged and slab runs
            # block (and capacity-finish) at the SAME length — the
            # token-exactness contract. For slab caches the two bounds
            # coincide (make_cache allocates exactly max_len).
            limit = self.max_len if self.paged else cache.capacity
            advanced = jnp.logical_and(active, cache.lengths < limit)
            # Dequantize INSIDE the scan body: hoisted outside, the bf16
            # tree becomes a loop-invariant XLA materializes once and
            # re-streams every substep — the exact bandwidth the int8
            # residency is supposed to save. In-body, the compiler may
            # fuse each convert+scale into its consuming matmul.
            step_fn = (self.model.decode_step_paged if self.paged
                       else self.model.decode_step)
            logits, cache = step_fn(
                self._mp(params), tokens, cache, advanced
            )
            # Repetition control: subtract presence (any prior emission)
            # and frequency (per emission) penalties over the slot's
            # generated-token counts. All-zero penalties make this an
            # exact no-op on the hot path.
            logits = logits.astype(jnp.float32) - (
                pres[:, None] * (counts > 0)
                + freq[:, None] * counts.astype(jnp.float32)
            )
            nxt = self._sample_tokens(logits, temps, topk, seeds,
                                      tok_idx0 + j, bias_ids, bias_vals,
                                      topp)
            nxt = jnp.where(advanced, nxt, tokens[:, 0])
            counts = counts.at[rows, nxt].add(advanced.astype(jnp.int32))
            return (cache, nxt[:, None], counts), (nxt, advanced)

        (cache, _, counts), (toks, adv) = jax.lax.scan(
            substep, (cache, tokens, counts),
            jnp.arange(horizon, dtype=jnp.int32),
        )
        packed = jnp.concatenate(
            [toks, adv.astype(jnp.int32), cache.lengths[None, :]], axis=0
        )
        return packed, cache, counts

    def _spec_impl(self, params, cache, dcache, step_state,
                   bias_ids, bias_vals):
        """One speculative round for the whole batch, greedy-exact.
        ``step_state`` [2, B] int32 packs pending tokens + active mask
        into the round's single per-dispatch upload.

        Draft scans ``k+1`` single-token steps (proposing d_1..d_k and
        keeping its own cache complete through d_k), the target scores the
        [t0, d_1..d_k] window in one ``verify_step`` forward, and each row
        accepts its longest matching draft prefix plus the target's own
        next token — between 1 and k+1 tokens per round, never diverging
        from what plain greedy decode would emit.

        Returns ``(packed [k+3, B] int32, cache, dcache)``: k+1 output-token
        rows, an n_out row, and a post-round lengths row — one host fetch.
        """
        params = self._mp(params)
        tokens = step_state[0][:, None]
        active = step_state[1].astype(bool)
        k = self.spec_tokens
        B = tokens.shape[0]
        S = self.max_len  # shared-cache capacity

        def dstep(carry, _):
            dc, tok = carry
            logits, dc = self.draft_model.decode_step(
                self.draft_params, tok, dc, active
            )
            nxt = jnp.argmax(
                logits.astype(jnp.float32), axis=-1
            ).astype(jnp.int32)
            nxt = jnp.where(active, nxt, tok[:, 0])
            return (dc, nxt[:, None]), nxt

        dlen0 = dcache.lengths
        (dcache, _), drafts = jax.lax.scan(
            dstep, (dcache, tokens), None, length=k + 1
        )  # drafts [k+1, B]; the final proposal is drafted only to keep
        # the draft cache complete — it is never verified.
        d = drafts[:k].T  # [B, k]
        window = jnp.concatenate([tokens, d], axis=1)  # [B, k+1]
        # Paged engines verify through the page-table scatter + the
        # staircase paged read (scratch pages pre-arranged host-side by
        # _reserve_spec_scratch); the slab path is unchanged. Same
        # window, same greedy rule — ONE accept computation below serves
        # both, which is what keeps paged+spec and slab+spec
        # byte-identical.
        verify = (self.model.verify_step_paged if self.paged
                  else self.model.verify_step)
        logits, cache = verify(params, window, cache, active)
        logits = logits.astype(jnp.float32)
        # Same per-request bias as the plain path (ONE rule — _apply_bias —
        # broadcast over the window) so biased greedy stays
        # speculative-exact.
        dense_bias = self._apply_bias(
            jnp.zeros((B, logits.shape[-1]), jnp.float32),
            bias_ids, bias_vals,
        )
        logits = logits + dense_bias[:, None, :]
        greedy = jnp.argmax(
            logits, axis=-1
        ).astype(jnp.int32)  # [B, k+1]; greedy[:, j] follows window[:, j]
        match = (d == greedy[:, :k]).astype(jnp.int32)
        m = jnp.sum(jnp.cumprod(match, axis=1), axis=1)  # accepted drafts
        n_out = m + 1
        # Capacity clamp: only tokens whose k/v actually landed may count.
        remaining = jnp.maximum(S - cache.lengths, 0)
        n_out = jnp.where(active, jnp.minimum(n_out, remaining), 0)
        j_idx = jnp.arange(k + 1)[None, :]
        gm = jnp.take_along_axis(greedy, m[:, None], axis=1)  # [B, 1]
        d_pad = jnp.concatenate(
            [d, jnp.zeros((B, 1), jnp.int32)], axis=1
        )
        out = jnp.where(j_idx < m[:, None], d_pad, gm)  # [B, k+1]
        adv = n_out.astype(jnp.int32)
        cache = cache.replace(lengths=cache.lengths + adv)
        # Draft cache tracked the SAME sequence: roll its lengths back to
        # the verified prefix (its k/v for t0..d_k stay resident; garbage
        # past the new length is overwritten before it is ever attended).
        dcache = dcache.replace(lengths=dlen0 + adv)
        packed = jnp.concatenate(
            [out.T, n_out[None, :], cache.lengths[None, :]], axis=0
        )
        return packed, cache, dcache

    def _draft_catchup_impl(self, dparams, dcache, window, active, counts):
        """Write the draft k/v for tokens the TARGET just decoded plainly
        (window [B, h] at each row's own draft length) and advance draft
        lengths by the per-row advanced count — the draft stays in lockstep
        with the sequence without influencing it."""
        _, dcache = self.draft_model.verify_step(
            dparams, window, dcache, active
        )
        return dcache.replace(
            lengths=dcache.lengths + jnp.where(active, counts, 0)
        )

    def _draft_prefill_impl(self, dparams, tokmask, dcache, meta_i):
        """Mirror of ``_prefill_impl`` for the draft model: fill the draft
        cache's rows for newly admitted prompts (no sampling — the draft
        only ever proposes from its cache). Takes the target prefill's
        packed device buffers verbatim — zero extra transfers."""
        tokens, attn_mask, slots = tokmask[0], tokmask[1], meta_i[0]
        nB = tokens.shape[0]
        row_cache = self.draft_model.make_cache(nB, dcache.capacity)
        _, rows = self.draft_model.prefill(dparams, tokens, attn_mask,
                                           row_cache)
        return copy_rows_into(dcache, rows, slots)

    def _draft_prefill_fn(self, bucket: int, group: int) -> Callable:
        fn = self._prefill_fns.get(("draft", bucket, group))
        if fn is None:
            # Donate the draft cache (arg 2 in the packed signature).
            fn = instrument("draft_prefill", jax.jit(
                self._draft_prefill_impl, donate_argnums=(2,)
            ))
            self._prefill_fns[("draft", bucket, group)] = fn
        return fn

    def _admit_group_sizes(self) -> List[int]:
        """Compiled prefill/chunk group widths: powers of two up to
        ``max_admissions_per_step``, plus the cap itself when it isn't
        one. The cap is a GROUP-WIDTH clamp on both arms — the legacy
        mono arm's ``_admit`` batches that many full-prompt prefills,
        and the chunked arm's ``_pump_prefill`` batches up to that many
        same-width single-chunk trains per dispatch (its PACING is the
        token budget, not this count). Either way, every group width
        the engine can dispatch must round up to a width warmup
        compiled, or a burst pays a 20-40s XLA compile mid-serving —
        the warmup-coverage contract (``ops/jit_model.py``)."""
        sizes, s = [], 1
        while s <= self.max_admissions_per_step:
            sizes.append(s)
            s *= 2
        if sizes[-1] != self.max_admissions_per_step:
            sizes.append(self.max_admissions_per_step)
        return sizes

    def _prefill_fn(self, bucket: int, group: int) -> Callable:
        fn = self._prefill_fns.get((bucket, group))
        if fn is None:
            # Donate the big cache (arg 2) — updated in place in HBM.
            name = ("prefill_group_paged" if self.paged
                    else "prefill_group")
            fn = instrument(name, jax.jit(
                self._prefill_paged_impl if self.paged
                else self._prefill_impl,
                donate_argnums=(2,),
            ))
            self._prefill_fns[(bucket, group)] = fn
        return fn

    def warmup(self) -> None:
        """Compile every hot-path program before serving: the arm's
        admission programs (chunked-paged: the chunk program over every
        (bucket x group) shape; slab-chunked: the long chunk + fused
        commit pair; mono: the (bucket x group) prefill grid) plus the
        decode horizons {1, ttft, decode} and the spec/draft programs
        when a draft rides along.

        Contract-bearing (ISSUE 20): the whole run is bracketed by the
        compile ledger's warmup phase — ``end_warmup`` arms the
        steady-state mark, after which ANY compile is a recorded
        violation — and the ledger's warmup counts are cross-checked
        against ``ops/jit_model.required_for``: a registered program
        this arm needs that warmup did not compile raises HERE, at
        startup, instead of stalling a request 20-40s mid-serving."""
        ledger = get_ledger()
        before = ledger.counts(phase=PHASE_WARMUP)
        ledger.begin_warmup()
        try:
            with self._device_ctx():
                self._warmup_impl()
        finally:
            ledger.end_warmup()
        after = ledger.counts(phase=PHASE_WARMUP)
        if after == before:
            # Zero new compiles: every program was already cached (this
            # engine was warmed before) — nothing to cross-check.
            return
        required = jit_model.required_for(
            self.chunked_prefill, self.paged, self.draft_model is not None
        )
        gaps = [
            p.name for p in required
            if after.get(p.name, 0) <= before.get(p.name, 0)
        ]
        if gaps:
            raise RuntimeError(
                f"warmup coverage gap: registered hot-path program(s) "
                f"{gaps} compiled nothing during warmup — the warmup "
                "routine and ops/jit_model.required_for disagree; fix "
                "whichever is wrong before this engine serves"
            )

    def _warmup_impl(self) -> None:
        if self.chunked_prefill and self.paged:
            # Chunked-universal admission: warm the pages-direct chunk
            # program at every (bucket, group) shape the pump can
            # produce, plus the (1, C_max) long-train shape (covered by
            # group size 1 at the largest bucket). All-sentinel tables:
            # every page write drops, the lengths scatter steers to the
            # sentinel slot — the full program compiles without touching
            # a real page.
            for b in self.prompt_buckets:
                for g in self._admit_group_sizes():
                    first, self._cache = self._chunk_paged_fn(
                        self.params,
                        jnp.stack([
                            jnp.zeros((g, b), jnp.int32),
                            jnp.ones((g, b), jnp.int32),
                        ]),
                        self._cache,
                        jnp.full((g, self._n_table_entries),
                                 self.num_pages, jnp.int32),
                        jnp.stack([
                            jnp.full((g,), self.num_slots, jnp.int32),
                            jnp.zeros((g,), jnp.int32),
                            jnp.zeros((g,), jnp.int32),
                            jnp.zeros((g,), jnp.int32),
                            jnp.zeros((g,), jnp.int32),
                            jnp.zeros((g,), jnp.int32),
                        ]),
                        jnp.stack([
                            jnp.zeros((g,), jnp.float32),
                            jnp.ones((g,), jnp.float32),
                        ]),
                        jnp.zeros((g, self.max_bias_entries), jnp.int32),
                        jnp.zeros((g, self.max_bias_entries),
                                  jnp.float32),
                    )
                    first.block_until_ready()
        elif self.chunked_prefill:
            # Slab chunked trains ride the row-cache chunk + fused
            # commit programs — warm THOSE, not the monolithic groups
            # this engine never dispatches (a cold chunk program is a
            # 20-40s XLA compile on the first real request, exactly
            # what warmup exists to prevent).
            C = self.prompt_buckets[-1]
            chunk_fn, commit_fn, _seed, _ex = self._long_prefill_fns(C)
            row = self.model.make_cache(1, self._long_row_cap(C))
            last, row = chunk_fn(
                self.params,
                jnp.zeros((1, C), jnp.int32),
                jnp.ones((1, C), jnp.int32),
                row, jnp.int32(0), jnp.int32(0),
            )
            first, self._cache = commit_fn(
                self._cache, row,
                jnp.zeros((3,), jnp.int32),
                last,
                jnp.asarray([0.0, 1.0], jnp.float32),
                jnp.zeros((1, self.max_bias_entries), jnp.int32),
                jnp.zeros((1, self.max_bias_entries), jnp.float32),
            )
            first.block_until_ready()
        else:
            self._warmup_prefill_groups()
        self._warmup_decode()

    def _warmup_prefill_groups(self) -> None:
        for b in self.prompt_buckets:
            for g in self._admit_group_sizes():
                tokmask = jnp.stack([
                    jnp.zeros((g, b), dtype=jnp.int32),
                    jnp.ones((g, b), dtype=jnp.int32),
                ])
                meta_i = jnp.stack([
                    jnp.arange(g, dtype=jnp.int32) % self.num_slots,
                    jnp.zeros((g,), jnp.int32),
                    jnp.zeros((g,), jnp.int32),
                    jnp.zeros((g,), jnp.int32),
                ])
                meta_f = jnp.stack([
                    jnp.zeros((g,), jnp.float32),
                    jnp.ones((g,), jnp.float32),
                ])
                extra = ()
                if self.paged:
                    # All-sentinel write pids: every page write drops, so
                    # warmup compiles the full scatter without touching a
                    # single real page (the table is still all-sentinel).
                    extra = (jnp.full(
                        (g, self._n_table_entries), self.num_pages,
                        jnp.int32,
                    ),)
                first, self._cache = self._prefill_fn(b, g)(
                    self.params, tokmask, self._cache, meta_i, meta_f,
                    jnp.zeros((g, self.max_bias_entries), jnp.int32),
                    jnp.zeros((g, self.max_bias_entries), jnp.float32),
                    *extra,
                )
                first.block_until_ready()

    def _warmup_decode(self) -> None:
        B = self.num_slots
        warm_samp_f = jnp.stack([
            jnp.zeros((B,), jnp.float32),
            jnp.ones((B,), jnp.float32),
            jnp.zeros((B,), jnp.float32),
            jnp.zeros((B,), jnp.float32),
        ])
        warm_samp_i = jnp.zeros((2, B), jnp.int32)
        for h in {1, self.ttft_horizon, self.decode_horizon}:
            packed, self._cache, self._counts = self._decode_fn(
                self.params,
                self._cache,
                jnp.zeros((3, B), dtype=jnp.int32),
                h,
                warm_samp_f,
                warm_samp_i,
                jnp.zeros((B, self.max_bias_entries), jnp.int32),
                jnp.zeros((B, self.max_bias_entries), jnp.float32),
                self._counts,
            )
            packed.block_until_ready()
        if self._dcache is not None:
            if not self.chunked_prefill:
                # Draft group-prefill programs serve the MONO admission
                # path only; chunked engines replay prompts through the
                # lazily-compiled draft chunk program instead.
                for b in self.prompt_buckets:
                    for g in self._admit_group_sizes():
                        self._dcache = self._draft_prefill_fn(b, g)(
                            self.draft_params,
                            jnp.stack([
                                jnp.zeros((g, b), dtype=jnp.int32),
                                jnp.ones((g, b), dtype=jnp.int32),
                            ]),
                            self._dcache,
                            jnp.stack([
                                jnp.arange(g, dtype=jnp.int32)
                                % self.num_slots,
                                jnp.zeros((g,), jnp.int32),
                                jnp.zeros((g,), jnp.int32),
                                jnp.zeros((g,), jnp.int32),
                            ]),
                        )
            packed, self._cache, self._dcache = self._spec_fn(
                self.params,
                self._cache,
                self._dcache,
                jnp.zeros((2, self.num_slots), dtype=jnp.int32),
                jnp.zeros((self.num_slots, self.max_bias_entries), jnp.int32),
                jnp.zeros((self.num_slots, self.max_bias_entries), jnp.float32),
            )
            packed.block_until_ready()
            # The catch-up runs after every PLAIN step of a spec engine —
            # one window shape per horizon; compile them now, not at the
            # first sampled request mid-serving.
            for h in {1, self.ttft_horizon, self.decode_horizon}:
                self._dcache = self._draft_catchup_fn(
                    self.draft_params,
                    self._dcache,
                    jnp.zeros((self.num_slots, h), dtype=jnp.int32),
                    jnp.zeros((self.num_slots,), dtype=bool),
                    jnp.zeros((self.num_slots,), dtype=jnp.int32),
                )
            self._dcache = self._dcache.replace(
                lengths=jnp.zeros((self.num_slots,), dtype=jnp.int32)
            )
        self._counts = self._zero_counts_fn(
            self._counts, jnp.int32(0), jnp.int32(0)
        )
        # Reset state dirtied by warmup runs.
        self._cache = self._cache.replace(
            lengths=jnp.zeros((self.num_slots,), dtype=jnp.int32)
        )
        n_warm = len(self._prefill_fns)
        if self.chunked_prefill and self.paged:
            # Chunk shapes live in ONE retracing jit, not _prefill_fns.
            n_warm = len(self.prompt_buckets) * len(
                self._admit_group_sizes()
            )
        logger.info(
            "%s: warmed %d %s programs + decode horizons {1, %d, %d}",
            self.model.name, n_warm,
            "chunk" if self.chunked_prefill and self.paged else "prefill",
            self.ttft_horizon, self.decode_horizon,
        )

    # --- admission ---------------------------------------------------------
    def _free_slots(self) -> List[int]:
        return [
            i for i, s in enumerate(self._slots)
            if s.free and i not in self._train_slots
        ]

    def _prep_prompt(self, req: Request) -> Tuple[np.ndarray, int, Dict]:
        """Validate one request BEFORE it costs a dispatch; returns
        (prompt ids, bucket, opts) where opts carries max_new / temperature
        / top_k / seed — or raises. Every way a payload can be malformed
        must surface here: past this point the request is committed to a
        slot and only engine errors can reject it."""
        try:
            prompt = np.asarray(
                req.payload["tokens"] if isinstance(req.payload, dict)
                else req.payload,
                dtype=np.int32,
            ).reshape(-1)
        except (TypeError, ValueError, KeyError) as e:
            raise BadRequest(f"{req.request_id}: malformed tokens: {e}")
        if prompt.size == 0:
            raise BadRequest(f"{req.request_id}: empty prompt")
        bucket = bucket_up(int(prompt.size), self.prompt_buckets)
        if bucket is None:
            # Longer than every bucket: admit via CHUNKED prefill (bucket
            # sentinel -1) as long as the cache can hold the prompt plus at
            # least one generated token.
            if prompt.size >= self.max_len:
                raise BadRequest(
                    f"{req.request_id}: prompt length {prompt.size} "
                    f"exceeds KV capacity {self.max_len}"
                )
            bucket = -1
        opts = {
            "_cache_len": int(prompt.size),  # post-commit cache lengths
            "max_new": self.default_max_new_tokens,
            "temperature": 0.0,   # greedy unless asked
            "top_k": 0,
            # Default seed derives from the request id via a STABLE hash
            # (crc32; Python's hash() is salted per process), so a
            # re-submitted request resamples the same way on any replica.
            "seed": zlib.crc32(req.request_id.encode()) & 0x7FFFFFFF,
            "stop": (),           # extra per-request stop token ids
            "session_id": None,   # multi-turn KV continuation key
            "logit_bias": {},     # token id -> additive logit bias
            "presence_penalty": 0.0,   # subtract once per distinct token
            "frequency_penalty": 0.0,  # subtract per emission
            "top_p": 1.0,              # nucleus sampling (1.0 = off)
        }
        if isinstance(req.payload, dict):
            p = req.payload
            try:
                # Coercion failures on client-supplied fields are the
                # CLIENT's fault (TypeError folds in: int(None) etc.) —
                # they must classify as BadRequest, not server errors.
                opts["max_new"] = int(
                    p.get("max_new_tokens", opts["max_new"])
                )
                opts["temperature"] = float(p.get("temperature", 0.0))
                opts["top_k"] = int(p.get("top_k", 0))
                opts["top_p"] = float(p.get("top_p", 1.0))
                opts["presence_penalty"] = float(
                    p.get("presence_penalty", 0.0)
                )
                opts["frequency_penalty"] = float(
                    p.get("frequency_penalty", 0.0)
                )
                if not (math.isfinite(opts["presence_penalty"])
                        and math.isfinite(opts["frequency_penalty"])):
                    # json.loads accepts Infinity/NaN; inf * 0 = NaN would
                    # silently poison the row's logits.
                    raise BadRequest(
                        f"{req.request_id}: penalties must be finite"
                    )
                if ((opts["presence_penalty"] or opts["frequency_penalty"])
                        and self._counts.shape[1] <= 1):
                    raise BadRequest(
                        f"{req.request_id}: penalties unsupported — model "
                        "exposes no vocab_size for token counting"
                    )
                if "seed" in p:
                    opts["seed"] = int(p["seed"]) & 0x7FFFFFFF
                opts["stop"] = frozenset(
                    int(t) for t in p.get("stop_token_ids", ())
                )
                if p.get("session_id") is not None:
                    opts["session_id"] = str(p["session_id"])
                    opts["_prompt_tokens"] = prompt
                bias = {
                    int(t): float(v)
                    for t, v in dict(p.get("logit_bias", {})).items()
                }
                for t in p.get("banned_tokens", ()):
                    bias[int(t)] = -1e9  # a ban = very negative bias
            except (TypeError, ValueError) as e:
                raise BadRequest(
                    f"{req.request_id}: malformed field: {e}"
                )
            if len(bias) > self.max_bias_entries:
                raise BadRequest(
                    f"{req.request_id}: {len(bias)} logit-bias entries "
                    f"exceed the limit of {self.max_bias_entries}"
                )
            V = getattr(self.model.cfg, "vocab_size", None)
            if V is not None and any(not 0 <= t < V for t in bias):
                raise BadRequest(
                    f"{req.request_id}: logit-bias token id out of vocab"
                )
            opts["logit_bias"] = bias
            if not 0.0 <= opts["top_p"] <= 1.0:
                raise BadRequest(
                    f"{req.request_id}: top_p must be in [0, 1]"
                )
            if opts["top_p"] == 0.0:
                # OpenAI's wire shape allows 0 (near-deterministic): the
                # smallest non-empty nucleus is the argmax alone.
                opts["top_p"] = 1e-9
            if opts["temperature"] < 0.0:
                raise BadRequest(
                    f"{req.request_id}: temperature must be >= 0"
                )
        return prompt, bucket, opts

    def _bias_arrays(self, opts: Dict):
        """opts -> fixed-width (ids [K], vals [K]) padded with no-op
        (0, 0.0) entries."""
        K = self.max_bias_entries
        ids = np.zeros((K,), dtype=np.int32)
        vals = np.zeros((K,), dtype=np.float32)
        for j, (t, v) in enumerate(opts.get("logit_bias", {}).items()):
            ids[j] = t
            vals[j] = v
        return ids, vals

    def _admit(self) -> int:
        """Fill free slots from the queue (continuous batching join).
        Chunked engines admit into chunk TRAINS — their prefill work is
        paced by ``prefill_token_budget`` in ``_pump_prefill``, so
        admission itself takes every free slot. The legacy monolithic
        arm rations by COUNT instead (at most
        ``max_admissions_per_step`` full-prompt prefills between decode
        steps) so prefills interleave with decode turns.

        Same-bucket prompts prefill as ONE batched program call (group
        padded to the next compiled power-of-two width by duplicating row 0
        — the duplicate writes identical data to the same slot, which is
        idempotent), so a burst of admissions costs one dispatch per bucket
        rather than one per request.

        The mono count cap only applies while slots are actively decoding
        (it exists to protect THEIR latency); an idle engine ramps by
        filling every free slot at once — there is nothing to stall."""
        free = self._free_slots()
        if not free:
            return 0
        if self._active_mask.any() and not self.chunked_prefill:
            # Legacy monolithic rationing: the admission COUNT bounds the
            # stall. Chunked engines admit into trains instead — the
            # token budget, not this cap, paces their prefill work.
            free = free[: self.max_admissions_per_step]
        batch = self.queue.get_batch(len(free), discard_stale=True)
        # Mid-admission visibility: these requests are in NEITHER the
        # queue nor a slot until their prefill registers (seconds for a
        # cold/large program) — drain/idle checks that only look at
        # queue depth + active slots would see "idle" in that window and
        # a shutdown would abort a request that was seconds from its
        # first token (observed: the colocation demo deterministically
        # dropped its final tail request this way).
        self._admitting = len(batch)
        # The batch itself stays reachable while mid-admission: a chip
        # quarantine must be able to reject these futures — they are in
        # neither the queue nor a slot, and a wedged prefill dispatch
        # would otherwise strand them forever.
        self._admitting_batch = batch
        try:
            if self.chunked_prefill:
                return self._admit_chunked(batch, free)
            return self._admit_batch(batch, free)
        finally:
            self._admitting = 0
            self._admitting_batch = []

    def _admit_batch(self, batch: List[Request],
                     free: List[int]) -> int:
        t_dequeue = now_ms()
        for req in batch:
            # Dequeue stamp for the TTFT decomposition; a slot-starved
            # requeue gets re-stamped on its next (sticking) dequeue.
            req.admit_ms = t_dequeue
        by_bucket: Dict[int, List[Tuple[Request, np.ndarray, Dict]]] = {}
        session_items: List[Tuple[Request, np.ndarray, Dict, Tuple]] = []
        sessions = (self.paged_sessions if self.paged
                    else self.session_cache)
        for req in batch:
            try:
                prompt, bucket, opts = self._prep_prompt(req)
            except Exception as e:  # noqa: BLE001 — bad prompt must not kill loop
                req.reject(e)
                continue
            hit = None
            if sessions is not None and opts["session_id"]:
                hit = sessions.lookup(opts["session_id"], prompt)
                if hit is not None and self.paged:
                    # Seed-read hold, taken AT LOOKUP: a long fill
                    # admitted earlier in this same round interleaves
                    # decode steps, whose finishes can store new session
                    # turns and EVICT this entry — without the hold its
                    # pages could be freed and rewritten before the seed
                    # gather reads them. The hold also lets the
                    # reservation below cover only the NON-shared tail.
                    self._allocator.incref(hit[0])
                    opts["_session_hold"] = list(hit[0])
                    opts["_session_share"] = hit[1] // self.page_size
                if hit is None:
                    # Misses can be requeued (a missed LONG prompt):
                    # mark now, count once at _register.
                    opts["_session_miss"] = True
            if self.paged and not self._alloc_admission_pages(
                    req, prompt, opts):
                continue  # page-starved: requeued (or shed) inside
            if hit is not None:
                # Counted at admission (_prefill_session), not here: a
                # slot-starved requeue would re-look-up and double-count.
                session_items.append((req, prompt, opts, hit))
                continue
            by_bucket.setdefault(bucket, []).append((req, prompt, opts))
        admitted = 0
        cap = self.max_admissions_per_step
        long_items = by_bucket.pop(-1, [])
        for bucket, items in by_bucket.items():
            for off in range(0, len(items), cap):  # chunks round up to a
                chunk = items[off : off + cap]     # compiled group width
                slots = free[admitted : admitted + len(chunk)]
                try:
                    self._prefill_group(bucket, chunk, slots)
                except Exception as e:  # noqa: BLE001 — dequeued requests
                    # must never dangle: a failed group rejects its members
                    logger.exception(
                        "%s: prefill group failed", self.model.name
                    )
                    for req, _p, opts in chunk:
                        self._release_pages(opts)
                        req.reject(e)
                    continue
                admitted += len(chunk)
        session_fill = (self._prefill_session_paged if self.paged
                        else self._prefill_session)
        singles = [
            (self._prefill_long, (req, prompt, opts))
            for req, prompt, opts in long_items
        ] + [
            (session_fill, (req, prompt, opts, hit))
            for req, prompt, opts, hit in session_items
        ]
        for fill, args in singles:
            req = args[0]
            if admitted >= len(free):
                # Ran out of slots this round — requeue untouched. A full
                # or closed queue refuses WITHOUT rejecting (router-retry
                # semantics), but here the engine holds the only reference:
                # an unchecked drop would leave the future hanging forever.
                self._release_pages(args[2])  # re-allocated on re-admission
                if not self.queue.add_request(req, reject_on_full=False,
                                              requeue=True):
                    self.queue.count_external_drop(
                        req, reason="requeue_refused"
                    )
                    req.reject(RequestDropped(
                        f"{req.request_id}: queue refused requeue during "
                        "chunked admission"
                    ))
                continue
            try:
                fill(*args, free[admitted])
            except Exception as e:  # noqa: BLE001 — same no-dangle rule
                logger.exception(
                    "%s: chunked prefill failed", self.model.name
                )
                self._release_pages(args[2])
                req.reject(e)
                continue
            admitted += 1
        return admitted

    # --- token-budget chunked admission (ISSUE 15 tentpole) ----------------
    def _admit_chunked(self, batch: List[Request],
                       free: List[int]) -> int:
        """Universal chunked admission: every dequeued request becomes a
        :class:`_ChunkTrain` holding a slot; NO prefill dispatches here —
        the token-budget scheduler (:meth:`_pump_prefill`) advances
        trains between decode turns, so dequeue latency is microseconds
        and the stall bound is owned by one place."""
        t_dequeue = now_ms()
        started = 0
        for req in batch:
            req.admit_ms = t_dequeue
            try:
                prompt, bucket, opts = self._prep_prompt(req)
            except Exception as e:  # noqa: BLE001 — bad prompt must not kill loop
                req.reject(e)
                continue
            slot_idx = free[started]  # len(batch) <= len(free) by dequeue
            try:
                self._start_train(req, prompt, bucket, opts, slot_idx)
            except Exception as e:  # noqa: BLE001 — no-dangle rule
                logger.exception(
                    "%s: train admission failed", self.model.name
                )
                self._release_pages(opts)
                req.reject(e)
                continue
            started += 1
        return started

    def _start_train(self, req: Request, prompt: np.ndarray, bucket: int,
                     opts: Dict, slot_idx: int) -> None:
        """Create the chunk train for one admission: resolve prefix /
        session reuse (paged: CoW page borrows with the base floored to
        a page boundary — the partial boundary page belongs to its owner
        and its positions are in the prompt, so the train recomputes
        them into its own pages; slab: row-cache seeding exactly like
        the legacy long path) and park the train for the budget pump.
        Fresh bucketed prompts keep their bucket as the chunk width so
        same-bucket trains group into one program; long prompts and
        seeded continuations chunk at the largest bucket."""
        C_max = self.prompt_buckets[-1]
        total = int(prompt.size)
        base = 0
        row = None
        insert_prefix = False
        W = bucket if bucket > 0 else C_max
        sessions = (self.paged_sessions if self.paged
                    else self.session_cache)
        hit = None
        if sessions is not None and opts["session_id"]:
            hit = sessions.lookup(opts["session_id"], prompt)
            if hit is None:
                opts["_session_miss"] = True
        if self.paged:
            opts.setdefault("_pages", [])
            opts["_shared_pages"] = 0
            if hit is not None:
                shared_ids, stored_len = hit
                n_share = stored_len // self.page_size
                # Counted at REGISTRATION (_register, via _session_hit):
                # a starvation-valve requeue re-admits and re-looks-up —
                # counting here would double-count, the same hazard the
                # legacy path dodged by counting after the requeue
                # window.
                opts["_session_hit"] = True
                if n_share > 0:
                    head = list(shared_ids[:n_share])
                    self._allocator.incref(head)
                    opts["_pages"] = head
                    opts["_shared_pages"] = n_share
                    base = n_share * self.page_size
                    self._page_journal.record(
                        "cow_copy", n_share,
                        self._allocator.allocated_pages, source="session",
                    )
                W = C_max
            # NOTE: prefix-cache lookup is deferred to the train's FIRST
            # chunk dispatch (_maybe_borrow_prefix) — the legacy fill
            # path looked up at fill time, after earlier admissions in
            # the same dequeue had published their pages, and two
            # identical queued prompts must keep sharing.
        else:
            # Slab trains always chunk at the largest bucket: ONE
            # compiled program set (chunk/commit/seed) serves every
            # train, and the chunk-granular prefix cache's fixed width
            # is exactly C_max.
            W = C_max
            row = self.model.make_cache(1, self._long_row_cap(W))
            if hit is not None:
                ek, ev, eks, evs, elen = hit
                opts["_session_hit"] = True  # counted at _register
                seed_fn, _ = self._session_fns()
                row = seed_fn(row, ek, ev, eks, evs, jnp.int32(elen))
                base = int(elen)
            elif self.prefix_cache is not None and total > W:
                phit = self.prefix_cache.lookup(prompt)
                if phit is not None:
                    _c, _co, seed_fn, _ex = self._long_prefill_fns(W)
                    row = seed_fn(row, *phit)
                    base = W
                    PREFIX_HITS.inc(tags={"model": self.model.name,
                                          "granularity": "chunk"})
                else:
                    insert_prefix = True
                    PREFIX_MISSES.inc(tags={"model": self.model.name,
                                            "granularity": "chunk"})
        self._trains.append(_ChunkTrain(
            req=req, prompt=prompt, opts=opts, slot_idx=slot_idx, C=W,
            pos=base, base=base, total=total, row=row,
            insert_prefix=insert_prefix, started_ms=now_ms(),
        ))
        self._train_slots.add(slot_idx)

    def _pump_prefill(self) -> None:
        """Spend at most ``prefill_token_budget`` tokens advancing
        pending chunk trains — the engine-owned interleave that replaced
        the count-based admission cap. FCFS head-first (oldest train's
        TTFT first); paged engines batch same-width trains into ONE
        chunk program per dispatch. Page-starved trains park for the
        round (counted) instead of evicting live streams; a round where
        NOTHING could progress while no stream is active triggers the
        starvation valve (requeue the newest train) so parked trains
        can never deadlock the pool among themselves."""
        if not self._trains:
            return
        model_tag = {"model": self.model.name}
        budget = self.prefill_token_budget
        parked: set = set()
        dispatched_any = False
        while budget > 0:
            head = next(
                (t for t in self._trains if id(t) not in parked), None
            )
            if head is None or head.C > budget:
                break
            if self.paged:
                members = [head]
                # Group SINGLE-chunk trains only: a multi-chunk train
                # dispatches solo so it can complete (and publish its
                # prefix pages) before an identical queued prompt's
                # first chunk looks the prefix up — batching two copies
                # of the same long prompt would compute both.
                if head.total - head.base <= head.C:
                    cap = min(self.max_admissions_per_step,
                              max(1, budget // head.C))
                    for t in self._trains:
                        if len(members) >= cap:
                            break
                        if (t is head or id(t) in parked
                                or t.C != head.C
                                or t.total - t.base > t.C):
                            continue
                        members.append(t)
                ready = []
                for t in members:
                    self._maybe_borrow_prefix(t)
                    if self._grant_train_pages(t):
                        ready.append(t)
                    else:
                        parked.add(id(t))
                        PREFILL_STARVED.inc(tags=model_tag)
                if not ready:
                    continue
                try:
                    self._dispatch_chunk_group(ready)
                except Exception as e:  # noqa: BLE001 — no-dangle rule
                    logger.exception(
                        "%s: chunk dispatch failed", self.model.name
                    )
                    for t in ready:
                        self._drop_train(t, e)
                    continue
                budget -= head.C * len(ready)
            else:
                try:
                    self._advance_train_slab(head)
                except Exception as e:  # noqa: BLE001 — no-dangle rule
                    logger.exception(
                        "%s: chunk dispatch failed", self.model.name
                    )
                    self._drop_train(head, e)
                    continue
                budget -= head.C
            dispatched_any = True
            if self.interleave_hook is not None:
                # Colocation fairness: co-tenant engines get their scans
                # between chunk dispatches, exactly as the legacy
                # ``between=`` callback provided.
                self.interleave_hook()
        if (self.paged and not dispatched_any and self._trains
                and not self._active_mask.any()):
            self._relieve_train_starvation()
        PREFILL_PENDING.set(float(len(self._trains)), tags=model_tag)

    def _drain_prefill(self) -> None:
        """Pump pending chunk trains to completion (tests and manual
        drivers that dequeued via ``_admit`` and want the admission
        fully registered; the serving loop never calls this — it pumps
        one budget per turn). Decode turns run ONLY when trains are
        parked behind pages that active streams hold — EOS is then the
        only thing that can free them."""
        while self._trains:
            before = (sum(t.pos for t in self._trains), len(self._trains))
            self._pump_prefill()
            after = (sum(t.pos for t in self._trains), len(self._trains))
            if after != before:
                continue
            if self._active_mask.any():
                # Starved behind live streams: advance them one turn so
                # finishes can free pages (a spin here would never end —
                # nothing else releases what the actives hold).
                self._step(horizon=1)
                continue
            # No progress and nothing decoding: trains are parked on
            # pages only EOS could free — a driver bug, not a wait.
            raise TimeoutError(
                f"{self.model.name}: chunk trains cannot progress "
                "(page-starved with no active streams)"
            )

    def _maybe_borrow_prefix(self, train: _ChunkTrain) -> None:
        """Longest-shared-page-prefix CoW borrow, resolved at the
        train's FIRST chunk dispatch (not at dequeue): earlier trains
        from the same burst publish their pages at completion, and the
        legacy fill-time lookup let an identical queued prompt share
        them — dequeue-time lookup would always miss. Borrowed pages
        become the train's head; ``pos``/``base`` jump past the shared
        positions."""
        if (not self.paged or self.paged_prefix is None
                or train.pos != train.base or train.pos != 0
                or train.opts.get("_shared_pages", 0)
                or train.opts.get("_prefix_done")
                or train.total <= self.page_size):
            return
        train.opts["_prefix_done"] = True
        phit = self.paged_prefix.lookup(train.prompt)
        if phit is None and self.host_spill is not None:
            phit = self._reload_spilled_prefix(train.prompt)
        if phit is None:
            PREFIX_MISSES.inc(tags={"model": self.model.name,
                                    "granularity": "page"})
            return
        shared_ids, shared_len = phit
        head = list(shared_ids)
        self._allocator.incref(head)
        train.opts["_pages"] = head + train.opts["_pages"]
        train.opts["_shared_pages"] = len(head)
        train.pos = train.base = shared_len
        self._page_journal.record(
            "cow_copy", len(head), self._allocator.allocated_pages,
            source="prefix",
        )
        PREFIX_HITS.inc(tags={"model": self.model.name,
                              "granularity": "page"})

    def _grant_train_pages(self, train: _ChunkTrain) -> bool:
        """Per-chunk page grant: extend the train's page run to cover
        the NEXT chunk's real positions (final chunks also cover the
        first generated token — or the first spec verify window on spec
        engines, the shared ``spec_scratch_pages`` rule). Cache pins
        shed first; a still-starved train parks (False) — live streams
        are never evicted to feed an admission."""
        take = min(train.C, train.total - train.pos)
        final = train.pos + take >= train.total
        if final:
            if self._dcache is not None:
                need = spec_scratch_pages(
                    train.total, self.spec_tokens + 1, self.page_size,
                    self._paged_capacity,
                )
            else:
                need = pages_for(
                    min(train.total + 1, self._paged_capacity),
                    self.page_size,
                )
        else:
            need = pages_for(train.pos + take, self.page_size)
        delta = need - len(train.opts["_pages"])
        if delta <= 0:
            return True
        while not self._allocator.can_alloc(delta):
            if not self._reclaim_cache_pins():
                break
        if not self._allocator.can_alloc(delta):
            return False
        train.opts["_pages"].extend(self._allocator.alloc(delta))
        return True

    def _dispatch_chunk_group(self, trains: List[_ChunkTrain]) -> None:
        """ONE pages-direct chunk program for up to a compiled group of
        same-width trains: chunk k/v scatter through per-row page-table
        rows, first token sampled in-program for final rows. Pad rows
        duplicate row 0 (identical data to identical pages — idempotent,
        the group-admission convention)."""
        W = trains[0].C
        n = len(trains)
        group = next(s for s in self._admit_group_sizes() if s >= n)
        tokens = np.zeros((group, W), np.int32)
        mask = np.zeros((group, W), np.int32)
        tables = np.full((group, self._n_table_entries), self.num_pages,
                         np.int32)
        meta_i = np.zeros((6, group), np.int32)
        meta_f = np.zeros((2, group), np.float32)
        bias_ids = np.zeros((group, self.max_bias_entries), np.int32)
        bias_vals = np.zeros((group, self.max_bias_entries), np.float32)
        finals: List[Tuple[int, _ChunkTrain]] = []
        for i, t in enumerate(trains):
            piece = t.prompt[t.pos : t.pos + W]
            take = int(piece.size)
            final = t.pos + take >= t.total
            tokens[i, :take] = piece
            mask[i, :take] = 1
            tables[i] = table_array(
                t.opts["_pages"], self._n_table_entries, self.num_pages
            )
            # Non-final rows steer the lengths scatter to the sentinel
            # slot: only the FINAL chunk publishes the verified length.
            meta_i[0, i] = t.slot_idx if final else self.num_slots
            meta_i[1, i] = t.pos
            meta_i[2, i] = take - 1
            meta_i[3, i] = t.opts["top_k"]
            meta_i[4, i] = t.opts["seed"]
            meta_i[5, i] = t.total
            meta_f[0, i] = t.opts["temperature"]
            meta_f[1, i] = t.opts.get("top_p", 1.0)
            bias_ids[i], bias_vals[i] = self._bias_arrays(t.opts)
            if final:
                finals.append((i, t))
        for i in range(n, group):
            tokens[i] = tokens[0]
            mask[i] = mask[0]
            tables[i] = tables[0]
            meta_i[:, i] = meta_i[:, 0]
            meta_f[:, i] = meta_f[:, 0]
            bias_ids[i] = bias_ids[0]
            bias_vals[i] = bias_vals[0]
        first, self._cache = self._chunk_paged_fn(
            self.params,
            jnp.asarray(np.stack([tokens, mask])),
            self._cache,
            jnp.asarray(tables),
            jnp.asarray(meta_i),
            jnp.asarray(meta_f),
            jnp.asarray(bias_ids),
            jnp.asarray(bias_vals),
        )
        for t in trains:
            t.pos = min(t.pos + W, t.total)
        PREFILL_CHUNKS.inc(n, tags={"model": self.model.name})
        self.interleave_log.append(("chunk", W * n))
        if not finals:
            return
        first_host = np.asarray(first)  # rdb-lint: disable=host-sync-in-hot-path (THE one fetch per chunk dispatch: the fused first-token ids — TTFT ends here, never at a logits round-trip)
        t_done = now_ms()
        for i, t in finals:
            self._retire_train(t)
            if self.paged_prefix is not None:
                # Publish BEFORE registration: a stop-on-first-token
                # finish frees the slot's pages, and the insert must pin
                # them first (the legacy after_commit contract).
                self.paged_prefix.insert(t.prompt, t.opts["_pages"])
            if self._dcache is not None:
                # The draft has no pages-direct path (its cache is a
                # slab): replay the whole prompt through the draft's
                # chunk program so speculation starts synced.
                self._draft_long_fill(
                    t.prompt, t.slot_idx, self.prompt_buckets[-1]
                )
            self._register(t.slot_idx, t.req, int(first_host[i]), t.opts,
                           t_done)

    def _advance_train_slab(self, train: _ChunkTrain) -> None:
        """One row-cache chunk for a slab train (the legacy chunk
        program under the token budget); the final chunk flows into the
        fused commit+sample dispatch via ``_commit_and_register``."""
        C = train.C
        chunk_fn, commit_fn, _seed, extract_fn = self._long_prefill_fns(C)
        piece = train.prompt[train.pos : train.pos + C]
        take = int(piece.size)
        tokens = np.zeros((1, C), np.int32)
        mask = np.zeros((1, C), np.int32)
        tokens[0, :take] = piece
        mask[0, :take] = 1
        train.last, train.row = chunk_fn(
            self.params, jnp.asarray(tokens), jnp.asarray(mask),
            train.row, jnp.int32(train.pos), jnp.int32(take - 1),
        )
        if train.insert_prefix and train.pos == 0 and take == C:
            # Chunk 0 was full: its k/v depend only on the first C token
            # ids — exactly reusable (the legacy after_first hook).
            self.prefix_cache.insert(
                train.prompt, *extract_fn(train.row, C)
            )
        train.pos += take
        PREFILL_CHUNKS.inc(tags={"model": self.model.name})
        self.interleave_log.append(("chunk", C))
        if train.pos >= train.total:
            self._retire_train(train)
            self._commit_and_register(
                train.req, train.prompt, train.opts, train.slot_idx,
                commit_fn, train.row, train.last, C,
            )

    def _retire_train(self, train: _ChunkTrain) -> None:
        if train in self._trains:
            self._trains.remove(train)
        self._train_slots.discard(train.slot_idx)

    def _drop_train(self, train: _ChunkTrain, exc: Exception) -> None:
        """A failed train must never dangle: release its pages (borrowed
        head decrefs its borrow) and reject the caller."""
        self._retire_train(train)
        self._release_pages(train.opts)
        train.req.reject(exc)

    def _relieve_train_starvation(self) -> None:
        """Deadlock valve for per-chunk grants: with no active streams
        there is no EOS to free pages, so an all-parked train set would
        wait forever on pages the OTHER parked trains hold. Requeue the
        NEWEST train (least sunk prefill cost — the slot-starvation
        requeue's twin), releasing its grant back to the pool. A LONE
        starved train should be impossible (the pool must back one
        slot's worth by construction, and with no actives + drained
        cache pins nothing else holds pages) — but if it ever happens,
        requeue it too: back in the queue, deadline-based staleness
        eventually rejects it, the legacy page-starvation economics,
        instead of the loop spinning on an unservable train forever.
        Prefer a train that has not dispatched yet (pos == base): zero
        sunk prefill cost AND no metrics to double-count."""
        if not self._trains:
            return
        train = next(
            (t for t in reversed(self._trains) if t.pos == t.base),
            self._trains[-1],
        )
        self._retire_train(train)
        self._release_pages(train.opts)
        if not self.queue.add_request(train.req, reject_on_full=False,
                                      requeue=True):
            self.queue.count_external_drop(
                train.req, reason="requeue_refused"
            )
            train.req.reject(RequestDropped(
                f"{train.req.request_id}: queue refused requeue during "
                "page-starved chunked admission"
            ))

    # --- paged admission bookkeeping ---------------------------------------
    def _alloc_admission_pages(self, req: Request, prompt: np.ndarray,
                               opts: Dict) -> bool:
        """Reserve the pages an admission needs (prompt + the first
        generated token's KV, MINUS any session pages the CoW borrow
        already covers — a long-history continuation must not demand its
        whole prompt's worth of free pages). Under pressure, cache pins
        (prefix/session entries) are shed before giving up. Page
        starvation is slot starvation's twin: the request goes back to
        the queue untouched and waits for EOS frees, exactly like a
        slot-starved single — never silently dropped.

        Spec engines reserve the first verify window's headroom
        alongside the KV (``pages_for(len + spec_tokens + 1)`` — THE
        shared round rule, ``tile_math.spec_scratch_pages``, called
        here with len = prompt size since the pending first token is
        row 0 OF the window): a slot admitted into a pool that cannot
        even host one round would otherwise thrash the round-scratch
        reclaim path from its very first step."""
        if self._dcache is not None:
            need_pages = spec_scratch_pages(
                int(prompt.size), self.spec_tokens + 1, self.page_size,
                self._paged_capacity,
            )
        else:
            need_pages = pages_for(int(prompt.size) + 1, self.page_size)
        need = max(0, need_pages
                   - int(opts.get("_session_share", 0)))
        while True:
            try:
                opts["_pages"] = self._allocator.alloc(need)
                return True
            except OutOfPages:
                if self._reclaim_cache_pins():
                    continue
                break
        hold = opts.pop("_session_hold", None)
        opts.pop("_session_share", None)
        if hold:
            self._allocator.decref(hold)
        if not self.queue.add_request(req, reject_on_full=False,
                                      requeue=True):
            self.queue.count_external_drop(req, reason="requeue_refused")
            req.reject(RequestDropped(
                f"{req.request_id}: queue refused requeue during "
                "page-starved admission"
            ))
        return False

    def _read_pages(self, page_ids: List[int]) -> Dict[str, np.ndarray]:
        """Gather the listed pages' contents to host (spill). The pages
        are pinned (prefix-cache refs) and never rewritten after
        publication (CoW invariant), so this read races nothing."""
        idx = np.asarray(page_ids, np.int32)
        out = {"k": np.asarray(self._cache.k[:, idx]),
               "v": np.asarray(self._cache.v[:, idx])}
        if self._cache.quantized:
            out["k_scale"] = np.asarray(self._cache.k_scale[:, idx])
            out["v_scale"] = np.asarray(self._cache.v_scale[:, idx])
        return out

    def _write_pages(self, page_ids: List[int],
                     payload: Dict[str, np.ndarray]) -> None:
        """Scatter spilled contents into freshly allocated pages
        (reload). Functional update — the pool array has one logical
        writer (this engine thread), like the page-table upload."""
        with self._device_ctx():
            idx = jnp.asarray(np.asarray(page_ids, np.int32))
            repl = {
                "k": self._cache.k.at[:, idx].set(
                    jnp.asarray(payload["k"], self._cache.k.dtype)),
                "v": self._cache.v.at[:, idx].set(
                    jnp.asarray(payload["v"], self._cache.v.dtype)),
            }
            if self._cache.quantized:
                repl["k_scale"] = self._cache.k_scale.at[:, idx].set(
                    jnp.asarray(payload["k_scale"], jnp.float32))
                repl["v_scale"] = self._cache.v_scale.at[:, idx].set(
                    jnp.asarray(payload["v_scale"], jnp.float32))
            self._cache = self._cache.replace(**repl)

    def _reload_spilled_prefix(
        self, prompt: np.ndarray
    ) -> Optional[Tuple[List[int], int]]:
        """Probe the host-RAM spill tier for the longest spilled
        page-prefix of ``prompt``; on a hit the pages come back into
        fresh HBM, get republished in the prefix cache, and the caller
        proceeds exactly as on an HBM hit. Returns the (page_ids,
        shared_len) borrow or None (absent, or no free pages for the
        reload — recompute then, never deepen the pressure)."""
        max_n = (int(prompt.size) - 1) // self.page_size
        if max_n <= 0 or len(self.host_spill) == 0:
            return None
        keys = digest_chain(prompt, self.page_size, max_n)
        for n in range(max_n, 0, -1):
            if keys[n - 1] not in self.host_spill:
                continue
            pids = self.host_spill.reload(keys[n - 1], self._allocator)
            if pids is None:
                return None
            # Republish (the cache pins them), then drop the reload's
            # own hold — pin symmetry identical to a slot publishing.
            self.paged_prefix.insert(prompt[: n * self.page_size], pids)
            self._allocator.decref(pids)
            return self.paged_prefix.lookup(prompt)
        return None

    def prefix_digests(self, limit: int = 128) -> Optional[Dict[str, Any]]:
        """Bounded digest publication for cluster-wide prefix routing:
        HBM prefix-cache entries plus spilled entries (both servable
        here — one reload vs a full recompute elsewhere), as
        ``{"page_size": ..., "digests": {hex: chain_len}}``."""
        if self.paged_prefix is None:
            return None
        digests = self.paged_prefix.digests(limit)
        if self.host_spill is not None and len(digests) < limit:
            for key, n in self.host_spill.digests(
                limit - len(digests)
            ).items():
                digests.setdefault(key, n)
        out: Dict[str, Any] = {
            "page_size": self.page_size, "digests": digests,
        }
        if self.host_spill is not None:
            # Spill round-trip convergence fix: a reload moves an entry
            # between tiers without changing this engine's advertised
            # union, so replacement-expiry upstream sees "unchanged" and
            # never notifies out-of-process routers. Surface the reloaded
            # keys so the controller forces a push (key present only when
            # non-empty — steady-state publications stay byte-identical).
            reloaded = self.host_spill.drain_republish()
            if reloaded:
                out["reloaded"] = reloaded
        return out

    def _reclaim_cache_pins(self) -> bool:
        """Shed one LRU cache pin under pool pressure — prefix entries
        first (pure recompute cost), then session turns (a re-prefill
        next turn). Cache pins are optimizations; live streams are not:
        this runs before any capacity-finish eviction. With a spill tier
        the shed prefix entry's page CONTENTS move to host RAM first, so
        the 'recompute cost' becomes 'one reload'. Returns True if an
        entry was dropped (its pages free unless a borrower still holds
        them — callers loop)."""
        if self.paged_prefix is not None and self.host_spill is not None:
            lru = self.paged_prefix.peek_lru()
            if lru is not None:
                key, pages = lru
                self.host_spill.spill(
                    key, list(pages), self._allocator.allocated_pages
                )
        for which, cache in (("prefix", self.paged_prefix),
                             ("session", self.paged_sessions)):
            if cache is not None and cache.evict_lru():
                self._page_journal.record(
                    "cache_reclaim", 0, self._allocator.allocated_pages,
                    cache=which,
                )
                return True
        return False

    def _release_pages(self, opts: Dict) -> None:
        """Undo an admission's page reservation (failed/requeued before
        a slot took ownership). Decrefs the whole list — borrowed CoW
        pages release their borrow, private pages free — plus any
        outstanding session seed-read hold (whole, or its post-swap
        tail)."""
        if not self.paged:
            return
        pages = opts.pop("_pages", None)
        opts.pop("_shared_pages", None)
        opts.pop("_session_share", None)
        if pages:
            self._allocator.decref(pages)
        for key in ("_session_hold", "_hold_tail"):
            hold = opts.pop(key, None)
            if hold:
                self._allocator.decref(hold)

    def _prefill_group(
        self,
        bucket: int,
        items: List[Tuple[Request, np.ndarray, Dict]],
        slot_ids: List[int],
    ) -> None:
        n = len(items)
        group = next(s for s in self._admit_group_sizes() if s >= n)
        tokens = np.zeros((group, bucket), dtype=np.int32)
        mask = np.zeros((group, bucket), dtype=np.int32)
        slots = np.zeros((group,), dtype=np.int32)
        temps = np.zeros((group,), dtype=np.float32)
        topk = np.zeros((group,), dtype=np.int32)
        topp = np.ones((group,), dtype=np.float32)
        seeds = np.zeros((group,), dtype=np.int32)
        bias_ids = np.zeros((group, self.max_bias_entries), dtype=np.int32)
        bias_vals = np.zeros((group, self.max_bias_entries),
                             dtype=np.float32)
        for i, (req, prompt, opts) in enumerate(items):
            tokens[i, : prompt.size] = prompt
            mask[i, : prompt.size] = 1
            slots[i] = slot_ids[i]
            temps[i] = opts["temperature"]
            topk[i] = opts["top_k"]
            topp[i] = opts.get("top_p", 1.0)
            seeds[i] = opts["seed"]
            bias_ids[i], bias_vals[i] = self._bias_arrays(opts)
        # Pad rows duplicate row 0 (same slot, same data — idempotent write).
        for i in range(n, group):
            tokens[i] = tokens[0]
            mask[i] = mask[0]
            slots[i] = slots[0]
            temps[i] = temps[0]
            topk[i] = topk[0]
            topp[i] = topp[0]
            seeds[i] = seeds[0]
            bias_ids[i] = bias_ids[0]
            bias_vals[i] = bias_vals[0]

        # Dtype-packed uploads: 5 transfers per admission group instead
        # of 10 (tok_idx is constant zero — prefill samples token 0 — so
        # it rides the int pack), and the draft prefill reuses the SAME
        # device buffers instead of re-uploading tokens/mask/slots.
        tokmask_d = jnp.asarray(np.stack([tokens, mask]))
        meta_i_d = jnp.asarray(np.stack([
            slots, topk, seeds, np.zeros((group,), np.int32),
        ]))
        meta_f_d = jnp.asarray(np.stack([temps, topp]))
        extra = ()
        if self.paged:
            # Physical destination pages per admitted row (sentinel
            # tail); pad rows duplicate row 0's pages — identical data
            # to identical pages, idempotent like the slot duplicate.
            pids = np.full((group, self._n_table_entries), self.num_pages,
                           dtype=np.int32)
            for i, (_req, _prompt, opts) in enumerate(items):
                pids[i] = table_array(opts["_pages"],
                                      self._n_table_entries, self.num_pages)
            for i in range(n, group):
                pids[i] = pids[0]
            extra = (jnp.asarray(pids),)
        first, self._cache = self._prefill_fn(bucket, group)(
            self.params,
            tokmask_d,
            self._cache,
            meta_i_d,
            meta_f_d,
            jnp.asarray(bias_ids),
            jnp.asarray(bias_vals),
            *extra,
        )
        if self._dcache is not None:
            # The draft must see the same prompt: fill its cache rows too.
            self._dcache = self._draft_prefill_fn(bucket, group)(
                self.draft_params,
                tokmask_d,
                self._dcache,
                meta_i_d,
            )
        first_host = np.asarray(first)  # ONE fetch for the whole group
        t = now_ms()
        for i, (req, _prompt, opts) in enumerate(items):
            self._register(slot_ids[i], req, int(first_host[i]), opts, t)

    # --- chunked prefill (long prompts) ------------------------------------
    def _prefill_chunk_impl(self, params, tokens, attn_mask, row_cache,
                            start, take_idx):
        return self.model.prefill_chunk(
            self._mp(params), tokens, attn_mask, row_cache, start, take_idx
        )

    def _commit_long_impl(self, cache, row_cache, meta_i, last_logits,
                          meta_f, bias_ids, bias_vals):
        """Copy the finished row cache into the big cache at ``slot`` and
        sample the first token — one dispatch closes the admission. The row
        cache is a whole number of chunks, so it can be LONGER than the
        shared cache; the static slice keeps only real capacity (positions
        past ``lengths`` are garbage either way and never attended).
        ``meta_i`` [3] packs slot/top_k/seed, ``meta_f`` [2] packs
        temperature/top_p (tok_idx is always 0 for a first sample)."""
        cache = commit_row(cache, row_cache, meta_i[0])
        first = self._sample_tokens(
            last_logits, meta_f[0:1], meta_i[1:2], meta_i[2:3],
            jnp.zeros((1,), jnp.int32), bias_ids, bias_vals, meta_f[1:2],
        )
        return first, cache

    def _seed_prefix_impl(self, row_cache, pk, pv, pks, pvs):
        """Copy a cached prefix segment into positions [0, C) of a fresh
        row cache — the HBM-copy replacement for recomputing chunk 0.
        One seed implementation serves both reuse paths (a parallel copy
        here once dropped the scale planes): the prefix segment's valid
        length is simply its width."""
        return self._seed_session_impl(
            row_cache, pk, pv, pks, pvs, pk.shape[2]
        )

    def _extract_prefix_impl(self, row_cache, width: int):
        """Static slice of the first ``width`` cache positions (the just-
        computed chunk 0) for insertion into the prefix cache — codes,
        and scale planes when the cache is quantized."""
        ks = vs = None
        if row_cache.quantized:
            ks = row_cache.k_scale[:, :, :width]
            vs = row_cache.v_scale[:, :, :width]
        return (row_cache.k[:, :, :width], row_cache.v[:, :, :width],
                ks, vs)

    def _commit_long_paged_impl(self, cache, row_cache, meta_i,
                                last_logits, meta_f, bias_ids, bias_vals,
                                write_pids):
        """Paged mirror of :meth:`_commit_long_impl`: the finished row is
        page-cut and scattered at ``write_pids`` [1, NP] (sentinel for
        borrowed CoW pages — the shared prefix is never rewritten — and
        the unallocated tail), then the first token samples."""
        cache = copy_rows_into_paged(cache, row_cache, meta_i[0:1],
                                     write_pids)
        first = self._sample_tokens(
            last_logits, meta_f[0:1], meta_i[1:2], meta_i[2:3],
            jnp.zeros((1,), jnp.int32), bias_ids, bias_vals, meta_f[1:2],
        )
        return first, cache

    def _seed_paged_impl(self, row_cache, cache, table_row, elen):
        """Gather a page run (``table_row`` [NP] int32, sentinel-padded)
        into positions [0, S) of a fresh row cache and mark ``elen``
        valid — how a CoW borrower sees its shared prefix KV during the
        tail prefill. Sentinel entries clamp to a real page; everything
        past ``elen`` is garbage the tail fill overwrites or the mask
        never attends (the standard invariant)."""
        P = cache.k.shape[1]
        safe = jnp.minimum(table_row, P - 1)
        S = self._paged_capacity

        def logical(arr):
            g = arr[:, safe]  # [L, NP, ps, ...]
            return g.reshape((arr.shape[0], 1, S) + arr.shape[3:])

        k = jax.lax.dynamic_update_slice(
            row_cache.k, logical(cache.k), (0, 0, 0, 0, 0)
        )
        v = jax.lax.dynamic_update_slice(
            row_cache.v, logical(cache.v), (0, 0, 0, 0, 0)
        )
        scales = {}
        if cache.k_scale is not None:
            scales = {
                "k_scale": jax.lax.dynamic_update_slice(
                    row_cache.k_scale, logical(cache.k_scale), (0, 0, 0, 0)
                ),
                "v_scale": jax.lax.dynamic_update_slice(
                    row_cache.v_scale, logical(cache.v_scale), (0, 0, 0, 0)
                ),
            }
        return row_cache.replace(
            k=k, v=v, lengths=jnp.full_like(row_cache.lengths, elen),
            **scales,
        )

    def _paged_seed_fn(self) -> Callable:
        fn = self._prefill_fns.get("paged_seed")
        if fn is None:
            fn = instrument("paged_seed", jax.jit(
                self._seed_paged_impl, donate_argnums=(0,)
            ))
            self._prefill_fns["paged_seed"] = fn
        return fn

    def _long_prefill_fns(self, chunk: int):
        """Lazily compiled (chunk, commit, seed, extract) fns — long
        prompts may never arrive, so their programs are not part of warmup;
        the persistent compilation cache absorbs the first-hit cost across
        restarts."""
        fns = self._prefill_fns.get(("long", chunk))
        if fns is None:
            fns = (
                instrument("long_chunk", jax.jit(
                    self._prefill_chunk_impl, donate_argnums=(3,)
                )),
                # Only the shared cache (arg 0) can alias the output; the
                # row cache's [L,1,row_cap,K,H] matches no output shape, so
                # donating it buys nothing and warns on every compile.
                instrument(
                    "long_commit_paged" if self.paged else "long_commit",
                    jax.jit(self._commit_long_paged_impl if self.paged
                            else self._commit_long_impl,
                            donate_argnums=(0,)),
                ),
                instrument("prefix_seed", jax.jit(
                    self._seed_prefix_impl, donate_argnums=(0,)
                )),
                instrument("prefix_extract", jax.jit(
                    self._extract_prefix_impl, static_argnums=(1,)
                )),
            )
            self._prefill_fns[("long", chunk)] = fns
        return fns

    def _long_row_cap(self, C: int) -> int:
        """Row-cache capacity for chunked fills: whole chunks covering
        max_len PLUS one spare chunk. The spare absorbs the final chunk of
        an UNALIGNED continuation (session base need not be a multiple of
        C) — without it, dynamic_update_slice CLAMPS the overrunning start
        index and silently overwrites earlier positions. One static shape
        for every prompt length and base, so all fills share programs; the
        commit slices back down to shared capacity. Paged engines
        additionally cover the page-rounded logical capacity, so the
        commit's page cut always has whole pages to slice."""
        cap = self._paged_capacity if self.paged else self.max_len
        return ((cap + C - 1) // C) * C + C

    def _interleave_step(self) -> None:
        """One plain decode step for the active batch between chunk
        dispatches — the bound that keeps a long fill from stalling
        in-flight requests for more than one chunk. When a colocation
        executor hosts this engine it installs ``interleave_hook``, so
        CO-TENANT engines get scans between chunks too — otherwise one
        tenant's long-prompt admission would monopolize the shared chip
        for the whole fill (engine/colocate.py)."""
        if self._active_mask.any():
            self._step(horizon=1)
        if self.interleave_hook is not None:
            self.interleave_hook()

    def _commit_and_register(
        self, req: Request, prompt: np.ndarray, opts: Dict, slot_idx: int,
        commit_fn: Callable, row, last, C: int,
        after_commit: Optional[Callable[[], None]] = None,
    ) -> None:
        """Shared tail of every chunked admission (long and session): one
        commit dispatch (row -> shared cache + first-token sample), the
        draft replay when speculation is on, then registration.
        ``after_commit`` runs between commit and registration — the
        paged prefix-publish hook, which must see committed pages but
        must run BEFORE a stop-on-first-token registration can free
        them."""
        bids, bvals = self._bias_arrays(opts)
        extra = ()
        if self.paged:
            shared = int(opts.get("_shared_pages", 0))
            wp = list(opts["_pages"])
            # Borrowed CoW pages: steered to the sentinel so the commit
            # scatter cannot rewrite them (first divergent position lands
            # in the first PRIVATE page by the share-length rule).
            wp[:shared] = [self.num_pages] * shared
            extra = (jnp.asarray(table_array(
                wp, self._n_table_entries, self.num_pages
            )[None]),)
        first, self._cache = commit_fn(
            self._cache,
            row,
            jnp.asarray(np.asarray(
                [slot_idx, opts["top_k"], opts["seed"]], np.int32
            )),
            last,
            jnp.asarray(np.asarray(
                [opts["temperature"], opts["top_p"]], np.float32
            )),
            jnp.asarray(bids[None]),
            jnp.asarray(bvals[None]),
            *extra,
        )
        if after_commit is not None:
            after_commit()
        if self._dcache is not None:
            self._draft_long_fill(prompt, slot_idx, C)
        self._register(slot_idx, req, int(np.asarray(first)[0]), opts,
                       now_ms())

    def _prefill_long(
        self, req: Request, prompt: np.ndarray, opts: Dict, slot_idx: int
    ) -> None:
        """Admit one prompt longer than every bucket: prefill it in
        ``chunk``-token compiled pieces into a private single-row cache,
        running ONE decode step for the active batch between chunks so a
        10k-token prompt stalls decoding by at most one chunk's latency
        (chunked-prefill admission), then commit the row into the shared
        cache. The reference has no analogue (single-shot vision)."""
        C = self.prompt_buckets[-1]
        chunk_fn, commit_fn, seed_fn, extract_fn = self._long_prefill_fns(C)
        L = int(prompt.size)
        n_chunks = (L + C - 1) // C
        row = self.model.make_cache(1, self._long_row_cap(C))
        start_chunk = 0
        base = 0
        after_first = None
        after_commit = None
        if self.paged and self.paged_prefix is not None:
            # Page-granular reuse: borrow the longest shared page-prefix
            # by reference (CoW — the boundary partial page and the tail
            # recompute into PRIVATE pages via the row), and publish this
            # prompt's own full-page prefixes once they are committed.
            hit = self.paged_prefix.lookup(prompt)
            if hit is None and self.host_spill is not None:
                # Host-RAM spill tier: a prefix shed under pool pressure
                # reloads instead of recomputing (journaled as "reload").
                hit = self._reload_spilled_prefix(prompt)
            if hit is not None:
                shared_ids, shared_len = hit
                self._swap_in_shared(opts, shared_ids)
                row = self._paged_seed_fn()(
                    row, self._cache,
                    jnp.asarray(table_array(
                        shared_ids, self._n_table_entries, self.num_pages
                    )),
                    jnp.int32(shared_len),
                )
                base = shared_len
                PREFIX_HITS.inc(tags={"model": self.model.name,
                                      "granularity": "page"})
            else:
                PREFIX_MISSES.inc(tags={"model": self.model.name,
                                        "granularity": "page"})
            after_commit = lambda: self.paged_prefix.insert(  # noqa: E731
                prompt, opts["_pages"]
            )
        elif self.prefix_cache is not None:
            # Chunk 0 is full (n_chunks >= 2 on this path), so its k/v
            # depend only on the first C token ids — exactly reusable.
            hit = self.prefix_cache.lookup(prompt)
            if hit is not None:
                row = seed_fn(row, *hit)
                start_chunk = 1
                PREFIX_HITS.inc(tags={"model": self.model.name,
                                      "granularity": "chunk"})
            else:
                after_first = lambda r: self.prefix_cache.insert(  # noqa: E731
                    prompt, *extract_fn(r, C)
                )
                PREFIX_MISSES.inc(tags={"model": self.model.name,
                                        "granularity": "chunk"})

        last, row = run_chunked(
            chunk_fn, self.params, prompt[base:], C, row,
            start_chunk=start_chunk, between=self._interleave_step,
            after_first=after_first, base=base,
        )
        self._commit_and_register(
            req, prompt, opts, slot_idx, commit_fn, row, last, C,
            after_commit=after_commit,
        )

    def _seed_session_impl(self, row_cache, ek, ev, eks, evs, elen):
        """Copy a stored session row ([L,1,S,K,H]) into a fresh row cache
        and mark ``elen`` positions valid. ``eks``/``evs`` are the row's
        scale planes (int8 caches) or None."""
        k = jax.lax.dynamic_update_slice(row_cache.k, ek, (0, 0, 0, 0, 0))
        v = jax.lax.dynamic_update_slice(row_cache.v, ev, (0, 0, 0, 0, 0))
        scales = {}
        if eks is not None:
            scales = {
                "k_scale": jax.lax.dynamic_update_slice(
                    row_cache.k_scale, eks, (0, 0, 0, 0)),
                "v_scale": jax.lax.dynamic_update_slice(
                    row_cache.v_scale, evs, (0, 0, 0, 0)),
            }
        return row_cache.replace(
            k=k, v=v, lengths=jnp.full_like(row_cache.lengths, elen),
            **scales,
        )

    def _extract_row_impl(self, cache, slot):
        """Slice one slot's full cache row out of the shared cache (the
        finished turn's KV, stored for the session's next turn) — codes
        plus scale planes when the cache is quantized."""
        k = jax.lax.dynamic_slice_in_dim(cache.k, slot, 1, axis=1)
        v = jax.lax.dynamic_slice_in_dim(cache.v, slot, 1, axis=1)
        ks = vs = None
        if cache.quantized:
            ks = jax.lax.dynamic_slice_in_dim(
                cache.k_scale, slot, 1, axis=1)
            vs = jax.lax.dynamic_slice_in_dim(
                cache.v_scale, slot, 1, axis=1)
        return k, v, ks, vs

    def _session_fns(self):
        fns = self._prefill_fns.get("session")
        if fns is None:
            fns = (
                instrument("session_seed", jax.jit(
                    self._seed_session_impl, donate_argnums=(0,)
                )),
                instrument("session_extract",
                           jax.jit(self._extract_row_impl)),
            )
            self._prefill_fns["session"] = fns
        return fns

    def _prefill_session(
        self, req: Request, prompt: np.ndarray, opts: Dict, hit: Tuple,
        slot_idx: int,
    ) -> None:
        """Continue a conversation from its stored KV: seed the row cache
        with the previous turn's row, chunk-prefill ONLY the new tail
        (traced start — the base need not be chunk-aligned), and commit.
        Turn-N admission cost scales with the new message, not the whole
        history."""
        ek, ev, eks, evs, elen = hit
        SESSION_HITS.inc(tags={"model": self.model.name})
        C = self.prompt_buckets[-1]
        chunk_fn, commit_fn, _seed_prefix, _extract = \
            self._long_prefill_fns(C)
        seed_fn, _ = self._session_fns()
        row = self.model.make_cache(1, self._long_row_cap(C))
        row = seed_fn(row, ek, ev, eks, evs, jnp.int32(elen))
        tail = prompt[elen:]
        last, row = run_chunked(
            chunk_fn, self.params, tail, C, row,
            between=self._interleave_step, base=elen,
        )
        # The draft replay inside covers the WHOLE prompt (the draft has
        # no stored row) so speculation starts synced.
        self._commit_and_register(
            req, prompt, opts, slot_idx, commit_fn, row, last, C
        )

    def _swap_in_shared(self, opts: Dict, shared_ids: List[int]) -> None:
        """CoW borrow at admission: pin the shared pages (incref), hand
        back the equivalent leading PRIVATE pages reserved at admission,
        and splice — ``opts['_pages']`` stays the slot's full logical
        run, with ``_shared_pages`` marking the borrowed (never-written)
        head. Incref-before-decref so nothing transits refcount 0."""
        n = len(shared_ids)
        pages = opts["_pages"]
        self._allocator.incref(shared_ids)
        self._allocator.decref(pages[:n])
        opts["_pages"] = list(shared_ids) + pages[n:]
        opts["_shared_pages"] = n
        self._page_journal.record(
            "cow_copy", n, self._allocator.allocated_pages, source="prefix"
        )

    def _prefill_session_paged(
        self, req: Request, prompt: np.ndarray, opts: Dict, hit: Tuple,
        slot_idx: int,
    ) -> None:
        """Paged session continuation: borrow the stored turn's FULL
        pages by reference, seed the row cache from the whole stored run
        (the partial boundary page's content rides into the row — its
        private copy is made by the commit, which is the copy-on-write),
        chunk-prefill only the new tail, and commit tail pages as
        private."""
        shared_ids, stored_len = hit
        SESSION_HITS.inc(tags={"model": self.model.name})
        C = self.prompt_buckets[-1]
        chunk_fn, commit_fn, _seed, _extract = self._long_prefill_fns(C)
        # Only COMPLETE pages are borrowed: the boundary page would be
        # written by the borrower (positions >= stored_len) and must
        # diverge into a private copy. The admission hold (taken at
        # lookup) pins ALL stored pages, and the admission reserved only
        # the NON-shared tail: transfer the full-page head of the hold
        # into the slot's borrow, keep the hold's tail pinned until the
        # seed has read it and the commit has written its private copy.
        n_share = stored_len // self.page_size
        opts.pop("_session_hold", None)  # split into borrow + tail below
        opts.pop("_session_share", None)
        opts["_pages"] = list(shared_ids[:n_share]) + opts["_pages"]
        opts["_shared_pages"] = n_share
        opts["_hold_tail"] = list(shared_ids[n_share:])
        self._page_journal.record(
            "cow_copy", n_share, self._allocator.allocated_pages,
            source="session",
        )
        row = self.model.make_cache(1, self._long_row_cap(C))
        row = self._paged_seed_fn()(
            row, self._cache,
            jnp.asarray(table_array(
                shared_ids, self._n_table_entries, self.num_pages
            )),
            jnp.int32(stored_len),
        )
        tail = prompt[stored_len:]
        last, row = run_chunked(
            chunk_fn, self.params, tail, C, row,
            between=self._interleave_step, base=stored_len,
        )
        self._commit_and_register(
            req, prompt, opts, slot_idx, commit_fn, row, last, C
        )
        hold_tail = opts.pop("_hold_tail", None)
        if hold_tail:
            self._allocator.decref(hold_tail)

    def _draft_long_fill(self, prompt: np.ndarray, slot_idx: int,
                         C: int) -> None:
        """Chunk the long prompt through the DRAFT model into its cache
        row, interleaving decode steps between chunks like the target fill
        — the chunked-prefill latency bound (one chunk's stall, not the
        whole prompt) must hold for the draft pass too."""
        fns = self._prefill_fns.get(("draft_long", C))
        if fns is None:
            def chunk_impl(dparams, tokens, attn_mask, row, start, take):
                return self.draft_model.prefill_chunk(
                    dparams, tokens, attn_mask, row, start, take
                )

            fns = (
                instrument("draft_long_chunk",
                           jax.jit(chunk_impl, donate_argnums=(3,))),
                instrument("draft_long_commit",
                           jax.jit(commit_row, donate_argnums=(0,))),
            )
            self._prefill_fns[("draft_long", C)] = fns
        chunk_fn, commit_fn = fns
        # Chunk-aligned (base 0 always): the unaligned-base spare chunk of
        # _long_row_cap is a target-path (session continuation) concern.
        dcap = self._dcache.capacity
        row = self.draft_model.make_cache(1, ((dcap + C - 1) // C) * C)
        _, row = run_chunked(
            chunk_fn, self.draft_params, prompt, C, row,
            between=self._interleave_step,
        )
        self._dcache = commit_fn(self._dcache, row, jnp.int32(slot_idx))

    def _register(
        self, slot_idx: int, req: Request, first_tok: int, opts: Dict,
        t: float,
    ) -> None:
        max_new = opts["max_new"]
        slot = self._slots[slot_idx]
        slot.request = req
        slot.generated = [first_tok]
        slot.max_new_tokens = max_new
        slot.prefill_done_ms = t
        slot.last_token = first_tok
        slot.stop = opts["stop"]
        slot.session_id = opts.get("session_id")
        slot.prompt_tokens = opts.get("_prompt_tokens")
        self._len_host[slot_idx] = int(opts.get("_cache_len", 0))
        if self.paged:
            # Ownership handoff: the slot now holds the admission's page
            # reservation; the host table mirror maps it for the next
            # dispatch's refresh.
            slot.pages = list(opts.get("_pages", ()))
            slot.shared_pages = int(opts.get("_shared_pages", 0))
            self._table_host[slot_idx] = table_array(
                slot.pages, self._n_table_entries, self.num_pages
            )
            self._table_dirty = True
        self._tokens[slot_idx, 0] = first_tok
        self._active_mask[slot_idx] = True
        self._temps[slot_idx] = opts["temperature"]
        self._topk[slot_idx] = opts["top_k"]
        self._topp[slot_idx] = opts.get("top_p", 1.0)
        self._seeds[slot_idx] = opts["seed"]
        self._bias_ids[slot_idx], self._bias_vals[slot_idx] = \
            self._bias_arrays(opts)
        self._pres[slot_idx] = opts.get("presence_penalty", 0.0)
        self._freq[slot_idx] = opts.get("frequency_penalty", 0.0)
        self._sampling_dev = None  # host arrays changed
        if self._pres[slot_idx] or self._freq[slot_idx]:
            # Stale counts only matter to rows that USE them: zero the
            # reused slot's row on demand (penalty-free admissions — the
            # common case — skip the dispatch; their penalties multiply
            # the stale counts by zero).
            self._counts = self._zero_counts_fn(
                self._counts, jnp.int32(slot_idx), jnp.int32(first_tok)
            )

        PREFILLS_TOTAL.inc(tags={"model": self.model.name})
        if opts.get("_session_hit"):
            # Chunked trains count their session hit here, past every
            # requeue window (mono session fills count at fill start —
            # they are equally past it).
            SESSION_HITS.inc(tags={"model": self.model.name})
        if opts.get("_session_miss"):
            SESSION_MISSES.inc(tags={"model": self.model.name})
        TTFT_MS.observe(
            t - req.arrival_ms, tags={"model": self.model.name},
            trace_id=(req.trace_ctx or {}).get("trace_id"),
        )
        admit_ms = getattr(req, "admit_ms", None) or t
        queue_wait = max(0.0, admit_ms - req.arrival_ms)
        # The share of queue_wait spent inside the decode scan that was in
        # flight when the request arrived: overlap of [arrival, dequeue]
        # with the most recently completed scan window.
        scan_wait = max(0.0, min(admit_ms, self._scan_end_ms)
                        - max(req.arrival_ms, self._scan_start_ms))
        prefill_ms = max(0.0, t - admit_ms)
        self._ttft_parts.append(
            (queue_wait, min(scan_wait, queue_wait), prefill_ms)
        )
        TTFT_QUEUE_MS.observe(queue_wait, tags={"model": self.model.name})
        TTFT_PREFILL_MS.observe(prefill_ms, tags={"model": self.model.name})
        if _tracer().enabled:
            # Retroactive prefill span (admit -> first token) in the
            # request's trace: with the queue.wait span the pop emitted,
            # the flight record now shows the full TTFT decomposition.
            _tracer().record_span(
                "decode.prefill",
                ctx=req.trace_ctx,
                start_ms=admit_ms,
                end_ms=t,
                model=self.model.name,
                lane=self.model.name,
                queue_wait_ms=round(queue_wait, 2),
                scan_wait_ms=round(min(scan_wait, queue_wait), 2),
            )
        req.stream_put(first_tok)
        # First token may already satisfy the stop conditions.
        if self._is_stop(slot, first_tok) or max_new <= 1:
            reason = "eos" if self._is_stop(slot, first_tok) else "length"
            self._finish(slot_idx, reason)

    def _is_stop(self, slot: _Slot, tok: int) -> bool:
        return (
            (self.eos_token_id is not None and tok == self.eos_token_id)
            or tok in slot.stop
        )

    # --- step + eviction ---------------------------------------------------
    def _finish(self, slot_idx: int, reason: str) -> None:
        slot = self._slots[slot_idx]
        req = slot.request
        t = now_ms()
        if self.paged and slot.pages:
            if (self.paged_sessions is not None and slot.session_id
                    and slot.prompt_tokens is not None):
                # O(1) session store: pin the pages covering the turn's
                # history (prompt + generated[:-1] — same stored-history
                # rule as the slab path) instead of copying the row out.
                # Incref (store) strictly before the slot's decref below,
                # so the pages never transit the free list.
                history = np.concatenate([
                    np.asarray(slot.prompt_tokens, np.int32),
                    np.asarray(slot.generated[:-1], np.int32),
                ])
                self.paged_sessions.store(
                    slot.session_id, slot.pages, history
                )
            self._free_slot_pages(slot_idx)
        if (self.session_cache is not None and slot.session_id
                and slot.prompt_tokens is not None):
            # The cache row holds prompt + generated[:-1] (the final token
            # is still pending, never fed). Store the row + that exact
            # history so the session's next turn continues from it. Any
            # cached positions past the history (spec rounds advance the
            # cache through tokens the host truncated at a stop) sit
            # beyond the stored length and are overwritten by the next
            # turn's tail prefill before they can be attended.
            _, extract_fn = self._session_fns()
            seg = extract_fn(self._cache, jnp.int32(slot_idx))
            history = np.concatenate([
                np.asarray(slot.prompt_tokens, np.int32),
                np.asarray(slot.generated[:-1], np.int32),
            ])
            self.session_cache.store(slot.session_id, seg, history)
        result = DecodeResult(
            tokens=list(slot.generated),
            finish_reason=reason,
            ttft_ms=slot.prefill_done_ms - req.arrival_ms,
            total_ms=t - req.arrival_ms,
        )
        req.fulfill(result)
        if _tracer().enabled:
            # Completion event joined to the caller's trace: carries the
            # numbers an operator actually debugs with.
            with _tracer().attach_context(req.trace_ctx, "decode.sequence") as sp:
                if sp is not None:
                    sp.attributes.update(
                        tokens=len(slot.generated),
                        finish_reason=reason,
                        ttft_ms=round(result.ttft_ms, 1),
                        total_ms=round(result.total_ms, 1),
                    )
        self.queue.record_batch_completion([req], completed_at_ms=t)
        TOKENS_TOTAL.inc(len(slot.generated), tags={"model": self.model.name})
        self._slots[slot_idx] = _Slot()
        self._active_mask[slot_idx] = False
        self._len_host[slot_idx] = 0
        self._temps[slot_idx] = 0.0
        self._topk[slot_idx] = 0
        self._topp[slot_idx] = 1.0
        self._seeds[slot_idx] = 0
        self._bias_ids[slot_idx] = 0
        self._bias_vals[slot_idx] = 0.0
        self._pres[slot_idx] = 0.0
        self._freq[slot_idx] = 0.0
        # NO device-array invalidation here: the freed slot's stale device
        # values are masked (inactive rows' samples are discarded and add
        # zero to counts), and _register refreshes the row before any
        # reuse — invalidating on every completion forced a full re-upload
        # of all eight sampling arrays per finished sequence, pure tunnel
        # overhead at high completion churn.
        self.completed += 1

    # --- page-pool management (paged mode) --------------------------------
    def _free_slot_pages(self, slot_idx: int) -> None:
        """Return a finished slot's page references to the pool — EOS
        frees pages immediately mid-cycle: this runs inside ``_harvest``,
        before the next admission check, so a burst waiting on pages can
        admit the moment a stream ends instead of at slab granularity.
        The device table row goes to sentinel at the next refresh, which
        happens before any dispatch could write through it."""
        slot = self._slots[slot_idx]
        if slot.pages:
            self._allocator.decref(slot.pages)
            slot.pages = []
            slot.shared_pages = 0
        self._table_host[slot_idx] = self.num_pages
        self._len_host[slot_idx] = 0
        self._table_dirty = True

    def _refresh_table(self) -> None:
        """Upload the host page-table mirror when it changed (admission,
        finish, growth). The device table has exactly ONE writer — this
        upload; compiled programs treat it as read-only — so the mirror
        can never drift from what the kernel gathers through."""
        if self._table_dirty:
            with self._device_ctx():
                self._cache = self._cache.replace(
                    page_table=jnp.asarray(self._table_host)
                )
            self._table_dirty = False

    def _ensure_page_headroom(self, horizon: int) -> None:
        """Grow active slots' page runs to cover the next ``horizon``
        substeps before the scan dispatches (a scan cannot allocate
        mid-flight — shapes are static and the allocator is host state).

        Over-subscribed pools can run dry here; the documented policy is
        to CAPACITY-FINISH the most recently admitted other slot (its
        caller gets a complete-but-truncated result, the same contract
        as cache exhaustion) and reuse its pages — newest-first eviction
        keeps long-running streams, which have the most sunk cost,
        alive. Full-backing pools (the default) never enter the eviction
        branch."""
        for i in np.flatnonzero(self._active_mask):
            slot = self._slots[i]
            if slot.free:
                continue
            need = pages_for(
                min(int(self._len_host[i]) + horizon, self.max_len),
                self.page_size,
            )
            delta = need - len(slot.pages)
            if delta <= 0:
                continue
            while not self._allocator.can_alloc(delta):
                # Shed cache pins first: a pool pinned by prefix/session
                # entries must never truncate a live stream to grow
                # another (the entries are pure optimizations and, being
                # the only non-slot owners, are what makes slot eviction
                # reclaim nothing).
                if self._reclaim_cache_pins():
                    continue
                victim = self._eviction_victim(exclude=int(i))
                if victim is None:
                    break
                PAGE_EVICTIONS.inc(tags={"model": self.model.name})
                self._page_journal.record(
                    "eviction", len(self._slots[victim].pages),
                    self._allocator.allocated_pages, slot=int(victim),
                )
                self._finish(victim, "capacity")
            if not self._allocator.can_alloc(delta):
                # Not even eviction could cover this slot: truncate IT.
                PAGE_EVICTIONS.inc(tags={"model": self.model.name})
                self._page_journal.record(
                    "eviction", len(slot.pages),
                    self._allocator.allocated_pages, slot=int(i),
                )
                self._finish(int(i), "capacity")
                continue
            slot.pages.extend(self._allocator.alloc(delta))
            self._table_host[i] = table_array(
                slot.pages, self._n_table_entries, self.num_pages
            )
            self._table_dirty = True

    def _eviction_victim(self, exclude: int) -> Optional[int]:
        """Most recently admitted active slot other than ``exclude``
        (newest-first eviction), or None."""
        best, best_t = None, -1.0
        for j, s in enumerate(self._slots):
            if j == exclude or s.free or not self._active_mask[j]:
                continue
            if s.prefill_done_ms > best_t:
                best, best_t = j, s.prefill_done_ms
        return best

    def _pick_horizon(self) -> int:
        """Three-tier horizon: full scan only when the batch is full (no
        admission possible — throughput-bound), single steps while requests
        wait for a free slot (admit ASAP), and the short ``ttft_horizon``
        when slots are free but nothing is queued — so an arrival during the
        scan waits at most ttft_horizon substeps, not decode_horizon."""
        if self.decode_horizon <= 1:
            return 1
        if self._trains:
            # Chunk trains pending: single-step turns keep the
            # chunk/turn interleave cadence tight — a long scan between
            # chunks would stretch every pending train's TTFT by the
            # whole scan.
            return 1
        if not self._free_slots():
            return self.decode_horizon
        if len(self.queue) == 0:
            return self.ttft_horizon
        return 1

    def ttft_breakdown(self) -> Dict[str, float]:
        """p50/p95 of the TTFT components over the rolling window:
        ``queue_wait`` (arrival -> dequeue — slot starvation plus waiting
        out in-flight scans), ``scan_wait`` (the in-flight-scan share of
        queue_wait; bounded by ttft_horizon substeps while slots are free),
        and ``prefill`` (dequeue -> first token). Published in the bench
        LLM row so an on-chip run shows where the TTFT milliseconds live."""
        parts = list(self._ttft_parts)
        if not parts:
            return {"n": 0}
        out: Dict[str, float] = {"n": len(parts)}
        for name, vals in zip(
            ("queue_wait_ms", "scan_wait_ms", "prefill_ms"),
            zip(*parts),
        ):
            s = sorted(vals)
            out[f"{name}_p50"] = round(s[len(s) // 2], 2)
            out[f"{name}_p95"] = round(s[min(len(s) - 1,
                                             int(len(s) * 0.95))], 2)
        return out

    def reset_ttft_window(self) -> None:
        """Drop the rolling TTFT window (benchmark phase boundaries)."""
        self._ttft_parts.clear()

    def _sampling_arrays(self):
        """Device copies of the per-slot sampling state, PACKED by dtype:
        (samp_f [4,B] = temps/topp/pres/freq, samp_i [2,B] = topk/seeds,
        bias_ids [B,K], bias_vals [B,K]) — four transfers per refresh
        instead of eight."""
        if self._sampling_dev is None:
            self._sampling_dev = (
                jnp.asarray(np.stack(
                    [self._temps, self._topp, self._pres, self._freq]
                )),
                jnp.asarray(np.stack([self._topk, self._seeds])),
                jnp.asarray(self._bias_ids),
                jnp.asarray(self._bias_vals),
            )
        return self._sampling_dev

    def _record_turn_span(self, horizon: int, active_mask,
                          spec: bool = False) -> None:
        """One retroactive span per decode scan (dispatch -> host fetch),
        linked to every sequence that was active in it: continuous
        batching's fan-in, the decode analogue of the batch-execution
        span. Bounded by num_slots links per turn."""
        links = [
            _link_to(slot.request.trace_ctx)
            for i, slot in enumerate(self._slots)
            if active_mask[i] and slot.request is not None
        ]
        _tracer().record_span(
            "decode.turn",
            start_ms=self._scan_start_ms,
            end_ms=self._scan_end_ms,
            links=links,
            model=self.model.name,
            lane=self.model.name,
            horizon=int(horizon),
            active=int(active_mask.sum()),
            spec=spec,
        )

    def _use_spec(self) -> bool:
        """Speculative rounds serve all-greedy batches only: sampled rows
        need rejection sampling for exactness, so any temperature>0 row
        drops the whole batch back to plain decode."""
        active = self._active_mask
        return (
            self._dcache is not None
            and self._sample_custom is None
            and bool(active.any())
            and float(self._temps[active].max(initial=0.0)) == 0.0
            # Penalties need the per-step count updates of the plain path
            # — NEGATIVE penalties (valid per the API) count too, so test
            # magnitude, not the signed max.
            and float(np.abs(self._pres[active]).max(initial=0.0)) == 0.0
            and float(np.abs(self._freq[active]).max(initial=0.0)) == 0.0
        )

    # --- paged spec-round page bookkeeping (ISSUE 13 tentpole) -----------
    def _reserve_spec_scratch(self) -> bool:
        """Extend each active slot's device table to cover this round's
        verify window ``[len, len + k + 1)`` with SCRATCH pages drawn
        from the shared pool (``tile_math.spec_scratch_pages`` — the
        admission headroom rule re-applied per round). Scratch pages are
        named by the table (the verify scatter writes through them) but
        are NOT yet owned by the slot: the round's outcome splices the
        accepted prefix's pages into ``slot.pages`` and frees the
        rejected tail (:meth:`_splice_spec_pages`).

        Under pool pressure, cache pins shed first (same ladder as
        :meth:`_ensure_page_headroom`); if the pool still cannot host a
        window, every page taken for THIS round is returned and the
        caller degrades to a plain paged step — speculation is an
        optimization, and the degradation is bounded (the non-spec paged
        arm), never a truncated live stream."""
        win = self.spec_tokens + 1
        for i in np.flatnonzero(self._active_mask):
            slot = self._slots[i]
            if slot.free:
                continue
            need = spec_scratch_pages(
                int(self._len_host[i]), win, self.page_size,
                self._paged_capacity,
            )
            delta = need - len(slot.pages)
            if delta <= 0:
                continue  # partial-page headroom covers the window
            while not self._allocator.can_alloc(delta):
                if not self._reclaim_cache_pins():
                    break
            if not self._allocator.can_alloc(delta):
                self._rollback_spec_scratch()
                return False
            pids = self._allocator.alloc(delta)
            n0 = len(slot.pages)
            self._spec_scratch[int(i)] = (n0, pids)
            self._table_host[i, n0:n0 + delta] = pids
            self._table_dirty = True
        return True

    def _rollback_spec_scratch(self) -> None:
        """Give back every scratch page of an unresolved round (aborted
        reserve, or a round that died between reserve and splice). The
        table row is REBUILT from the slot's owned pages rather than
        sentinel-stamping the recorded span: between a crashed round and
        this rollback the row may have been rewritten by a plain step's
        headroom growth or a finish + fresh admission at the same index,
        and blind sentinels over that span would silently void a live
        occupant's KV writes (mode=\"drop\") — the corruption class the
        regression test pins. The scratch pages themselves are still
        exclusively round-held (refcount 1, never in ``slot.pages``), so
        the decref is unconditionally correct."""
        for i, (_n0, pids) in self._spec_scratch.items():
            self._table_host[i] = table_array(
                self._slots[i].pages, self._n_table_entries, self.num_pages
            )
            self._table_dirty = True
            self._allocator.decref(pids)
        self._spec_scratch.clear()

    def _splice_spec_pages(self, lengths_host: np.ndarray) -> None:
        """Resolve the round's scratch pages from the verified lengths:
        scratch pages whose table span is covered by the ACCEPTED length
        commit by page-table splice — re-pointed into ``slot.pages``
        with zero KV bytes copied (the entries already name them; the
        accepted tokens' k/v landed there during verify) — and the
        rejected tail frees back to the pool, its table entries reset to
        the sentinel. Each movement is an allocator-journal event
        (``spec_commit``/``spec_reject``), the acceptance signal the
        Perfetto export renders next to ``decode.turn`` spans. Runs
        BEFORE the harvest so a finishing slot frees exactly the pages
        it owns. The dict is drained up front: were an entry to survive
        its own resolution, a later rollback would decref the same
        pages twice."""
        items = list(self._spec_scratch.items())
        self._spec_scratch.clear()
        for i, (n0, pids) in items:
            slot = self._slots[i]
            covered = pages_for(int(lengths_host[i]), self.page_size)
            commit_n = max(0, min(covered - n0, len(pids)))
            committed, rejected = pids[:commit_n], pids[commit_n:]
            if committed:
                slot.pages.extend(committed)
                self._page_journal.record(
                    "spec_commit", len(committed),
                    self._allocator.allocated_pages, slot=int(i),
                )
            if rejected:
                self._table_host[i, n0 + commit_n:n0 + len(pids)] = \
                    self.num_pages
                self._table_dirty = True
                self._allocator.decref(rejected)
                self._page_journal.record(
                    "spec_reject", len(rejected),
                    self._allocator.allocated_pages, slot=int(i),
                )

    def spec_acceptance(self) -> Optional[float]:
        """Rolling draft-token acceptance rate (accepted/drafted over
        the bounded round window), or None before the first round —
        stamped into bench rows and the profiled-acceptance input the
        sim's spec pricing consumes."""
        if not self._spec_acc_window:
            return None
        acc = sum(a for a, _ in self._spec_acc_window)
        drafted = sum(d for _, d in self._spec_acc_window)
        return acc / drafted if drafted else None

    def _spec_step(self) -> None:
        k = self.spec_tokens
        paged_tag = "true" if self.paged else "false"
        if self.paged:
            if self._spec_scratch:
                # A previous round died between reserve and splice (a
                # device error the loop swallowed): its scratch would
                # otherwise leak refcounts forever and shadow-occupy the
                # pool. Roll it back before arranging a fresh window.
                self._rollback_spec_scratch()
            if not self._reserve_spec_scratch():
                # Pool too tight for a verify window this round: one
                # plain paged step instead (its own headroom ladder may
                # capacity-evict, but the spec path never does) — under
                # sustained pressure throughput degrades to the non-spec
                # paged arm, not off a cliff.
                return self._step(horizon=1)
        try:
            # From here to the packed fetch, scratch is armed but
            # unresolved: ANY failure — table upload, sampling-state
            # upload, the dispatch itself — must roll it back NOW, not
            # at the next spec round (there may never be one: a sampled
            # row can pin _use_spec() False for the engine's remaining
            # lifetime, shadow-occupying the pool), then let the loop's
            # error handling see the error.
            if self.paged:
                self._refresh_table()
            (_samp_f, _samp_i, bias_ids_d, bias_vals_d) = \
                self._sampling_arrays()
            self._scan_start_ms = now_ms()
            packed, self._cache, self._dcache = self._spec_fn(
                self.params,
                self._cache,
                self._dcache,
                jnp.asarray(np.stack([
                    self._tokens[:, 0],
                    self._active_mask.astype(np.int32),
                ])),
                bias_ids_d,
                bias_vals_d,
            )
            ph = np.asarray(packed)  # ONE fetch per round  # rdb-lint: disable=host-sync-in-hot-path (THE one fetch per spec round: ph carries tokens+counts+lengths packed)
        except BaseException:
            if self.paged:
                self._rollback_spec_scratch()
            raise
        self._scan_end_ms = now_ms()
        self.interleave_log.append(("turn", k))
        if _tracer().enabled:
            self._record_turn_span(k, self._active_mask, spec=True)
        out = ph[: k + 1]        # [k+1, B]
        n_out = ph[k + 1]        # [B]
        lengths = ph[k + 2]      # [B]
        if self.paged:
            # Accepted prefixes commit by page-table splice, rejected
            # tails free — resolved from the post-round lengths BEFORE
            # the harvest can finish (and free) any slot.
            self._splice_spec_pages(lengths)
        self.steps += 1
        DECODE_STEPS.inc(tags={"model": self.model.name})
        tags = {"model": self.model.name, "paged": paged_tag}
        SPEC_ROUNDS.inc(tags=tags)
        live = np.asarray([
            not slot.free and self._active_mask[i] and n_out[i] > 0
            for i, slot in enumerate(self._slots)
        ])
        active_n = int(self._active_mask.sum())
        drafted = k * active_n
        accepted = int((n_out[live] - 1).sum()) if live.any() else 0
        # Conservation by construction, pinned in tier-1:
        # accepted + rejected == drafted, per round.
        if drafted:
            SPEC_DRAFTED.inc(drafted, tags=tags)
            SPEC_REJECTED.inc(drafted - accepted, tags=tags)
            self._spec_acc_window.append((accepted, drafted))
            rate = self.spec_acceptance()
            if rate is not None:
                SPEC_ACCEPTANCE.set(rate, tags=tags)
        if accepted:  # one summed increment, not one .inc() per slot
            SPEC_ACCEPTED.inc(accepted, tags=tags)
        # Same harvest as the plain scan, with advanced = (j < n_out):
        # a short row is draft rejection, not cache capacity.
        self._harvest(
            out,
            np.arange(k + 1)[:, None] < n_out[None, :],
            lengths,
            k + 1,
            blocked_finishes_capacity=False,
        )

    def _step(self, horizon: Optional[int] = None) -> None:
        if horizon is None and self._use_spec():
            return self._spec_step()
        h = horizon if horizon is not None else self._pick_horizon()
        if self.paged:
            # Pages for every position this scan can write, allocated
            # host-side before the dispatch (static shapes can't grow
            # mid-scan), then one tiny [B, NP] table upload when dirty.
            self._ensure_page_headroom(h)
            self._refresh_table()
        # Per-slot index of the NEXT token to sample (prefill was index 0).
        tok_idx = np.asarray(
            [len(s.generated) if not s.free else 0 for s in self._slots],
            dtype=np.int32,
        )
        prev_tokens = self._tokens.copy()  # draft catch-up window head
        active_at_dispatch = self._active_mask.copy()
        samp_f, samp_i, bias_ids_d, bias_vals_d = self._sampling_arrays()
        self._scan_start_ms = now_ms()
        packed, self._cache, self._counts = self._decode_fn(
            self.params,
            self._cache,
            # ONE per-dispatch upload: tokens / active / sample index.
            jnp.asarray(np.stack([
                self._tokens[:, 0],
                active_at_dispatch.astype(np.int32),
                tok_idx,
            ])),
            h,
            samp_f,
            samp_i,
            bias_ids_d,
            bias_vals_d,
            self._counts,
        )
        packed_host = np.asarray(packed)          # ONE fetch per dispatch  # rdb-lint: disable=host-sync-in-hot-path (THE one fetch per dispatch: packed carries tokens+advanced+lengths)
        self._scan_end_ms = now_ms()
        if active_at_dispatch.any():
            self.interleave_log.append(("turn", h))
        if _tracer().enabled and active_at_dispatch.any():
            self._record_turn_span(h, active_at_dispatch)
        toks_host = packed_host[:h]               # [h, B]
        advanced_host = packed_host[h : 2 * h].astype(bool)   # [h, B]
        lengths_host = packed_host[2 * h]         # [B] (post-horizon)
        self.steps += h
        DECODE_STEPS.inc(h, tags={"model": self.model.name})
        if self._dcache is not None:
            # Keep the DRAFT cache tracking the sequence through plain
            # decode intervals (sampled-row fallback, inter-chunk steps):
            # without this, speculation resumes from a stale draft context
            # and acceptance collapses. The tokens whose k/v landed at
            # positions [len, len+h) are [pending, emitted[:-1]].
            window = np.concatenate(
                [prev_tokens, toks_host[: h - 1].T], axis=1
            )  # [B, h]
            counts = advanced_host.sum(axis=0).astype(np.int32)
            self._dcache = self._draft_catchup_fn(
                self.draft_params,
                self._dcache,
                jnp.asarray(window),
                jnp.asarray(active_at_dispatch),
                jnp.asarray(counts),
            )
        self._harvest(toks_host, advanced_host, lengths_host, h)

    def _harvest(self, toks_host, advanced_host, lengths_host, h: int,
                 blocked_finishes_capacity: bool = True) -> None:
        """Distribute a scan's [h, B] outputs to their slots.

        Vectorized: at 64 slots x a 32-substep horizon the former
        per-token Python loop executed ~2k interpreter iterations per
        dispatch — pure host overhead on a chip whose dispatch cadence is
        a few ms. Here numpy computes, per slot, how many tokens to
        accept and which finish fires, with the SAME semantics as the
        scalar loop it replaced: a non-advanced substep finishes
        "capacity" (cache was full at entry, no token), a stop token is
        accepted then finishes "eos", the max_new bound accepts its last
        token then finishes "length" — and at equal accepted counts the
        scalar loop's check order makes eos beat length beat capacity.
        Tokens append in bulk; only requests that actually stream pay a
        per-token push. Substeps after EOS/stop decoded garbage into the
        slot's cache tail; prefill overwrites the row on reuse.

        ``blocked_finishes_capacity``: in a plain scan a non-advanced
        substep means the cache was full — finish "capacity". The
        speculative path reuses this harvest with advanced = (j < n_out),
        where a short row means DRAFT REJECTION, not capacity: there only
        n_out == 0 (no room for even the target's own token) finishes,
        plus the shared trailing max_len check.
        """
        active_idx = [
            i for i, slot in enumerate(self._slots)
            if not slot.free and self._active_mask[i]
        ]
        if not active_idx:
            return
        cols = np.asarray(active_idx, dtype=np.int64)  # rdb-lint: disable=host-sync-in-hot-path (host-built python index list, no device value)
        # Host mirror of cache lengths (page-headroom math + the
        # kv_occupancy metric); finished slots re-zero in _finish.
        self._len_host[cols] = lengths_host[cols]
        toks = toks_host[:, cols]          # [h, n]
        adv = advanced_host[:, cols]       # [h, n]
        # First non-advanced substep (h if every substep advanced).
        blocked = ~adv
        cap_at = np.where(
            blocked.any(axis=0), blocked.argmax(axis=0), h
        )
        # First stop token: the shared EOS id vectorized; per-request
        # extra stop ids (rare) OR-ed in per column.
        if self.eos_token_id is not None:
            stop_mask = toks == self.eos_token_id
        else:
            stop_mask = np.zeros_like(adv)
        for c, i in enumerate(active_idx):
            extra = self._slots[i].stop
            if extra:
                stop_mask[:, c] |= np.isin(
                    toks[:, c], np.fromiter(extra, dtype=np.int64)
                )
        stop_take = np.where(
            stop_mask.any(axis=0), stop_mask.argmax(axis=0) + 1, h + 1
        )
        len_take = np.asarray([
            max(0, self._slots[i].max_new_tokens
                - len(self._slots[i].generated))
            for i in active_idx
        ])
        accepted = np.minimum.reduce([
            cap_at, stop_take, len_take, np.full_like(cap_at, h),
        ])
        for c, i in enumerate(active_idx):
            slot = self._slots[i]
            acc = int(accepted[c])
            if acc > 0:
                new_toks = toks[:acc, c].tolist()
                slot.generated.extend(new_toks)
                slot.last_token = new_toks[-1]
                self._tokens[i, 0] = new_toks[-1]
                if slot.request.stream is not None:
                    for tok in new_toks:
                        slot.request.stream_put(tok)
            if stop_take[c] == accepted[c]:
                self._finish(i, "eos")
            elif len_take[c] == accepted[c]:
                self._finish(i, "length")
            elif cap_at[c] == accepted[c] and (
                cap_at[c] < h if blocked_finishes_capacity
                else cap_at[c] == 0
            ):
                # A genuinely blocked substep (cache full at entry) —
                # cap_at == h just means every substep advanced.
                self._finish(i, "capacity")
            elif lengths_host[i] >= self.max_len:
                self._finish(i, "capacity")

    # --- page fabric (live stream migration + prefix push) -----------------
    def live_stream_ids(self) -> List[str]:
        """Request ids of migration-eligible streams: slotted, past
        their first token, not mid-chunked-prefill (trains hold no
        emitted tokens yet, so they are requeue-safe under the
        at-most-once-after-first-token pin and drain the old way).
        Benign-racy read for planners; eligibility is re-checked on the
        engine thread at service time."""
        out: List[str] = []
        for i, s in enumerate(self._slots):
            if s.free or s.request is None or i in self._train_slots:
                continue
            if s.generated:
                out.append(s.request.request_id)
        return out

    def request_migration(
        self, request_id: str,
        deliver: Callable[[PageParcel], bool],
    ) -> bool:
        """Thread-safe: ask this engine to migrate ``request_id`` out
        through ``deliver`` at its next between-turns service point.
        ``deliver`` is invoked ON the engine thread with the frozen
        parcel and must return True only once the destination accepted
        it; the slot is committed (freed without fulfil) on True and
        left decoding untouched on False/raise. Returns False if the
        stream is not live here (advisory — a stream that finishes
        before service is simply skipped, duplicates are harmless)."""
        if not self.paged:
            return False
        live = any(
            (not s.free) and s.request is not None
            and s.request.request_id == request_id
            for s in self._slots
        )
        if not live:
            return False
        with self._fabric_lock:
            self._migrate_out_q.append((request_id, deliver))
        return True

    def request_prefix_push(
        self, key: bytes, deliver: Callable[[PageParcel], bool],
    ) -> bool:
        """Thread-safe: export prefix-cache entry ``key`` as a push
        parcel through ``deliver`` at the next service point (skipped
        if evicted by then)."""
        if not self.paged or self.paged_prefix is None:
            return False
        with self._fabric_lock:
            self._push_out_q.append((key, deliver))
        return True

    def accept_parcel(self, parcel: PageParcel) -> bool:
        """Thread-safe destination half of the courier edge: admission-
        check ``parcel`` and enqueue it for import on the engine thread.
        The checks are ADVISORY (the free-pages read races the engine
        thread benignly); the import path keeps its own OOM fallback
        chain (reclaim cache pins -> capacity-truncate), so a stale
        accept is honest, never corrupting. A False return leaves the
        source slot untouched — it simply resumes decoding."""
        if not self.paged or parcel.page_size != self.page_size:
            return False
        if parcel.kind == STREAM:
            if parcel.resume_len > self.max_len:
                return False
            s = parcel.sampling
            if (float(s.get("temperature", 0.0)) > 0.0
                    and int(s.get("base_seed", -1)) != self.base_seed):
                # Sampled rows only resume byte-identically under the
                # same engine-level PRNG base key; greedy rows never
                # consult it.
                return False
            with self._fabric_lock:
                pending = [p for p in self._parcel_in_q
                           if p.kind == STREAM]
                free_slots = sum(
                    1 for i, sl in enumerate(self._slots)
                    if sl.free and i not in self._train_slots
                )
                if len(pending) + 1 > free_slots:
                    return False
                pend_pages = sum(p.n_pages for p in pending)
                if not self._allocator.can_alloc(
                        pend_pages + parcel.n_pages):
                    return False
                self._parcel_in_q.append(parcel)
            return True
        # Prefix pushes are speculative: admission only rejects the
        # impossible (bigger than the pool); a tight pool skips the
        # install at import time rather than deepening pressure.
        if parcel.n_pages > self.num_pages:
            return False
        with self._fabric_lock:
            self._parcel_in_q.append(parcel)
        return True

    def _fabric_pending(self) -> bool:
        if not self.paged:
            return False
        with self._fabric_lock:
            return bool(self._parcel_in_q or self._migrate_out_q
                        or self._push_out_q)

    def _service_fabric(self) -> None:
        """Engine thread, between decode turns: drain the parcel
        mailboxes and process them. Pops under the rank-100 fabric lock
        into locals FIRST, then processes unlocked — the handlers call
        into queue accounting (rank 80) and request futures (rank 90),
        which must never nest under rank 100."""
        if not self.paged:
            return
        with self._fabric_lock:
            if not (self._parcel_in_q or self._migrate_out_q
                    or self._push_out_q):
                return
            inbound, self._parcel_in_q = self._parcel_in_q, []
            moves, self._migrate_out_q = self._migrate_out_q, []
            pushes, self._push_out_q = self._push_out_q, []
        for parcel in inbound:
            self._import_parcel(parcel)
        for rid, deliver in moves:
            self._migrate_stream_out(rid, deliver)
        for key, deliver in pushes:
            self._push_prefix_out(key, deliver)

    def _migrate_stream_out(
        self, request_id: str,
        deliver: Callable[[PageParcel], bool],
    ) -> None:
        """Freeze -> deliver -> commit. The export is read-only and the
        slot is torn down only AFTER the courier acknowledged delivery,
        so every failure mode (courier death, partition mid-parcel,
        destination refusal) leaves the stream decoding here as if the
        directive never arrived."""
        idx = None
        for i, s in enumerate(self._slots):
            if (not s.free and s.request is not None
                    and s.request.request_id == request_id
                    and i not in self._train_slots and s.generated):
                idx = i
                break
        if idx is None:
            return  # finished/moved since requested — nothing to do
        slot = self._slots[idx]
        req = slot.request
        parcel = export_stream_parcel(self, idx)
        ok = False
        try:
            ok = bool(deliver(parcel))
        except Exception:  # noqa: BLE001 — courier faults must not kill the stream
            logger.exception(
                "%s: migrate_out delivery failed for %s",
                self.model.name, request_id,
            )
        if not ok:
            return
        # Commit: the destination owns the stream now. Mirror _finish's
        # slot/sampling reset WITHOUT fulfil or completion accounting —
        # the same TokenStream keeps flowing from the new engine, and
        # note_migrated_out closes this queue's books instead.
        self._page_journal.record(
            "migrate_out", parcel.n_pages,
            self._allocator.allocated_pages,
            slot=int(idx), bytes=parcel.nbytes, request=request_id,
        )
        self.queue.note_migrated_out(req)
        self._free_slot_pages(idx)
        self._slots[idx] = _Slot()
        self._active_mask[idx] = False
        self._temps[idx] = 0.0
        self._topk[idx] = 0
        self._topp[idx] = 1.0
        self._seeds[idx] = 0
        self._bias_ids[idx] = 0
        self._bias_vals[idx] = 0.0
        self._pres[idx] = 0.0
        self._freq[idx] = 0.0
        self.migrated_out += 1

    def _import_parcel(self, parcel: PageParcel) -> None:
        if parcel.kind == PREFIX:
            self._install_prefix(parcel)
            return
        idx = None
        for i, s in enumerate(self._slots):
            if s.free and i not in self._train_slots:
                idx = i
                break
        need = parcel.n_pages
        if idx is not None:
            while not self._allocator.can_alloc(need):
                # Accepted capacity evaporated (admissions raced the
                # courier): cache pins are optimizations, inbound live
                # streams are not.
                if not self._reclaim_cache_pins():
                    break
        if idx is None or not self._allocator.can_alloc(need):
            # OOM-after-accept last resort: a complete-but-truncated
            # result — the same honest contract as cache exhaustion.
            self._fulfill_truncated(parcel)
            return
        pages = self._allocator.alloc(need) if need else []
        if parcel.payload:
            self._write_pages(pages, parcel.payload)
        self._register_migrated(idx, parcel, pages)
        self._page_journal.record(
            "migrate_in", need, self._allocator.allocated_pages,
            slot=int(idx), bytes=parcel.nbytes,
            request=parcel.request.request_id,
        )
        self.queue.note_migrated_in(parcel.request)
        self.migrated_in += 1

    def _register_migrated(
        self, slot_idx: int, parcel: PageParcel, pages: List[int],
    ) -> None:
        """Splice an imported stream into ``slot_idx`` and resume it.
        The _register variant for a stream that already emitted tokens:
        no stream_put / TTFT / prefill accounting (all happened at the
        source), device ``lengths`` set explicitly (normally the
        prefill program's job), penalty counts reconstructed from the
        generated list (the counts row of a live slot equals
        ``bincount(generated)`` — the scan counts only tokens it
        sampled plus the register-counted first token, and a live slot
        kept every one of them)."""
        slot = self._slots[slot_idx]
        slot.request = parcel.request
        slot.generated = list(parcel.generated)
        slot.max_new_tokens = parcel.max_new_tokens
        slot.prefill_done_ms = parcel.prefill_done_ms
        slot.last_token = int(parcel.generated[-1])
        slot.stop = parcel.stop
        slot.session_id = parcel.session_id
        slot.prompt_tokens = parcel.prompt_tokens
        slot.pages = list(pages)
        slot.shared_pages = 0
        self._len_host[slot_idx] = int(parcel.cache_len)
        self._table_host[slot_idx] = table_array(
            slot.pages, self._n_table_entries, self.num_pages
        )
        self._table_dirty = True
        self._tokens[slot_idx, 0] = slot.last_token
        self._active_mask[slot_idx] = True
        s = parcel.sampling
        self._temps[slot_idx] = float(s.get("temperature", 0.0))
        self._topk[slot_idx] = int(s.get("top_k", 0))
        self._topp[slot_idx] = float(s.get("top_p", 1.0))
        self._seeds[slot_idx] = int(s.get("seed", 0))
        self._bias_ids[slot_idx] = np.asarray(s["bias_ids"]) \
            if "bias_ids" in s else 0
        self._bias_vals[slot_idx] = np.asarray(s["bias_vals"]) \
            if "bias_vals" in s else 0.0
        self._pres[slot_idx] = float(s.get("presence_penalty", 0.0))
        self._freq[slot_idx] = float(s.get("frequency_penalty", 0.0))
        self._sampling_dev = None  # host arrays changed
        with self._device_ctx():
            self._cache = self._cache.replace(
                lengths=self._cache.lengths.at[slot_idx].set(
                    int(parcel.cache_len)
                )
            )
            if self._pres[slot_idx] or self._freq[slot_idx]:
                vocab = int(self._counts.shape[1])
                row = np.bincount(
                    np.asarray(parcel.generated, np.int64) % vocab,
                    minlength=vocab,
                )[:vocab].astype(np.int32)
                self._counts = self._counts.at[slot_idx].set(
                    jnp.asarray(row)
                )

    def _fulfill_truncated(self, parcel: PageParcel) -> None:
        """Destination-OOM fallback after accept: resolve the stream as
        complete-but-truncated instead of stranding it (the source
        already committed the hand-off and cannot take it back)."""
        req = parcel.request
        t = now_ms()
        self.queue.note_migrated_in(req)
        req.fulfill(DecodeResult(
            tokens=list(parcel.generated),
            finish_reason="capacity",
            ttft_ms=parcel.prefill_done_ms - req.arrival_ms,
            total_ms=t - req.arrival_ms,
        ))
        self.queue.record_batch_completion([req], completed_at_ms=t)
        self.completed += 1
        logger.warning(
            "%s: migrated-in stream %s capacity-truncated "
            "(destination OOM after accept)",
            self.model.name, req.request_id,
        )

    def _push_prefix_out(
        self, key: bytes, deliver: Callable[[PageParcel], bool],
    ) -> None:
        parcel = export_prefix_parcel(self, key)
        if parcel is None:
            return  # evicted between planning and export
        ok = False
        try:
            ok = bool(deliver(parcel))
        except Exception:  # noqa: BLE001 — a failed push costs nothing
            logger.exception(
                "%s: prefix push delivery failed", self.model.name
            )
        if not ok:
            return
        self._page_journal.record(
            "push_out", parcel.n_pages,
            self._allocator.allocated_pages, bytes=parcel.nbytes,
        )
        self.pushes_out += 1

    def _install_prefix(self, parcel: PageParcel) -> None:
        """Install a pushed prefix parcel digest-direct: alloc, write,
        publish under the parcel's chain address. Skips duplicates and
        tight pools (a speculative warm must never evict local state to
        make room for itself)."""
        cache = self.paged_prefix
        if cache is None or not parcel.digest:
            return
        if parcel.digest in cache._entries:
            return
        need = parcel.n_pages
        if not self._allocator.can_alloc(need):
            return
        pages = self._allocator.alloc(need)
        self._write_pages(pages, parcel.payload)
        if cache.install(parcel.digest, pages):
            self._page_journal.record(
                "push_in", need, self._allocator.allocated_pages,
                bytes=parcel.nbytes,
            )
            self.pushes_in += 1
        # Pin symmetry: install increfs for the cache; drop the alloc's
        # own hold (pages free immediately on the losing race branch).
        self._allocator.decref(pages)

    # --- loop --------------------------------------------------------------
    def run_until_idle(self, timeout_s: float = 60.0) -> None:
        """Drive admissions + steps until queue and slots are empty (tests,
        offline batch generation)."""
        deadline = time.monotonic() + timeout_s
        with self._device_ctx():
            while time.monotonic() < deadline:
                self._service_fabric()
                admitted = self._admit()
                self._pump_prefill()
                if self._active_mask.any():
                    self._step()
                elif (not admitted and not self._trains
                        and len(self.queue) == 0
                        and not self._fabric_pending()):
                    return
        raise TimeoutError(f"{self.model.name}: decode did not drain")

    def _loop(self) -> None:
        with self._device_ctx():
            while self._run.is_set():
                try:
                    self._service_fabric()
                    self._admit()
                    self._pump_prefill()
                    if self._active_mask.any():
                        self._step()
                        ACTIVE_SLOTS.set(
                            float(self._active_mask.sum()),
                            tags={"model": self.model.name},
                        )
                        if self.paged:
                            KV_PAGES_FREE.set(
                                float(self._allocator.free_pages),
                                tags={"model": self.model.name},
                            )
                            KV_PAGE_OCCUPANCY.set(
                                self._allocator.allocated_pages
                                / self.num_pages,
                                tags={"model": self.model.name},
                            )
                    elif not self._trains:
                        self.queue.wait_for_requests(self.idle_wait_s)
                    self.last_heartbeat = time.monotonic()
                except Exception:  # noqa: BLE001 — engine must not die silently
                    logger.exception(
                        "%s: decode loop iteration failed", self.model.name
                    )
                    time.sleep(0.05)  # rdb-lint: disable=event-loop-blocking (decode-loop error backoff on the engine's own thread)

    def release_buffers(self) -> None:
        """Drop the engine's HBM footprint (cache + params + compiled fns)
        so a replacement replica can reuse the chip. Call only after the
        loop has stopped; the engine is unusable afterwards."""
        self._cache = None
        self.params = None
        self._prefill_fns.clear()
        self._decode_fn = None
        self._counts = None
        self._zero_counts_fn = None
        self._sampling_dev = None
        self._dcache = None
        if self.draft_model is not None:
            self.draft_params = None
            self._spec_fn = None
            self._draft_catchup_fn = None
        if self.prefix_cache is not None:
            self.prefix_cache.clear()  # device k/v entries freed on GC
        if self.session_cache is not None:
            self.session_cache.clear()
        if self.paged:
            # Drop cache pins first (clean decrefs), then the pool state.
            if self.paged_prefix is not None:
                self.paged_prefix.clear()
            if self.paged_sessions is not None:
                self.paged_sessions.clear()
            if self.host_spill is not None:
                self.host_spill.clear()  # host copies die with the pool
            self._allocator = None
            self._table_host = None

    def abort_active(self, exc: Exception) -> None:
        """Reject every request still occupying a slot (replica shutdown:
        in-flight sequences must not leave futures/streams hanging). Call
        only after the loop has stopped."""
        for i, slot in enumerate(self._slots):
            if not slot.free and slot.request is not None:
                slot.request.reject(exc)
                if self.paged and self._allocator is not None:
                    self._free_slot_pages(i)
                self._slots[i] = _Slot()
                self._active_mask[i] = False
        # Chunk trains are in-flight requests too (slot held, pages
        # granted, no tokens yet): reject + release, never strand.
        for train in list(self._trains):
            train.req.reject(exc)
            if self._allocator is not None:
                self._release_pages(train.opts)
        self._trains.clear()
        self._train_slots.clear()
        # Accepted-but-unimported inbound parcels hold live streams the
        # SOURCE already released (note_migrated_out closed its books);
        # reject them too — they entered no books here, so conservation
        # holds on both sides.
        if self.paged:
            with self._fabric_lock:
                inbound, self._parcel_in_q = self._parcel_in_q, []
                self._migrate_out_q.clear()
                self._push_out_q.clear()
            for parcel in inbound:
                if parcel.kind == STREAM and parcel.request is not None:
                    parcel.request.reject(exc)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._run.set()
        self._thread = threading.Thread(
            target=self._loop, name=f"decode-{self.model.name}", daemon=True
        )
        self._thread.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        self._run.clear()
        if self._thread is not None:
            self._thread.join(timeout_s)
            if self._thread.is_alive():
                # Wedged in a device call: leave the handle so callers can
                # see the thread still lives (buffer release must not happen
                # under it).
                logger.warning(
                    "%s: loop thread did not exit within %.1fs",
                    self.model.name, timeout_s,
                )
            else:
                self._thread = None

    def kv_occupancy(self) -> float:
        """Useful fraction of RESERVED KV positions — the decode
        slot-occupancy metric the paged pool exists to raise. A slab
        engine reserves ``num_slots * max_len`` up front (a slot's tail
        tokens hold a whole slab whether it caches 3 tokens or 300); a
        paged engine reserves only allocated pages, so at equal traffic
        its value is >= the slab configuration's by construction —
        pinned by the paged-vs-slab engine test. 1.0 when nothing is
        reserved."""
        used = float(self._len_host.sum())
        if self.paged:
            reserved = float(
                self._allocator.allocated_pages * self.page_size
            ) if self._allocator is not None else 0.0
        else:
            reserved = float(self.num_slots * self.max_len)
        return used / reserved if reserved > 0 else 1.0

    def snapshot(self) -> Dict[str, Any]:
        """Operator-facing state dump (the engine analogue of
        ``LiveScheduler.snapshot()``): slot/KV occupancy plus — in paged
        mode — the allocator event journal (bounded ring; ``events``
        carries the retained tail, ``journal_total``/``journal_rotated``
        say how much history the ring has seen/shed, so a consumer can
        tell a quiet pool from a ring that wrapped). The journal feeds
        ``utils/trace_export.to_chrome_trace(spans, journal=...)`` for a
        Perfetto lane time-aligned with decode-turn spans."""
        out: Dict[str, Any] = {
            "model": self.model.name,
            "paged": self.paged,
            "num_slots": self.num_slots,
            "active_slots": self.active_slots,
            "kv_occupancy": self.kv_occupancy(),
            "ttft": self.ttft_breakdown(),
            "prefill": {
                "mode": "chunked" if self.chunked_prefill else "mono",
                "token_budget": self.prefill_token_budget,
                "pending_trains": len(self._trains),
            },
        }
        if self.paged:
            out["page_size"] = self.page_size
            out["num_pages"] = self.num_pages
            out["free_pages"] = self._allocator.free_pages
            out["allocated_pages"] = self._allocator.allocated_pages
            out["page_journal"] = {
                "events": self._page_journal.snapshot(),
                "journal_total": self._page_journal.total,
                "journal_rotated": self._page_journal.rotated_out,
            }
            out["fabric"] = {
                "migrated_out": self.migrated_out,
                "migrated_in": self.migrated_in,
                "pushes_out": self.pushes_out,
                "pushes_in": self.pushes_in,
            }
        if self.draft_model is not None:
            out["spec"] = {
                "spec_tokens": self.spec_tokens,
                "acceptance": self.spec_acceptance(),
                "rounds_windowed": len(self._spec_acc_window),
            }
        return out

    @property
    def active_slots(self) -> int:
        return int(self._active_mask.sum())

    @property
    def busy(self) -> bool:
        """Work in flight: active slots OR requests mid-admission
        (dequeued but not yet slotted — invisible to both queue depth
        and ``active_slots``; drain logic that ignores this window
        aborts requests seconds from their first token). Accepted-but-
        unimported inbound parcels count too: the source already
        committed the hand-off."""
        return (self._admitting > 0 or bool(self._trains)
                or bool(self._active_mask.any())
                or self._fabric_pending())
