"""Model hosting: load/unload model params with HBM accounting.

Replaces the reference's in-actor model registry loading
(``293-project/src/scheduler.py:374-421`` torchvision → ``cuda:0``,
``:499-515`` unload via ``cpu()+del+empty_cache`` / load on hot-swap).
On TPU there is no allocator cache to flush: params are device arrays; when
the last reference drops, XLA frees the HBM. Loading restores from an orbax
checkpoint when one exists, else initializes from seed (the reference's
"reload from registry" behavior).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, Optional, Tuple

import jax

from ray_dynamic_batching_tpu.models.base import ServableModel, get_model
from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("host")


class ModelHost:
    """Reference-counted (model → params) cache for one process."""

    def __init__(self, checkpoint_dir: Optional[str] = None, seed: int = 0,
                 model_kwargs: Optional[Dict[str, Dict[str, Any]]] = None):
        from ray_dynamic_batching_tpu.utils.compile_cache import maybe_enable

        maybe_enable()  # repeat bucket compiles become disk hits
        self.checkpoint_dir = checkpoint_dir
        self.seed = seed
        self.model_kwargs = model_kwargs or {}
        self._loaded: Dict[str, Tuple[ServableModel, Any]] = {}
        self._refcounts: Dict[str, int] = {}
        self._lock = threading.Lock()

    def _load_params(self, model: ServableModel):
        if self.checkpoint_dir:
            path = os.path.join(self.checkpoint_dir, model.name)
            if os.path.isdir(path):
                try:
                    import orbax.checkpoint as ocp

                    ckptr = ocp.StandardCheckpointer()
                    abstract = jax.eval_shape(
                        lambda: model.init(jax.random.PRNGKey(self.seed))
                    )
                    params = ckptr.restore(os.path.abspath(path), abstract)
                    logger.info("%s: restored checkpoint from %s", model.name, path)
                    return params
                except Exception as e:  # noqa: BLE001
                    logger.warning(
                        "%s: checkpoint restore failed (%s); initializing",
                        model.name, e,
                    )
        return model.init(jax.random.PRNGKey(self.seed))

    def acquire(self, name: str) -> Tuple[ServableModel, Any]:
        """Load (or re-reference) a model; returns (model, params)."""
        with self._lock:
            if name in self._loaded:
                self._refcounts[name] += 1
                return self._loaded[name]
        model = get_model(name, **self.model_kwargs.get(name, {}))
        params = self._load_params(model)
        with self._lock:
            if name not in self._loaded:  # lost no race: idempotent either way
                self._loaded[name] = (model, params)
                self._refcounts[name] = 0
            self._refcounts[name] += 1
            return self._loaded[name]

    def release(self, name: str) -> None:
        """Drop one reference; frees HBM when the last holder releases."""
        with self._lock:
            if name not in self._refcounts:
                return
            self._refcounts[name] -= 1
            if self._refcounts[name] <= 0:
                del self._loaded[name]
                del self._refcounts[name]
                logger.info("%s: unloaded (HBM freed on GC)", name)

    def loaded_models(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._refcounts)

    def save_checkpoint(self, name: str, out_dir: Optional[str] = None) -> str:
        """Persist params with orbax (control-plane checkpoint/resume story)."""
        import orbax.checkpoint as ocp

        with self._lock:
            if name not in self._loaded:
                raise KeyError(f"{name} not loaded")
            _, params = self._loaded[name]
        base = out_dir or self.checkpoint_dir
        if base is None:
            raise ValueError("no checkpoint_dir configured")
        path = os.path.abspath(os.path.join(base, name))
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(path, params, force=True)
        ckptr.wait_until_finished()
        return path
