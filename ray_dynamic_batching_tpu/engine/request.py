"""Request object flowing through queues to the replica engine.

Analogue of the reference's ``BatchRequest``
(``293-project/src/scheduler.py:181-188``: request_id, data, arrival_time,
SLO). Result delivery is a ``concurrent.futures.Future`` so the asyncio
ingress can await it while the engine hot loop stays a plain thread.
"""

from __future__ import annotations

import itertools
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

_req_counter = itertools.count(1)


def now_ms() -> float:
    return time.monotonic() * 1000.0


@dataclass
class Request:
    model: str
    payload: Any                      # model-specific input (np arrays, tokens)
    slo_ms: float
    request_id: str = ""
    arrival_ms: float = field(default_factory=now_ms)
    seq_len: int = 0                  # shape bucket hint for LLM inputs
    future: Future = field(default_factory=Future)
    trace_ctx: Dict[str, Any] = field(default_factory=dict)

    def __post_init__(self) -> None:
        if not self.request_id:
            self.request_id = f"{self.model}-{next(_req_counter)}"

    @property
    def deadline_ms(self) -> float:
        return self.arrival_ms + self.slo_ms

    def queue_delay_ms(self, now: Optional[float] = None) -> float:
        return (now if now is not None else now_ms()) - self.arrival_ms

    def reject(self, exc: Exception) -> None:
        if not self.future.done():
            self.future.set_exception(exc)

    def fulfill(self, result: Any) -> None:
        if not self.future.done():
            self.future.set_result(result)


class RequestDropped(Exception):
    """Raised into a request's future when the queue drops it."""


class RequestStale(Exception):
    """Raised when a request cannot meet its deadline and is discarded
    (staleness discard, ref scheduler.py:281-283)."""
