"""Request object flowing through queues to the replica engine.

Analogue of the reference's ``BatchRequest``
(``293-project/src/scheduler.py:181-188``: request_id, data, arrival_time,
SLO). Result delivery is a ``concurrent.futures.Future`` so the asyncio
ingress can await it while the engine hot loop stays a plain thread.

Streaming delivery (ref generator batches, ``serve/batching.py:209-276``,
and the streaming replica path, ``serve/_private/replica.py:515-544``) rides
a :class:`TokenStream`: the producer (decode engine / generator callable)
pushes chunks as they exist, the consumer (proxy, client) iterates them
before the request completes. The future still resolves with the final
result, so non-streaming callers are unaffected.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

from ray_dynamic_batching_tpu.utils.concurrency import OrderedLock

_req_counter = itertools.count(1)


def now_ms() -> float:
    return time.monotonic() * 1000.0


# --- QoS classes (Shepherd-style priority tiers, ROADMAP item 4) -----------
# Rank orders DEQUEUE priority (lower = served first) and the inverse shed
# order (highest rank sheds first). Weights price an SLO miss for the
# planner's weighted attainment (scheduler/replan.weighted_attainment):
# an interactive miss costs 4x a best-effort one.
QOS_CLASSES = ("interactive", "standard", "best_effort")
QOS_RANK = {"interactive": 0, "standard": 1, "best_effort": 2}
QOS_WEIGHTS = {"interactive": 4.0, "standard": 2.0, "best_effort": 1.0}
DEFAULT_QOS_CLASS = "standard"
DEFAULT_TENANT = "default"


def normalize_qos(value: Optional[str]) -> str:
    """Validate a client-supplied class name; unknown values are the
    CLIENT's fault (BadRequest -> 4xx), never a silent default — a typo'd
    'interactve' must not quietly serve at best-effort shed priority."""
    if value is None or value == "":
        return DEFAULT_QOS_CLASS
    if value not in QOS_RANK:
        raise BadRequest(
            f"unknown qos_class {value!r} (one of: {', '.join(QOS_CLASSES)})"
        )
    return value


class StreamClosed(Exception):
    """Raised by :meth:`TokenStream.get` after close + drain."""


class TokenStream:
    """Single-producer single-consumer chunk stream with blocking reads.

    The producer calls :meth:`put` per chunk and exactly one of
    :meth:`close` / :meth:`abort`; the consumer iterates (or calls
    :meth:`get`) and stops at close. Thread-safe; the hot producer path is
    one lock acquire + notify.
    """

    def __init__(self, max_buffer: int = 4096) -> None:
        self._chunks: deque = deque()
        self._lock = OrderedLock("token_stream")
        self._cond = threading.Condition(self._lock)
        self._closed = False
        self._error: Optional[Exception] = None
        self._on_chunk = None
        self._on_close = None
        self.max_buffer = max_buffer
        # Chunks actually delivered toward the consumer (buffered or pushed).
        # The failover layer reads this to enforce at-most-once-after-first-
        # token: a streaming request that already emitted a chunk must NOT
        # be transparently retried — the client has observed partial output.
        self.emitted = 0
        # Invoked exactly once, on the emitted 0 -> 1 edge (producer
        # thread, outside the lock). The hedge race claims first-winner
        # here: the instant a primary emits its first token, the hedge
        # is cancelled — the at-most-once-after-first-token boundary,
        # enforced at the token source rather than by polling. A hook
        # that returns ``False`` VETOES delivery of the triggering chunk
        # (the producer lost the claim while this chunk was in flight;
        # ``emitted`` still counts it — the winner's grafted chunks keep
        # the stream's observed-output contract honest).
        self.on_first_emit = None

    def put(self, chunk: Any, drop_if=None) -> None:
        """``drop_if`` (checked under the lock, at entry AND delivery)
        lets a producer make its own suppression atomic with delivery:
        the hedge loser passes its ``cancelled`` flag so a chunk that
        passed an earlier check cannot land after the race resolves."""
        first_emit_cb = None
        with self._cond:
            if self._closed:
                return  # consumer gone / finished — drop quietly
            if drop_if is not None and drop_if():
                return  # producer suppressed (lost the hedge race)
            self.emitted += 1
            if self.emitted == 1 and self.on_first_emit is not None:
                first_emit_cb = self.on_first_emit
        if first_emit_cb is not None:
            if first_emit_cb() is False:
                return  # race hook vetoed this producer's chunk
        with self._cond:
            if self._closed:
                return  # the first-emit hook may have closed us
            if drop_if is not None and drop_if():
                return  # race resolved against this producer mid-put
            if self._on_chunk is not None:
                cb = self._on_chunk
            else:
                if len(self._chunks) >= self.max_buffer:
                    # Slow consumer: drop oldest (token streams are advisory;
                    # the future still carries the complete result).
                    self._chunks.popleft()
                self._chunks.append(chunk)
                self._cond.notify()
                return
        cb(chunk)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            cb = self._on_close
        if cb is not None:
            cb(None)

    def abort(self, exc: Exception) -> None:
        with self._cond:
            self._error = exc
            self._closed = True
            self._cond.notify_all()
            cb = self._on_close
        if cb is not None:
            cb(exc)

    def subscribe(self, on_chunk, on_close) -> None:
        """Switch to push delivery: buffered chunks replay immediately, then
        the producer invokes ``on_chunk(chunk)`` inline per put and exactly
        one ``on_close(error_or_None)`` at the end. Callbacks must be cheap
        and thread-safe (they run on the producer thread) — an asyncio
        consumer bridges with ``loop.call_soon_threadsafe``. This removes
        the blocked-reader thread a pull consumer would need."""
        with self._cond:
            # Backlog replays while the lock is held, BEFORE inline delivery
            # becomes visible to put() — otherwise a concurrent put could
            # deliver a new chunk ahead of older buffered ones.
            for c in self._chunks:
                on_chunk(c)
            self._chunks.clear()
            self._on_chunk = on_chunk
            self._on_close = on_close
            closed, err = self._closed, self._error
        if closed:
            on_close(err)

    def get(self, timeout_s: Optional[float] = None) -> Any:
        """Next chunk; raises :class:`StreamClosed` when drained+closed,
        ``TimeoutError`` on timeout, or the producer's abort error."""
        deadline = (
            time.monotonic() + timeout_s if timeout_s is not None else None
        )
        with self._cond:
            while True:
                if self._chunks:
                    return self._chunks.popleft()
                if self._closed:
                    if self._error is not None:
                        raise self._error
                    raise StreamClosed()
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    raise TimeoutError("TokenStream.get timed out")
                self._cond.wait(remaining)

    def __iter__(self) -> Iterator[Any]:
        while True:
            try:
                yield self.get()
            except StreamClosed:
                return

    def drain(self, timeout_s: float = 10.0) -> List[Any]:
        """Collect every chunk until close (tests / non-incremental readers)."""
        out: List[Any] = []
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                out.append(self.get(timeout_s=deadline - time.monotonic()))
            except StreamClosed:
                return out


@dataclass
class Request:
    model: str
    payload: Any                      # model-specific input (np arrays, tokens)
    slo_ms: float
    request_id: str = ""
    arrival_ms: float = field(default_factory=now_ms)
    # Stamped by the decode engine when the request is dequeued into a slot
    # (TTFT = [arrival..admit: queue/scan wait] + [admit..first token:
    # prefill]); None until an engine admits it.
    admit_ms: Optional[float] = None
    # Stamped by RequestQueue.add_request on the LAST enqueue (a request can
    # be requeued on router retry / slot starvation): the queue-wait span
    # measures from here, not from arrival — routing time is its own hop.
    enqueue_ms: Optional[float] = None
    seq_len: int = 0                  # shape bucket hint for LLM inputs
    future: Future = field(default_factory=Future)
    trace_ctx: Dict[str, Any] = field(default_factory=dict)
    # Present iff the caller asked for incremental delivery; producers that
    # don't stream simply never touch it (future-only contract unchanged).
    stream: Optional[TokenStream] = None
    # Model-multiplexing hint (ref pow_2_scheduler.py:52): the router
    # prefers replicas that already hold this model in HBM.
    multiplexed_model_id: Optional[str] = None
    # Dispatch count (router assignments, including failover re-dispatches).
    # The failover layer bounds this with its attempt budget; it never
    # resets on retry, so a bouncing request cannot circulate forever.
    attempts: int = 0
    # Frozen at admission (arrival + SLO). Retries budget against THIS
    # deadline: a re-dispatched request gets no fresh SLO clock, exactly
    # like the reference's shed accounting (a request either completes
    # within its admitted deadline or is counted shed).
    admission_deadline_ms: float = 0.0
    # Multi-tenant QoS (ROADMAP item 4): who sent it and at which service
    # tier. Both ride the request through every retry/requeue — failover
    # re-dispatches the SAME object, so class and tenant survive failover
    # by construction (pinned in tests/test_qos.py).
    tenant: str = DEFAULT_TENANT
    qos_class: str = DEFAULT_QOS_CLASS
    # Set by the hedge race's loser-cancellation: a cancelled request
    # still QUEUED is discarded at pop time (counted once, reason
    # "cancelled" — its outcome was already delivered by the winner); a
    # cancelled request already mid-execution finishes harmlessly (its
    # fulfill/reject no-op against the resolved future).
    cancelled: bool = False
    # True for a hedge shadow (never armed for a further hedge itself).
    is_hedge: bool = False

    def __post_init__(self) -> None:
        if not self.request_id:
            self.request_id = f"{self.model}-{next(_req_counter)}"
        if not self.admission_deadline_ms:
            self.admission_deadline_ms = self.arrival_ms + self.slo_ms
        self.qos_class = normalize_qos(self.qos_class)
        if not self.tenant:
            self.tenant = DEFAULT_TENANT

    @property
    def deadline_ms(self) -> float:
        return self.admission_deadline_ms

    def remaining_ms(self, now: Optional[float] = None) -> float:
        """Deadline budget left (negative = already past due)."""
        return self.deadline_ms - (now if now is not None else now_ms())

    def queue_delay_ms(self, now: Optional[float] = None) -> float:
        return (now if now is not None else now_ms()) - self.arrival_ms

    def reject(self, exc: Exception, force: bool = False) -> None:
        """``force=True`` is the hedge winner's delivery path: a
        cancelled request's own (late, lost) execution must not touch
        the client — only the race winner resolves it."""
        if self.cancelled and not force:
            return
        if self.stream is not None:
            self.stream.abort(exc)
        if not self.future.done():
            self.future.set_exception(exc)

    def fulfill(self, result: Any, force: bool = False) -> None:
        if self.cancelled and not force:
            return
        if self.stream is not None:
            self.stream.close()
        if not self.future.done():
            self.future.set_result(result)

    def stream_put(self, chunk: Any) -> None:
        """Push one incremental chunk (no-op for non-streaming requests).
        A cancelled dispatch's chunks are dropped at the source — and
        re-checked under the stream lock at delivery (``drop_if``), so a
        chunk in flight when the hedge race resolves cannot interleave
        with the winner's grafted stream."""
        if self.stream is not None and not self.cancelled:
            self.stream.put(chunk, drop_if=lambda: self.cancelled)

    def cancel(self) -> None:
        """Mark this dispatch redundant (the hedge race was won by the
        other arm). Queues discard it at pop; a running execution keeps
        computing but its chunks, result, and errors are suppressed —
        the winner owns the client-visible outcome."""
        self.cancelled = True


class BadRequest(ValueError):
    """The request payload itself is malformed — the CLIENT's fault. The
    HTTP proxy maps this (and only this) to a 4xx; plain ValueError from
    replica/engine internals stays a server error."""


class RequestDropped(Exception):
    """Raised into a request's future when the queue drops it."""


class RequestStale(Exception):
    """Raised when a request cannot meet its deadline and is discarded
    (staleness discard, ref scheduler.py:281-283)."""
