"""Collation: request payloads → bucket-padded device input batches.

The TPU-critical step the reference does with ``torch.stack(...).cuda()``
(``293-project/src/scheduler.py:443``): here every batch is padded UP to the
scheduled (batch, seq) bucket so the engine always calls an already-compiled
XLA program — arbitrary shapes would recompile per request mix
(SURVEY.md §7 hard part (a)).

Payload contracts by model family:
- vision:           np.ndarray [H, W, C] float
- text_classifier:  np.ndarray [T] int32 token ids (ragged across requests)
- causal_lm:        np.ndarray [T] int32 prompt tokens (decode engine pads)
- asr:              np.ndarray [T_frames, n_mels] float mel features
                    (ragged; padded to duration buckets, models/asr.py)
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from ray_dynamic_batching_tpu.engine.request import Request
from ray_dynamic_batching_tpu.models.base import ServableModel
from ray_dynamic_batching_tpu.utils.tracing import link_to, tracer


def collate_vision(
    model: ServableModel, requests: List[Request], batch_bucket: int
) -> Tuple[Tuple[np.ndarray, ...], int]:
    n = len(requests)
    (spec,) = model.input_shapes(batch_bucket)
    batch = np.zeros(spec.shape, dtype=spec.dtype)
    for i, req in enumerate(requests):
        batch[i] = np.asarray(req.payload, dtype=spec.dtype)
    return (batch,), n


def collate_text(
    model: ServableModel,
    requests: List[Request],
    batch_bucket: int,
    seq_bucket: int,
) -> Tuple[Tuple[np.ndarray, np.ndarray], int]:
    n = len(requests)
    tokens = np.zeros((batch_bucket, seq_bucket), dtype=np.int32)
    mask = np.zeros((batch_bucket, seq_bucket), dtype=np.int32)
    for i, req in enumerate(requests):
        ids = np.asarray(req.payload, dtype=np.int32)[:seq_bucket]
        tokens[i, : len(ids)] = ids
        mask[i, : len(ids)] = 1
    # Padding rows keep all-zero masks; attention treats them as empty.
    mask[n:, 0] = 1  # at least one valid key so softmax rows are well-formed
    return (tokens, mask), n


def collate_asr(
    model: ServableModel,
    requests: List[Request],
    batch_bucket: int,
    text_bucket: int = 8,
) -> Tuple[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray], int]:
    """Ragged mel clips → duration-bucketed (mel, mask) plus a start-of-
    transcript prompt per row, matching ASRModel.apply's signature."""
    from ray_dynamic_batching_tpu.models.asr import collate_audio

    n = len(requests)
    mels = [np.asarray(r.payload, dtype=np.float32) for r in requests]
    mel, mask = collate_audio(mels, batch_bucket)
    tokens = np.zeros((batch_bucket, text_bucket), np.int32)
    tokens[:, 0] = model.cfg.sot_token
    text_mask = np.zeros((batch_bucket, text_bucket), np.int32)
    text_mask[:, 0] = 1
    mask[n:, 0] = 1  # padding rows: one valid frame keeps softmax well-formed
    return (mel, mask, tokens, text_mask), n


def collate(
    model: ServableModel,
    requests: List[Request],
    batch_bucket: int,
    seq_bucket: int = 0,
) -> Tuple[Tuple[np.ndarray, ...], int]:
    if not tracer().enabled:  # keep the disabled hot path allocation-free
        return _collate(model, requests, batch_bucket, seq_bucket)
    with tracer().span(
        "collate.batch",
        links=[link_to(r.trace_ctx) for r in requests],
        model=model.name,
        lane=model.name,
        family=model.family,
        batch_bucket=batch_bucket,
        n=len(requests),
    ):
        return _collate(model, requests, batch_bucket, seq_bucket)


def _collate(
    model: ServableModel,
    requests: List[Request],
    batch_bucket: int,
    seq_bucket: int,
) -> Tuple[Tuple[np.ndarray, ...], int]:
    if model.family == "vision":
        return collate_vision(model, requests, batch_bucket)
    if model.family in ("text_classifier", "causal_lm"):
        if seq_bucket <= 0:
            seq_bucket = max(
                (len(np.atleast_1d(r.payload)) for r in requests), default=1
            )
        return collate_text(model, requests, batch_bucket, seq_bucket)
    if model.family == "asr":
        return collate_asr(model, requests, batch_bucket)
    raise ValueError(f"no collator for model family {model.family!r}")
