"""Replica engine — one chip's duty-cycle executor.

TPU-native re-design of the reference's ``GPUWorker`` actor
(``293-project/src/scheduler.py:374-584``): an infinite duty-cycle round-robin
over (session, occupancy) placements — take a batch from the session's queue
(:551), run the forward (:435-472), sleep out the rest of the time slice
(:564-570) — with schedule updates applied at cycle boundaries via an update
channel (:483-523, :906-929).

TPU-first differences:
- the "forward" is an **already-compiled XLA program** selected from a
  (model, batch-bucket, seq-bucket) cache; inputs are bucket-padded by
  ``collate`` so the hot loop never traces or compiles;
- hot-swap **precompiles before going live**: a new schedule's buckets are
  compiled while the old schedule keeps serving, then swapped at a cycle
  boundary — the TPU analogue of unload→``empty_cache``→load, where the cost
  is XLA compile + weight upload rather than allocator churn
  (SURVEY.md §7 hard parts (a)/(b));
- timing uses ``block_until_ready`` walls (device timeline), and the slice
  sleep accounts for the measured step, mirroring the reference's
  ``cuda.synchronize`` timing.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from queue import Empty, SimpleQueue
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ray_dynamic_batching_tpu.engine.batching import BatchPolicy, NexusFixedBatch
from ray_dynamic_batching_tpu.engine.collate import collate
from ray_dynamic_batching_tpu.engine.host import ModelHost
from ray_dynamic_batching_tpu.engine.queue import QueueManager
from ray_dynamic_batching_tpu.engine.request import Request
from ray_dynamic_batching_tpu.scheduler.nexus import NodePlan, Placement
from ray_dynamic_batching_tpu.utils.logging import get_logger
from ray_dynamic_batching_tpu.utils import metrics as m
from ray_dynamic_batching_tpu.utils.tracing import link_to, tracer

logger = get_logger("engine")

# Module-level metrics (single registration; tagged per model/engine).
BATCHES_TOTAL = m.Counter(
    "rdb_engine_batches_total", "Batches executed", tag_keys=("engine", "model")
)
REQUESTS_TOTAL = m.Counter(
    "rdb_engine_requests_total", "Requests served", tag_keys=("engine", "model")
)
STEP_LATENCY_MS = m.Histogram(
    "rdb_engine_step_latency_ms", "Compiled step latency", tag_keys=("engine", "model")
)
ENGINE_OCCUPANCY = m.Gauge(
    "rdb_engine_occupancy", "Scheduled occupancy", tag_keys=("engine",)
)
SWAP_TOTAL = m.Counter(
    "rdb_engine_schedule_swaps_total", "Schedule hot-swaps applied", tag_keys=("engine",)
)


@dataclass
class CompiledStep:
    """One (model, batch_bucket, seq_bucket) compiled program + its params."""

    model_name: str
    batch_bucket: int
    seq_bucket: int
    fn: Callable[..., Any]
    model: Any
    params: Any


@dataclass
class ActiveSchedule:
    """The engine's live schedule (placements share one duty cycle)."""

    placements: List[Placement] = field(default_factory=list)
    duty_cycle_ms: float = 0.0
    steps: Dict[str, CompiledStep] = field(default_factory=dict)  # by model
    policies: Dict[str, BatchPolicy] = field(default_factory=dict)


class ReplicaEngine:
    """One executor thread bound to one chip (or one mesh slice)."""

    def __init__(
        self,
        engine_id: str,
        queues: QueueManager,
        host: ModelHost,
        seq_bucket_default: int = 0,
        idle_wait_s: float = 0.01,
    ):
        self.engine_id = engine_id
        self.queues = queues
        self.host = host
        self.seq_bucket_default = seq_bucket_default
        self.idle_wait_s = idle_wait_s
        self._ready: SimpleQueue = SimpleQueue()  # prepared schedules
        self._schedule = ActiveSchedule()
        self._active = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._cycle_count = 0
        self._last_error: Optional[Exception] = None
        self._pending_plan: Optional[NodePlan] = None
        self._assign_lock = threading.Lock()
        self._preparer: Optional[threading.Thread] = None
        # Compiled-executable cache keyed (model, batch_bucket, seq_bucket):
        # rebalancing between schedules that share buckets must not pay the
        # 20-40s XLA compile again. Executables hold code, not weights, so
        # they survive model unload/reload (params are call arguments).
        self._compile_cache: Dict[Tuple[str, int, int], Any] = {}
        self._compile_cache_cap = 64
        self._closed = False
        # Observed/expected step-latency ratios for the gray-failure
        # detector (LiveScheduler.enable_gray_monitoring — the live twin
        # of SimEngine.track_ratios): a healthy engine reads ~1.0
        # whatever it hosts, a 10x-throttled chip reads ~10. Armed only
        # when gray monitoring is on; drained per monitor tick.
        self.track_ratios = False
        self._fresh_ratios: list = []

    # --- schedule handoff (ref update_queues.put, scheduler.py:906-929) ---
    def assign(self, plan: NodePlan) -> None:
        """Queue a new node plan. Params load + XLA compiles run on a
        background preparer thread while the old schedule keeps serving; the
        hot loop only performs the pointer swap at a cycle boundary."""
        with self._assign_lock:
            self._pending_plan = plan
            if self._preparer is None or not self._preparer.is_alive():
                self._preparer = threading.Thread(
                    target=self._prepare_loop,
                    name=f"engine-{self.engine_id}-prepare",
                    daemon=True,
                )
                self._preparer.start()

    def _prepare_loop(self) -> None:
        while True:
            with self._assign_lock:
                plan = self._pending_plan
                self._pending_plan = None
                if plan is None:
                    self._preparer = None
                    return
            try:
                prepared = self._prepare(plan)
                with self._assign_lock:  # atomic vs stop()'s drain
                    if self._closed:
                        # stop() raced us past its _ready drain: nobody will
                        # apply this schedule, release its refs here.
                        for name in prepared.steps:
                            self.host.release(name)
                    else:
                        self._ready.put((plan, prepared))
            except Exception as e:  # noqa: BLE001
                self._last_error = e
                logger.exception(
                    "%s: schedule preparation failed; keeping old schedule",
                    self.engine_id,
                )

    def _prepare(self, plan: NodePlan) -> ActiveSchedule:
        """Load params + compile every placement's bucket BEFORE going live
        (the reference loads inside the swap window, :507-515; on TPU that
        would stall serving for the full XLA compile)."""
        steps: Dict[str, CompiledStep] = {}
        policies: Dict[str, BatchPolicy] = {}
        acquired: List[str] = []
        try:
            for p in plan.placements:
                name = p.session.model
                model, params = self.host.acquire(name)
                acquired.append(name)
                seq = p.session.seq_len or self.seq_bucket_default
                example = model.example_inputs(p.batch_size, seq or None)
                if seq == 0 and model.family in ("text_classifier", "causal_lm"):
                    # Collate must pad to the exact shape the AOT program was
                    # lowered with; recover the model's default seq bucket.
                    seq = int(example[0].shape[1])
                key = (name, p.batch_size, seq)
                compiled = self._compile_cache.get(key)
                if compiled is None:
                    compiled = jax.jit(model.apply).lower(
                        params, *example
                    ).compile()
                    if len(self._compile_cache) >= self._compile_cache_cap:
                        # Evict LEAST-RECENTLY-USED, not oldest-inserted: a
                        # hot executable recompiling mid-serving costs 20-40s
                        # of blown SLOs on the chip.
                        self._compile_cache.pop(next(iter(self._compile_cache)))
                    self._compile_cache[key] = compiled
                else:
                    # Hit refreshes recency (insertion order is the LRU order).
                    self._compile_cache.pop(key)
                    self._compile_cache[key] = compiled
                steps[name] = CompiledStep(
                    model_name=name,
                    batch_bucket=p.batch_size,
                    seq_bucket=seq,
                    fn=compiled,
                    model=model,
                    params=params,
                )
                policies[name] = NexusFixedBatch(
                    p.batch_size, expected_latency_ms=p.latency_ms
                )
        except Exception:
            for name in acquired:  # roll back refs or params leak in HBM
                self.host.release(name)
            raise
        return ActiveSchedule(
            placements=list(plan.placements),
            duty_cycle_ms=plan.duty_cycle_ms,
            steps=steps,
            policies=policies,
        )

    def _apply_updates(self) -> None:
        """Swap in the newest prepared schedule, if any (ref
        _check_for_updates, :483-523: unload removed → load added → swap
        atomically — here load/compile already happened off-thread)."""
        latest = None
        while True:
            try:
                candidate = self._ready.get_nowait()
            except Empty:
                break
            if latest is not None:
                # Superseded schedule: release the refs its _prepare acquired.
                for name in latest[1].steps:
                    self.host.release(name)
            latest = candidate
        if latest is None:
            return
        plan, new_schedule = latest
        old_models = set(self._schedule.steps)
        self._schedule = new_schedule  # atomic swap at cycle boundary
        # Each ActiveSchedule owns exactly one host reference per model
        # (_prepare acquired for the new one), so release ALL old refs —
        # retained models keep a balanced count, removed ones unload.
        for name in old_models:
            self.host.release(name)
        ENGINE_OCCUPANCY.set(
            sum(p.occupancy for p in plan.placements),
            tags={"engine": self.engine_id},
        )
        SWAP_TOTAL.inc(tags={"engine": self.engine_id})
        logger.info("%s: swapped to %s", self.engine_id, plan.describe())

    # --- hot loop (ref execute_schedule, scheduler.py:525-584) ------------
    def _run_placement(self, p: Placement, step: CompiledStep,
                       policy: BatchPolicy) -> float:
        """Execute one session's slice; returns elapsed ms."""
        name = p.session.model
        queue = self.queues.queue(name)
        batch = policy.next_batch(queue)
        if not batch:
            return 0.0
        t0 = time.perf_counter()
        # One compiled-step span per batch execution, tagged with the bucket
        # the program was compiled for and LINKED to every member request's
        # span (the fan-in parent/child cannot express); each member then
        # gets a completion span linking BACK, so both directions navigate.
        traced = tracer().enabled
        member_links = [link_to(r.trace_ctx) for r in batch] if traced else None
        step_start_ms = m.now_ms() if traced else 0.0
        try:
            with tracer().span(
                "engine.step",
                links=member_links,
                model=name,
                engine=self.engine_id,
                lane=self.engine_id,
                batch_bucket=step.batch_bucket,
                seq_bucket=step.seq_bucket,
                n=len(batch),
            ) as step_span:
                inputs, n_real = collate(
                    step.model, batch, step.batch_bucket, step.seq_bucket
                )
                out = step.fn(step.params, *inputs)
                # np.asarray forces the device->host fetch, which is the only
                # reliable completion signal on the axon tunnel
                # (block_until_ready returns early there); the engine needs
                # the results host-side anyway to fulfill futures.
                results = np.asarray(out)[:n_real]  # rdb-lint: disable=host-sync-in-hot-path (THE designed fetch: host results fulfill futures and signal axon completion)
        except Exception as e:  # noqa: BLE001
            for req in batch:
                req.reject(e)
            self._last_error = e
            logger.error("%s/%s: step failed: %s", self.engine_id, name, e)
            return (time.perf_counter() - t0) * 1000.0
        elapsed_ms = (time.perf_counter() - t0) * 1000.0
        if self.track_ratios and p.latency_ms > 0:
            # Engine-thread append, monitor-thread drain (GIL-atomic
            # list swap in drain_ratios — same contract as SimEngine).
            self._fresh_ratios.append(elapsed_ms / p.latency_ms)
        for req, res in zip(batch, results):
            req.fulfill(res)
        if step_span is not None:
            end_ms = m.now_ms()
            for req in batch:
                # Per-request execution span in the REQUEST's trace, linked
                # to the batch step it rode.
                tracer().record_span(
                    "engine.request",
                    ctx=req.trace_ctx,
                    start_ms=step_start_ms,
                    end_ms=end_ms,
                    links=[link_to(step_span)],
                    model=name,
                    engine=self.engine_id,
                    lane=self.engine_id,
                )
        queue.record_batch_completion(batch)
        BATCHES_TOTAL.inc(tags={"engine": self.engine_id, "model": name})
        REQUESTS_TOTAL.inc(n_real, tags={"engine": self.engine_id, "model": name})
        STEP_LATENCY_MS.observe(
            elapsed_ms, tags={"engine": self.engine_id, "model": name},
            trace_id=step_span.trace_id if step_span is not None else None,
        )
        return elapsed_ms

    def _run_cycle(self) -> None:
        sched = self._schedule
        if not sched.placements:
            time.sleep(self.idle_wait_s)  # rdb-lint: disable=event-loop-blocking (idle wait on the engine's own thread)
            return
        cycle_start = time.perf_counter()
        for p in sched.placements:
            step = sched.steps[p.session.model]
            policy = sched.policies[p.session.model]
            elapsed_ms = self._run_placement(p, step, policy)
            # Sleep out the remainder of this session's slice so co-tenants
            # get their scheduled share (ref :564-570).
            slice_ms = p.occupancy * sched.duty_cycle_ms
            remaining_ms = slice_ms - elapsed_ms
            if remaining_ms > 0.05:
                time.sleep(remaining_ms / 1000.0)  # rdb-lint: disable=event-loop-blocking (duty-cycle slice pacing on the engine's own thread; co-tenant shares depend on it)
        # Absorb any leftover duty-cycle time (unallocated occupancy).
        total_ms = (time.perf_counter() - cycle_start) * 1000.0
        leftover_ms = sched.duty_cycle_ms - total_ms
        if leftover_ms > 0.05:
            time.sleep(leftover_ms / 1000.0)  # rdb-lint: disable=event-loop-blocking (duty-cycle leftover absorption on the engine's own thread)
        self._cycle_count += 1

    def _loop(self) -> None:
        while self._active.is_set():
            try:
                self._apply_updates()
                self._run_cycle()
            except Exception as e:  # noqa: BLE001 — engine must not die silently
                self._last_error = e
                logger.exception("%s: cycle failed", self.engine_id)
                time.sleep(0.05)  # rdb-lint: disable=event-loop-blocking (loop error backoff on the engine's own thread)

    # --- lifecycle --------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._active.set()
        self._thread = threading.Thread(
            target=self._loop, name=f"engine-{self.engine_id}", daemon=True
        )
        self._thread.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        self._closed = True
        self._active.clear()
        if self._thread is not None:
            self._thread.join(timeout_s)
            self._thread = None
        # Wait for any in-flight preparation: a schedule landing in _ready
        # AFTER the drain below would leak its host refs (params in HBM).
        with self._assign_lock:
            self._pending_plan = None  # cancel queued-but-unstarted plans
            preparer = self._preparer
        if preparer is not None:
            preparer.join(timeout_s)
        # Release refs of the live schedule AND any prepared-but-unapplied
        # schedules still sitting in the ready queue. Under the assign lock:
        # a preparer that outlived the bounded join above will see _closed
        # inside the same lock and release its own refs instead of putting.
        with self._assign_lock:
            while True:
                try:
                    _, sched = self._ready.get_nowait()
                except Empty:
                    break
                for name in sched.steps:
                    self.host.release(name)
        for name in list(self._schedule.steps):
            self.host.release(name)
        self._schedule = ActiveSchedule()

    @property
    def cycle_count(self) -> int:
        return self._cycle_count

    def drain_ratios(self) -> list:
        """Observed/expected step ratios since the last drain (the gray
        monitor's per-tick observation window; GIL-atomic list swap —
        engine thread appends, monitor thread drains)."""
        out, self._fresh_ratios = self._fresh_ratios, []
        return out

    def healthy(self) -> bool:
        """Liveness for the scheduler's heal path (mirror of
        ``serve.Replica.healthy``): started, not stopped, and the duty-
        cycle thread is actually alive — a crashed hot loop must drop
        this engine out of the planner's candidate set."""
        if self._closed:
            return False
        if self._thread is None:
            return True  # not started yet — serves once started
        return self._active.is_set() and self._thread.is_alive()

    @property
    def models(self) -> List[str]:
        return list(self._schedule.steps)

    def describe(self) -> str:
        s = self._schedule
        return (
            f"ReplicaEngine({self.engine_id}, duty={s.duty_cycle_ms:.1f}ms, "
            f"models={sorted(s.steps)})"
        )
