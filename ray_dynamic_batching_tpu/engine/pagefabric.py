"""KV page parcels — page sets as shippable objects (ISSUE 18 tentpole).

PAPER.md's layer survey names the plasma object store (L2) as the one
substrate layer this repo had not re-created: Ray makes data a
first-class shippable object. Our page sets are already refcounted
(PageAllocator), cluster-identified by :func:`~.paging.digest_chain`,
and spillable to host RAM (HostSpillTier) — this module adds the last
property, *mobility*. A :class:`PageParcel` is a page run serialized to
host numpy (int8 scale planes included) plus everything needed to
resume it elsewhere:

- **Stream parcels** carry a LIVE decode stream: the page contents
  covering its cached tokens, the full sampling row
  (temperature/top-k/top-p/seed/penalties/bias — and ``base_seed``,
  because the device PRNG key is ``fold_in(fold_in(PRNGKey(base_seed),
  seed), len(generated))``, all host-derivable), and the stream cursor
  (the ``generated`` list + the live :class:`Request` object itself).
  Re-registering the parcel on a destination engine resumes the SAME
  ``TokenStream`` — re-routed, never retried — so the
  at-most-once-after-first-token pin holds across the move and the
  client sees an uninterrupted stream.
- **Prefix parcels** carry one prefix-cache entry addressed by its
  chain digest: the receiver installs it digest-direct
  (``PagedPrefixCache.install`` — token bytes never leave the source
  replica) so a hot prompt warms peers ahead of demand.

The export/import functions here run ON the owning engine's thread
(the engine services its parcel mailboxes between decode turns — see
``DecodeEngine._service_fabric``); everything in this module is
therefore single-threaded with respect to the engine state it touches.
The transfer plane that moves parcels BETWEEN engines lives in
``serve/kv_fabric.py`` and rides the ControlFabric seam, so chaos
partition windows apply to couriers exactly as to every other control
edge.

Token-exactness across a migration is a host-arithmetic fact, pinned in
tier-1: the sampled-token key depends only on (base_seed, per-request
seed, len(generated)) and the penalty counts row equals
``bincount(generated)`` for any live slot (the first token is counted
at register; a surviving slot accepted every token the scan counted) —
all of which the parcel carries or the importer reconstructs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from ray_dynamic_batching_tpu.engine.paging import digest_chain
from ray_dynamic_batching_tpu.ops.tile_math import pages_for

# Parcel kinds: a live stream move vs a speculative prefix replication.
STREAM = "stream"
PREFIX = "prefix"


@dataclass
class PageParcel:
    """One shippable page set + the state to resume it elsewhere.

    In-process transfer object: arrays are host numpy copies (gathered
    off-device by the exporter), ``request`` is the live Request whose
    TokenStream keeps flowing after the import splices the pages in.
    ``digest`` is the chain address of the deepest full page covered
    (``b""`` when less than one full page is cached) — the same 16-byte
    identity the prefix caches, spill tier, and router directory key by.
    """

    kind: str                                   # STREAM | PREFIX
    page_size: int
    cache_len: int                              # tokens the pages cover
    payload: Dict[str, np.ndarray]              # k/v [+ k_scale/v_scale]
    digest: bytes = b""
    src: str = ""                               # exporting engine/replica
    # --- stream-only resume state ------------------------------------
    request: Optional[Any] = None               # live engine Request
    generated: List[int] = field(default_factory=list)
    max_new_tokens: int = 0
    prefill_done_ms: float = 0.0
    stop: frozenset = frozenset()
    session_id: Optional[str] = None
    prompt_tokens: Optional[np.ndarray] = None
    sampling: Dict[str, Any] = field(default_factory=dict)

    @property
    def n_pages(self) -> int:
        return pages_for(self.cache_len, self.page_size)

    @property
    def nbytes(self) -> int:
        """Courier-priced size: page plane bytes + the resume tokens.
        This is the ``parcel_bytes`` the replanner multiplies by the
        courier rate (scheduler/replan.py::migration_parcel_cost)."""
        n = sum(int(a.nbytes) for a in self.payload.values())
        n += 4 * (len(self.generated)
                  + (int(self.prompt_tokens.size)
                     if self.prompt_tokens is not None else 0))
        return n

    @property
    def resume_len(self) -> int:
        """KV capacity a destination must offer: cached tokens plus the
        tokens the stream may still generate."""
        return self.cache_len + max(
            0, self.max_new_tokens - len(self.generated)
        )


def export_stream_parcel(engine, slot_idx: int) -> PageParcel:
    """Freeze ``slot_idx``'s live stream into a parcel (engine thread,
    between decode turns). READ-ONLY: the slot keeps every page and all
    host/device state — a failed delivery simply resumes decoding here,
    because nothing was torn down to build the parcel."""
    slot = engine._slots[slot_idx]
    cache_len = int(engine._len_host[slot_idx])
    need = pages_for(cache_len, engine.page_size)
    # Headroom pages past the cached length hold garbage (the scan only
    # attends < lengths); exporting them would ship dead bytes.
    page_ids = list(slot.pages[:need])
    payload = engine._read_pages(page_ids) if page_ids else {}
    tokens = np.concatenate([
        np.asarray(slot.prompt_tokens, np.int32)
        if slot.prompt_tokens is not None else np.zeros((0,), np.int32),
        np.asarray(slot.generated, np.int32),
    ])
    chain = digest_chain(tokens, engine.page_size)
    return PageParcel(
        kind=STREAM,
        page_size=engine.page_size,
        cache_len=cache_len,
        payload=payload,
        digest=chain[-1] if chain else b"",
        src=engine.model.name,
        request=slot.request,
        generated=list(slot.generated),
        max_new_tokens=slot.max_new_tokens,
        prefill_done_ms=slot.prefill_done_ms,
        stop=slot.stop,
        session_id=slot.session_id,
        prompt_tokens=slot.prompt_tokens,
        sampling={
            "temperature": float(engine._temps[slot_idx]),
            "top_k": int(engine._topk[slot_idx]),
            "top_p": float(engine._topp[slot_idx]),
            "seed": int(engine._seeds[slot_idx]),
            "presence_penalty": float(engine._pres[slot_idx]),
            "frequency_penalty": float(engine._freq[slot_idx]),
            "bias_ids": np.array(engine._bias_ids[slot_idx]),
            "bias_vals": np.array(engine._bias_vals[slot_idx]),
            # Exactness gate: the device PRNG base key is engine-level,
            # so a sampled row only resumes byte-identically on an
            # engine sharing it (accept_parcel refuses otherwise).
            "base_seed": int(engine.base_seed),
        },
    )


def export_prefix_parcel(engine, key: bytes) -> Optional[PageParcel]:
    """One prefix-cache entry as a push parcel (engine thread). The
    pages are pinned by the cache and never rewritten after publication
    (CoW invariant), so the gather races nothing; None when the entry
    was evicted between planning and export."""
    cache = engine.paged_prefix
    if cache is None:
        return None
    entry = cache._entries.get(key)
    if entry is None:
        return None
    page_ids = list(entry)
    return PageParcel(
        kind=PREFIX,
        page_size=engine.page_size,
        cache_len=len(page_ids) * engine.page_size,
        payload=engine._read_pages(page_ids),
        digest=key,
        src=engine.model.name,
    )
