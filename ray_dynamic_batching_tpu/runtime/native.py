"""ctypes bindings for the C++ runtime substrate (native/src/rdb_native.cc).

The native layer plays the roles the reference implements in C++
(SURVEY.md §2.2): shared-memory object store (plasma,
``src/ray/object_manager/plasma/store.cc``), shared-memory request queues
with batch pop (fixes the per-item actor RPC at
``293-project/src/scheduler.py:277``), KV store with versioned long-poll
watch (GCS KV + ``serve/_private/long_poll.py``), actor mailbox runtime
(``transport/actor_scheduling_queue.cc`` ordering semantics +
``gcs_actor_manager.cc:1361`` max_restarts), and a heartbeat health
registry (``gcs_health_check_manager.cc``).

Bindings use ctypes (no pybind11 in this image); the library is built on
first use with the repo Makefile and cached.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Callable, List, Optional, Sequence, Tuple

_REPO_ROOT = Path(__file__).resolve().parents[2]
_NATIVE_DIR = _REPO_ROOT / "native"
_LIB_PATH = _NATIVE_DIR / "build" / "librdb_native.so"
_BUILD_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None


def build_native(force: bool = False) -> Path:
    """Compile the native library if needed (idempotent, cached)."""
    with _BUILD_LOCK:
        src = _NATIVE_DIR / "src" / "rdb_native.cc"
        if (
            not force
            and _LIB_PATH.exists()
            and _LIB_PATH.stat().st_mtime >= src.stat().st_mtime
        ):
            return _LIB_PATH
        subprocess.run(
            ["make", "-C", str(_NATIVE_DIR)],
            check=True,
            capture_output=True,
            text=True,
        )
        return _LIB_PATH


ACTOR_FN = ctypes.CFUNCTYPE(
    ctypes.c_int,
    ctypes.c_uint64,
    ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_uint32,
    ctypes.c_void_p,
)


def _lib() -> ctypes.CDLL:
    global _LIB
    if _LIB is not None:
        return _LIB
    with _BUILD_LOCK:
        if _LIB is not None:
            return _LIB
    build_native()
    lib = ctypes.CDLL(str(_LIB_PATH))
    c = ctypes
    u8p = c.POINTER(c.c_uint8)
    sigs = {
        "rdb_queue_create": ([c.c_char_p, c.c_uint32, c.c_uint32], c.c_void_p),
        "rdb_queue_open": ([c.c_char_p], c.c_void_p),
        "rdb_queue_push": ([c.c_void_p, u8p, c.c_uint32], c.c_int),
        "rdb_queue_pop_batch": (
            [c.c_void_p, u8p, c.c_uint32, c.POINTER(c.c_uint32), c.c_int],
            c.c_int,
        ),
        "rdb_queue_size": ([c.c_void_p], c.c_uint32),
        "rdb_queue_dropped": ([c.c_void_p], c.c_uint64),
        "rdb_queue_item_size": ([c.c_void_p], c.c_uint32),
        "rdb_queue_capacity": ([c.c_void_p], c.c_uint32),
        "rdb_queue_close": ([c.c_void_p, c.c_int], None),
        "rdb_store_create": ([c.c_char_p, c.c_uint64, c.c_uint32], c.c_void_p),
        "rdb_store_open": ([c.c_char_p], c.c_void_p),
        "rdb_store_put": ([c.c_void_p, c.c_uint64, u8p, c.c_uint64], c.c_int64),
        "rdb_store_get": ([c.c_void_p, c.c_uint64, u8p, c.c_uint64], c.c_int64),
        "rdb_store_delete": ([c.c_void_p, c.c_uint64], c.c_int),
        "rdb_store_contains": ([c.c_void_p, c.c_uint64], c.c_int),
        "rdb_store_used": ([c.c_void_p], c.c_uint64),
        "rdb_store_evictions": ([c.c_void_p], c.c_uint64),
        "rdb_store_close": ([c.c_void_p, c.c_int], None),
        "rdb_kv_create": ([], c.c_void_p),
        "rdb_kv_destroy": ([c.c_void_p], None),
        "rdb_kv_put": ([c.c_void_p, c.c_char_p, u8p, c.c_uint32], c.c_uint64),
        "rdb_kv_get": (
            [c.c_void_p, c.c_char_p, u8p, c.c_uint32, c.POINTER(c.c_uint64)],
            c.c_int64,
        ),
        "rdb_kv_del": ([c.c_void_p, c.c_char_p], c.c_int),
        "rdb_kv_watch": (
            [c.c_void_p, c.c_char_p, c.c_uint64, c.c_int],
            c.c_uint64,
        ),
        "rdb_kv_keys": ([c.c_void_p, c.c_char_p, u8p, c.c_uint32], c.c_int64),
        "rdb_actors_create": ([c.c_uint32], c.c_void_p),
        "rdb_actor_register": (
            [c.c_void_p, c.c_char_p, ACTOR_FN, c.c_void_p, c.c_uint32,
             c.c_uint32],
            c.c_uint64,
        ),
        "rdb_actor_post": ([c.c_void_p, c.c_uint64, u8p, c.c_uint32], c.c_int),
        "rdb_actors_drain": ([c.c_void_p, c.c_int], c.c_int),
        "rdb_actor_processed": ([c.c_void_p, c.c_uint64], c.c_uint64),
        "rdb_actor_failed": ([c.c_void_p, c.c_uint64], c.c_uint64),
        "rdb_actor_is_dead": ([c.c_void_p, c.c_uint64], c.c_int),
        "rdb_actors_destroy": ([c.c_void_p], None),
        "rdb_health_create": ([c.c_double], c.c_void_p),
        "rdb_health_destroy": ([c.c_void_p], None),
        "rdb_health_report": ([c.c_void_p, c.c_char_p], None),
        "rdb_health_remove": ([c.c_void_p, c.c_char_p], c.c_int),
        "rdb_health_dead": ([c.c_void_p, u8p, c.c_uint32], c.c_int64),
        "rdb_health_alive_count": ([c.c_void_p], c.c_int),
    }
    for name, (argtypes, restype) in sigs.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = restype
    _LIB = lib
    return lib


def _buf(data: bytes):
    return (ctypes.c_uint8 * len(data)).from_buffer_copy(data) if data else \
        ctypes.cast(ctypes.c_char_p(b""), ctypes.POINTER(ctypes.c_uint8))


class NativeQueue:
    """Cross-process shared-memory MPMC queue with batch pop.

    One ``pop_batch`` call drains up to ``max_items`` — the single-RPC batch
    pop the reference's queue lacks (SURVEY.md §3.1)."""

    def __init__(self, name: str, capacity: int = 1024, item_size: int = 4096,
                 create: bool = True):
        lib = _lib()
        self._lib = lib
        self.name = name.encode() if isinstance(name, str) else name
        if create:
            self._q = lib.rdb_queue_create(self.name, capacity, item_size)
        else:
            self._q = lib.rdb_queue_open(self.name)
        if not self._q:
            raise OSError(f"cannot {'create' if create else 'open'} queue {name}")
        self.item_size = lib.rdb_queue_item_size(self._q)
        self._owner = create

    def push(self, data: bytes) -> bool:
        """False = dropped because full (reference drop policy)."""
        rc = self._lib.rdb_queue_push(self._q, _buf(data), len(data))
        if rc == -2:
            raise ValueError(
                f"item of {len(data)} bytes exceeds slot size {self.item_size}"
            )
        return rc == 0

    def pop_batch(self, max_items: int, timeout_ms: int = 0) -> List[bytes]:
        out = (ctypes.c_uint8 * (max_items * self.item_size))()
        lens = (ctypes.c_uint32 * max_items)()
        n = self._lib.rdb_queue_pop_batch(
            self._q,
            ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)),
            max_items,
            lens,
            timeout_ms,
        )
        # memoryview slicing: copy only the n returned items, not the
        # whole max_items*item_size buffer (this is the hot serving path)
        mv = memoryview(out)
        return [
            bytes(mv[i * self.item_size: i * self.item_size + lens[i]])
            for i in range(max(n, 0))
        ]

    def __len__(self) -> int:
        return self._lib.rdb_queue_size(self._q)

    @property
    def dropped(self) -> int:
        return self._lib.rdb_queue_dropped(self._q)

    def close(self, unlink: Optional[bool] = None) -> None:
        if self._q:
            self._lib.rdb_queue_close(
                self._q, int(self._owner if unlink is None else unlink)
            )
            self._q = None


class ObjectStore:
    """Shared-memory object store with LRU eviction (plasma role)."""

    def __init__(self, name: str, capacity_bytes: int = 64 << 20,
                 max_objects: int = 4096, create: bool = True):
        lib = _lib()
        self._lib = lib
        self.name = name.encode() if isinstance(name, str) else name
        if create:
            self._s = lib.rdb_store_create(self.name, capacity_bytes, max_objects)
        else:
            self._s = lib.rdb_store_open(self.name)
        if not self._s:
            raise OSError(f"cannot {'create' if create else 'open'} store {name}")
        self._owner = create

    def put(self, oid: int, data: bytes) -> bool:
        rc = self._lib.rdb_store_put(self._s, oid, _buf(data), len(data))
        if rc == -2:
            raise KeyError(f"object {oid} already exists (immutable store)")
        return rc >= 0

    def get(self, oid: int) -> Optional[bytes]:
        # probe-then-read retry loop: the object can be deleted/evicted (or
        # in the KV case, grown) by another process between the two calls,
        # so trust only a read whose reported length fits the buffer
        n = self._lib.rdb_store_get(
            self._s, oid,
            ctypes.cast((ctypes.c_uint8 * 0)(), ctypes.POINTER(ctypes.c_uint8)),
            0,
        )
        while True:
            if n < 0:
                return None
            out = (ctypes.c_uint8 * max(n, 1))()
            n2 = self._lib.rdb_store_get(
                self._s, oid, ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)),
                n,
            )
            if n2 < 0:
                return None
            if n2 <= n:
                return bytes(out)[:n2]
            n = n2  # grew concurrently; retry with the larger size

    def delete(self, oid: int) -> bool:
        return self._lib.rdb_store_delete(self._s, oid) == 0

    def __contains__(self, oid: int) -> bool:
        return bool(self._lib.rdb_store_contains(self._s, oid))

    @property
    def used_bytes(self) -> int:
        return self._lib.rdb_store_used(self._s)

    @property
    def evictions(self) -> int:
        return self._lib.rdb_store_evictions(self._s)

    def close(self, unlink: Optional[bool] = None) -> None:
        if self._s:
            self._lib.rdb_store_close(
                self._s, int(self._owner if unlink is None else unlink)
            )
            self._s = None


class KVStore:
    """In-process KV with versioned long-poll watch (GCS KV role)."""

    def __init__(self):
        self._lib = _lib()
        self._kv = self._lib.rdb_kv_create()

    def put(self, key: str, value: bytes) -> int:
        return self._lib.rdb_kv_put(
            self._kv, key.encode(), _buf(value), len(value)
        )

    def get(self, key: str) -> Optional[Tuple[bytes, int]]:
        version = ctypes.c_uint64()
        n = self._lib.rdb_kv_get(
            self._kv, key.encode(),
            ctypes.cast((ctypes.c_uint8 * 0)(), ctypes.POINTER(ctypes.c_uint8)),
            0, ctypes.byref(version),
        )
        while True:
            if n < 0:
                return None
            out = (ctypes.c_uint8 * max(n, 1))()
            n2 = self._lib.rdb_kv_get(
                self._kv, key.encode(),
                ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)), n,
                ctypes.byref(version),
            )
            if n2 < 0:
                return None
            if n2 <= n:
                return bytes(out)[:n2], version.value
            n = n2  # value grew between probe and read; retry

    def delete(self, key: str) -> bool:
        return self._lib.rdb_kv_del(self._kv, key.encode()) == 0

    def watch(self, key: str, have_version: int = 0,
              timeout_ms: int = 1000) -> int:
        """Block until the key's version exceeds have_version; 0 = timeout
        (the long-poll listen_for_change contract)."""
        return self._lib.rdb_kv_watch(
            self._kv, key.encode(), have_version, timeout_ms
        )

    def keys(self, prefix: str = "") -> List[str]:
        cap = 1 << 16
        while True:
            out = (ctypes.c_uint8 * cap)()
            n = self._lib.rdb_kv_keys(
                self._kv, prefix.encode(),
                ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)), cap
            )
            if n == 0:
                return []
            if n <= cap:
                return bytes(out)[:n].decode().split("\n")
            cap = n + 1024  # listing outgrew the buffer; re-call larger

    def close(self) -> None:
        if self._kv:
            self._lib.rdb_kv_destroy(self._kv)
            self._kv = None


class ActorPool:
    """Actor runtime: named actors with FIFO mailboxes executed serially
    per actor, in parallel across actors, with max_restarts fault policy."""

    def __init__(self, n_threads: int = 4):
        self._lib = _lib()
        self._rt = self._lib.rdb_actors_create(n_threads)
        self._callbacks = {}  # keep CFUNCTYPE objects alive

    def register(self, name: str, handler: Callable[[bytes], None],
                 mailbox_cap: int = 1024, max_restarts: int = 3) -> int:
        def trampoline(actor_id, msg_ptr, msg_len, _ctx):
            try:
                data = bytes(
                    ctypes.cast(
                        msg_ptr, ctypes.POINTER(ctypes.c_uint8 * msg_len)
                    ).contents
                ) if msg_len else b""
                handler(data)
                return 0
            except Exception:
                return 1  # counted as a failure -> restart accounting

        cb = ACTOR_FN(trampoline)
        actor_id = self._lib.rdb_actor_register(
            self._rt, name.encode(), cb, None, mailbox_cap, max_restarts
        )
        self._callbacks[actor_id] = cb
        return actor_id

    def post(self, actor_id: int, msg: bytes) -> bool:
        rc = self._lib.rdb_actor_post(self._rt, actor_id, _buf(msg), len(msg))
        if rc == -2:
            raise KeyError(f"actor {actor_id} missing or dead")
        return rc == 0

    def drain(self, timeout_ms: int = 10_000) -> bool:
        return self._lib.rdb_actors_drain(self._rt, timeout_ms) == 0

    def processed(self, actor_id: int) -> int:
        return self._lib.rdb_actor_processed(self._rt, actor_id)

    def failed(self, actor_id: int) -> int:
        return self._lib.rdb_actor_failed(self._rt, actor_id)

    def is_dead(self, actor_id: int) -> bool:
        return bool(self._lib.rdb_actor_is_dead(self._rt, actor_id))

    def close(self) -> None:
        if self._rt:
            self._lib.rdb_actors_destroy(self._rt)
            self._rt = None
            self._callbacks.clear()


class HealthTable:
    """Heartbeat registry with staleness detection (GCS health-check role)."""

    def __init__(self, timeout_s: float = 5.0):
        self._lib = _lib()
        self._h = self._lib.rdb_health_create(timeout_s)

    def report(self, node: str) -> None:
        self._lib.rdb_health_report(self._h, node.encode())

    def remove(self, node: str) -> bool:
        return self._lib.rdb_health_remove(self._h, node.encode()) == 0

    def dead_nodes(self) -> List[str]:
        cap = 1 << 14
        while True:
            out = (ctypes.c_uint8 * cap)()
            n = self._lib.rdb_health_dead(
                self._h, ctypes.cast(out, ctypes.POINTER(ctypes.c_uint8)), cap
            )
            if n == 0:
                return []
            if n <= cap:
                return bytes(out)[:n].decode().split("\n")
            cap = n + 1024

    @property
    def alive_count(self) -> int:
        return self._lib.rdb_health_alive_count(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.rdb_health_destroy(self._h)
            self._h = None
