"""Multi-process cluster — worker "nodes" as local processes.

Re-creates the reference's multi-node-without-a-cluster strategy
(``python/ray/cluster_utils.py:135`` — multiple raylets as local processes
in one machine): a worker node here is a spawned process running a replica
loop behind the C++ shm substrate, and the head process keeps the
controller/router and reaches it through a :class:`ProcessReplica` adapter
that speaks the standard replica surface. The division of labor mirrors the
reference's two-node serving split:

- head: controller + router + (optionally) HTTP ingress;
- worker: execution loop, fed request metadata over the shm MPMC ring and
  payloads/results over the shm object store (``engine/shm_bridge.py`` —
  the gRPC+plasma pairing of the reference, SURVEY.md §2.2).

Failure detection rides per-node heartbeat files (the GCS health-check
role, ``gcs_health_check_manager.h:39``): a killed worker stops beating,
``ProcessReplica.healthy()`` goes false, and the controller's UNCHANGED
heal path replaces the node — cross-process replica failover without any
cluster-specific control-plane code.
"""

from __future__ import annotations

import importlib
import multiprocessing as mp
import os
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from ray_dynamic_batching_tpu.engine.request import Request, RequestDropped
from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("cluster")

HEARTBEAT_INTERVAL_S = 0.1
READY_TIMEOUT_S = 30.0


# --- worker process --------------------------------------------------------

def demo_echo_factory() -> Callable[[List[Any]], List[Any]]:
    """Batch identity — the cross-process smoke deployment."""
    return lambda payloads: list(payloads)


def demo_double_factory() -> Callable[[List[Any]], List[Any]]:
    return lambda payloads: [p * 2 for p in payloads]


def _resolve_factory(spec: str) -> Callable:
    """'pkg.module:callable' → the callable, imported in THIS process (the
    reference re-imports deployment code on each node the same way)."""
    mod_name, _, fn_name = spec.partition(":")
    return getattr(importlib.import_module(mod_name), fn_name)


def _worker_main(
    shm_name: str,
    hb_path: str,
    deployment: str,
    replica_id: str,
    factory_spec: str,
    replica_options: Dict[str, Any],
) -> None:
    """Entry point of a worker node process."""
    # Worker nodes are host-side executors; keep them off the accelerator
    # so N nodes don't fight over one chip (compute-on-TPU replicas run in
    # the head process or get their own chip via placement groups).
    import jax

    jax.config.update("jax_platforms", "cpu")

    from ray_dynamic_batching_tpu.engine.shm_bridge import ShmBridge
    from ray_dynamic_batching_tpu.serve.replica import Replica

    fn = _resolve_factory(factory_spec)()
    replica = Replica(
        replica_id=replica_id,
        deployment=deployment,
        fn=fn,
        **replica_options,
    )
    replica.start()
    bridge = ShmBridge(shm_name, submit=replica.assign, create=True)
    bridge.start()
    # First beat doubles as the readiness signal: the shm ring exists now,
    # so the head may attach.
    while True:
        tmp = hb_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(time.time()))
        os.replace(tmp, hb_path)
        time.sleep(HEARTBEAT_INTERVAL_S)


# --- head-side adapter -----------------------------------------------------

class ProcessReplica:
    """A worker-node process behind the standard replica surface.

    Duck-typed to what the router, autoscaler, and controller state machine
    consume (``queue_len``/``accepting``/``assign``/``healthy``/``stop``/
    ``stats``), so a process node plugs into the existing control plane
    exactly like an in-process replica.

    Startup is LAZY: ``__init__`` only spawns the process (milliseconds),
    so the controller's lock hold stays bounded; the node reports
    ``accepting() == False`` until the worker's first heartbeat lands and
    the shm frontend attaches, and ``healthy()`` grants a startup grace of
    ``READY_TIMEOUT_S`` so the heal path doesn't replace a node that is
    still importing jax.

    One poller thread multiplexes every in-flight request (non-blocking
    ``try_result`` sweep) — no thread-per-request, and ``stop`` joins the
    poller BEFORE closing the shm handles (the use-after-free hazard
    ``shm_bridge.py`` documents).
    """

    def __init__(
        self,
        replica_id: str,
        deployment: str,
        factory_spec: str,
        workdir: str,
        max_ongoing_requests: int = 256,
        heartbeat_stale_s: float = 1.0,
        replica_options: Optional[Dict[str, Any]] = None,
        result_timeout_s: float = 30.0,
    ) -> None:
        self.replica_id = replica_id
        self.deployment = deployment
        self.max_ongoing_requests = max_ongoing_requests
        self.heartbeat_stale_s = heartbeat_stale_s
        self.result_timeout_s = result_timeout_s
        self.shm_name = f"rdbnode-{uuid.uuid4().hex[:10]}"
        os.makedirs(workdir, exist_ok=True)
        self.hb_path = os.path.join(
            workdir, replica_id.replace("#", "_") + ".hb"
        )
        if os.path.exists(self.hb_path):
            os.unlink(self.hb_path)

        self.frontend = None  # attaches on first heartbeat
        self._started_at = time.monotonic()
        # oid -> (request, deadline)
        self._pending: Dict[int, Any] = {}
        self._lock = threading.Lock()
        self._closed = False
        self._poller: Optional[threading.Thread] = None
        self.loaded_models: List[str] = []
        self.max_multiplexed_models = 8

        ctx = mp.get_context("spawn")  # never fork a jax-initialized head
        self.process = ctx.Process(
            target=_worker_main,
            args=(
                self.shm_name,
                self.hb_path,
                deployment,
                replica_id,
                factory_spec,
                dict(replica_options or {}),
            ),
            daemon=True,
            name=f"node-{replica_id}",
        )
        self.process.start()
        logger.info(
            "node %s spawning (pid %d, shm %s)",
            replica_id, self.process.pid, self.shm_name,
        )

    # --- readiness ---------------------------------------------------------
    def _try_attach(self) -> bool:
        """Attach the shm frontend once the worker's first beat confirms
        the ring exists. Cheap when already attached."""
        if self.frontend is not None:
            return True
        if not os.path.exists(self.hb_path):
            return False
        with self._lock:
            if self.frontend is None and not self._closed:
                from ray_dynamic_batching_tpu.engine.shm_bridge import (
                    ShmFrontend,
                )

                self.frontend = ShmFrontend(self.shm_name, create=False)
                logger.info("node %s ready", self.replica_id)
        return self.frontend is not None

    def wait_ready(self, timeout_s: float = READY_TIMEOUT_S) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._try_attach():
                return True
            if not self.process.is_alive():
                return False
            time.sleep(0.01)
        return False

    # --- router-facing surface -------------------------------------------
    def queue_len(self) -> int:
        with self._lock:
            return len(self._pending)

    def accepting(self) -> bool:
        return (
            not self._closed
            and self.process.is_alive()
            and self._try_attach()
            and self.queue_len() < self.max_ongoing_requests
        )

    def assign(self, request: Request) -> bool:
        if not self.accepting():
            return False
        deadline = time.monotonic() + min(
            self.result_timeout_s, request.slo_ms / 1000.0
        )
        with self._lock:
            if self._closed or self.frontend is None:
                return False
            try:
                oid = self.frontend.submit(
                    request.model, request.payload, request.slo_ms,
                    request_id=request.request_id,
                )
            except RuntimeError:
                return False  # ring/store full: retryable, router backs off
            self._pending[oid] = (request, deadline)
            if self._poller is None:
                self._poller = threading.Thread(
                    target=self._poll_loop,
                    name=f"poll-{self.replica_id}",
                    daemon=True,
                )
                self._poller.start()
            if request.multiplexed_model_id:
                record_multiplexed_model_locked(
                    self.loaded_models,
                    request.multiplexed_model_id,
                    self.max_multiplexed_models,
                )
        return True

    def record_multiplexed_model(self, model_id: str) -> None:
        with self._lock:
            record_multiplexed_model_locked(
                self.loaded_models, model_id, self.max_multiplexed_models
            )

    def _poll_loop(self) -> None:
        """Sweep every outstanding oid with non-blocking probes; one thread
        serves all in-flight requests of this node."""
        while not self._closed:
            now = time.monotonic()
            with self._lock:
                items = list(self._pending.items())
            if not items:
                time.sleep(0.005)
                continue
            for oid, (request, deadline) in items:
                outcome = None  # (kind, value)
                try:
                    found, value = self.frontend.try_result(oid)
                    if found:
                        outcome = ("ok", value)
                    elif now > deadline:
                        outcome = ("err", TimeoutError(
                            f"{self.replica_id}: no result for "
                            f"{request.request_id}"
                        ))
                except Exception as e:  # noqa: BLE001 — worker-side error
                    outcome = ("err", e)
                if outcome is None:
                    continue
                with self._lock:
                    self._pending.pop(oid, None)
                kind, value = outcome
                if kind == "ok":
                    request.fulfill(value)
                else:
                    request.reject(value)
            time.sleep(0.002)

    # --- controller-facing lifecycle --------------------------------------
    def start(self) -> None:
        pass  # the process spawned in __init__; readiness is lazy

    def healthy(self, stall_timeout_s: float = 60.0) -> bool:
        if self._closed or not self.process.is_alive():
            return False
        try:
            with open(self.hb_path) as f:
                last = float(f.read().strip() or 0)
        except (OSError, ValueError):
            # No first beat yet: healthy within the startup grace window.
            return (time.monotonic() - self._started_at) < READY_TIMEOUT_S
        return (time.time() - last) < max(
            self.heartbeat_stale_s, 3 * HEARTBEAT_INTERVAL_S
        )

    def drain_queue(self) -> List[Request]:
        return []  # queued work lives in the worker process

    def stop(self, timeout_s: float = 5.0, drain: bool = True) -> None:
        if self._closed:
            return
        if drain:
            deadline = time.monotonic() + timeout_s
            while self.queue_len() > 0 and time.monotonic() < deadline:
                time.sleep(0.01)
        self._closed = True
        self.process.terminate()
        self.process.join(timeout_s)
        if self.process.is_alive():
            self.process.kill()
            self.process.join(1.0)
        # The poller must be OUT of the C shm calls before close() frees
        # the mappings (shm_bridge.py:240 documents the segfault); leak
        # rather than close under a live thread.
        with self._lock:
            poller = self._poller
        if poller is not None:
            poller.join(2.0)
        exc = RequestDropped(f"{self.replica_id} stopped")
        with self._lock:
            leftovers = list(self._pending.values())
            self._pending.clear()
        for request, _deadline in leftovers:
            request.reject(exc)
        if poller is not None and poller.is_alive():
            logger.error(
                "node %s poller did not exit; leaking shm handles",
                self.replica_id,
            )
        elif self.frontend is not None:
            try:
                self.frontend.close(unlink=True)
            except Exception:  # noqa: BLE001 — shm may already be gone
                pass
        try:
            os.unlink(self.hb_path)
        except OSError:
            pass
        logger.info("node %s stopped", self.replica_id)

    def reconfigure(self, **kwargs) -> None:
        mor = kwargs.get("max_ongoing_requests")
        if mor is not None:
            self.max_ongoing_requests = mor

    def stats(self) -> dict:
        return {
            "ongoing": float(self.queue_len()),
            "pid": float(self.process.pid or -1),
            "alive": float(self.process.is_alive()),
        }


class ProcessDeployment:
    """Controller factory: every replica of the deployment is its own
    worker-node process (``make_replica`` protocol, like LLMDeployment)."""

    def __init__(
        self,
        factory_spec: str,
        workdir: str,
        heartbeat_stale_s: float = 1.0,
        replica_options: Optional[Dict[str, Any]] = None,
        result_timeout_s: float = 30.0,
    ) -> None:
        self.factory_spec = factory_spec
        self.workdir = workdir
        self.heartbeat_stale_s = heartbeat_stale_s
        self.replica_options = replica_options or {}
        self.result_timeout_s = result_timeout_s

    def make_replica(
        self, replica_id: str, config: Any, devices: Any = None,
    ) -> ProcessReplica:
        return ProcessReplica(
            replica_id=replica_id,
            deployment=config.name,
            factory_spec=self.factory_spec,
            workdir=self.workdir,
            max_ongoing_requests=config.max_ongoing_requests,
            heartbeat_stale_s=self.heartbeat_stale_s,
            replica_options=self.replica_options,
            result_timeout_s=self.result_timeout_s,
        )
