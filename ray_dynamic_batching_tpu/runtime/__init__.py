"""Runtime substrate: KV store, supervision, native bindings.

The (much smaller) TPU-native counterpart of Ray's C++ control plane —
GCS KV (runtime.kv), health/restart supervision, and ctypes bindings to the
native core (SURVEY.md §2.2 translation notes).
"""
