"""Job manager — submitted entrypoints as tracked subprocesses.

Re-creates the GCS job manager's role (``gcs_server/gcs_job_manager.cc``:
job table with lifecycle states, persisted to GCS storage) and the shape of
Ray's job-submission API (entrypoint command, captured logs, terminal
status polling). A job here is an OS process: the framework's units of
long-running work — profilers, workload drivers, batch generation — are
scripts, and the manager owns their lifecycle, log capture, and restart-
safe bookkeeping.

The job table lives in the KV store (``jobs:{id}``) exactly like the serve
controller's checkpoints, so a restarted manager recovers the table and
marks jobs whose processes died with it (ref: GCS restart reconciles its
job table from storage).
"""

from __future__ import annotations

import json
import os
import shlex
import subprocess
import threading
import time
import uuid
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_dynamic_batching_tpu.runtime.kv import KVStore
from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("jobs")

JOB_KEY = "jobs:{job_id}"

PENDING = "PENDING"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"
LOST = "LOST"  # manager restarted; the process is gone
TERMINAL = (SUCCEEDED, FAILED, STOPPED, LOST)


@dataclass
class JobInfo:
    job_id: str
    entrypoint: List[str]
    status: str = PENDING
    pid: Optional[int] = None
    return_code: Optional[int] = None
    submitted_at: float = field(default_factory=time.time)
    finished_at: Optional[float] = None
    log_path: str = ""
    metadata: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(asdict(self), sort_keys=True)

    @staticmethod
    def from_json(text: str) -> "JobInfo":
        return JobInfo(**json.loads(text))


class JobManager:
    """Submit/track/stop jobs; table checkpointed to the KV store."""

    def __init__(
        self,
        kv: Optional[KVStore] = None,
        workdir: str = "/tmp/rdb_jobs",
    ) -> None:
        self.kv = kv or KVStore()
        self.workdir = workdir
        os.makedirs(workdir, exist_ok=True)
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()

    # --- lifecycle ----------------------------------------------------------
    def submit(
        self,
        entrypoint: Union[str, Sequence[str]],
        job_id: Optional[str] = None,
        env: Optional[Dict[str, str]] = None,
        cwd: Optional[str] = None,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Launch the entrypoint as a tracked subprocess; returns job_id
        (ref JobSubmissionClient.submit_job shape)."""
        if isinstance(entrypoint, str):
            entrypoint = shlex.split(entrypoint)
        job_id = job_id or f"job-{uuid.uuid4().hex[:10]}"
        if self.get(job_id) is not None:
            raise ValueError(f"job {job_id!r} already exists")
        log_path = os.path.join(self.workdir, f"{job_id}.log")
        info = JobInfo(
            job_id=job_id,
            entrypoint=list(entrypoint),
            log_path=log_path,
            metadata=dict(metadata or {}),
        )
        log_f = open(log_path, "wb")
        try:
            proc = subprocess.Popen(
                entrypoint,
                stdout=log_f,
                stderr=subprocess.STDOUT,
                env={**os.environ, **(env or {})},
                cwd=cwd,
                start_new_session=True,  # stop() kills the whole group
            )
        except OSError as e:
            log_f.close()
            info.status = FAILED
            info.finished_at = time.time()
            info.metadata["error"] = str(e)
            self._save(info)
            raise
        finally:
            # Popen dup'd the fd (or we failed) — the parent's handle is
            # done either way.
            if not log_f.closed:
                log_f.close()
        info.status = RUNNING
        info.pid = proc.pid
        with self._lock:
            self._procs[job_id] = proc
        self._save(info)
        threading.Thread(
            target=self._reap, args=(job_id, proc), daemon=True,
            name=f"job-{job_id}",
        ).start()
        logger.info("job %s started: pid=%d %s", job_id, proc.pid, entrypoint)
        return job_id

    def _reap(self, job_id: str, proc: subprocess.Popen) -> None:
        rc = proc.wait()
        info = self.get(job_id)
        if info is None:
            return
        if info.status == STOPPED:
            info.return_code = rc
        else:
            info.status = SUCCEEDED if rc == 0 else FAILED
            info.return_code = rc
        info.finished_at = time.time()
        self._save(info)
        with self._lock:
            self._procs.pop(job_id, None)
        logger.info("job %s finished: rc=%d -> %s", job_id, rc, info.status)

    def stop(self, job_id: str, grace_s: float = 3.0) -> bool:
        """SIGTERM the job's process group, SIGKILL after the grace period
        (ref gcs_job_manager job termination)."""
        import signal

        with self._lock:
            proc = self._procs.get(job_id)
        info = self.get(job_id)
        if info is None:
            return False
        if proc is None or proc.poll() is not None:
            return False  # already terminal
        info.status = STOPPED
        self._save(info)
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            return True
        deadline = time.monotonic() + grace_s
        while proc.poll() is None and time.monotonic() < deadline:
            time.sleep(0.05)
        if proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        return True

    # --- introspection -------------------------------------------------------
    def get(self, job_id: str) -> Optional[JobInfo]:
        raw = self.kv.get(JOB_KEY.format(job_id=job_id))
        return JobInfo.from_json(raw) if raw else None

    def status(self, job_id: str) -> Optional[str]:
        info = self.get(job_id)
        return info.status if info else None

    def wait(self, job_id: str, timeout_s: float = 60.0,
             poll_s: float = 0.05) -> JobInfo:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            info = self.get(job_id)
            if info is not None and info.status in TERMINAL:
                return info
            time.sleep(poll_s)
        raise TimeoutError(f"job {job_id} not terminal within {timeout_s}s")

    def logs(self, job_id: str) -> str:
        info = self.get(job_id)
        if info is None or not info.log_path:
            return ""
        try:
            with open(info.log_path, "r", errors="replace") as f:
                return f.read()
        except OSError:
            return ""

    def list_jobs(self) -> List[JobInfo]:
        out = []
        for key in self.kv.keys("jobs:"):
            raw = self.kv.get(key)
            if raw:
                out.append(JobInfo.from_json(raw))
        return sorted(out, key=lambda j: j.submitted_at)

    # --- persistence ----------------------------------------------------------
    def _save(self, info: JobInfo) -> None:
        self.kv.put(JOB_KEY.format(job_id=info.job_id), info.to_json())

    def recover(self) -> List[str]:
        """After a manager restart: RUNNING jobs whose processes died with
        the old manager become LOST (ref GCS job-table reconciliation on
        restart). Returns the affected job ids."""
        lost = []
        for info in self.list_jobs():
            if info.status != RUNNING:
                continue
            alive = False
            if info.pid is not None:
                try:
                    os.kill(info.pid, 0)
                    alive = True
                except OSError:
                    alive = False
            if not alive:
                info.status = LOST
                info.finished_at = time.time()
                self._save(info)
                lost.append(info.job_id)
        return lost
