"""Cluster KV store — the control plane's persistence substrate.

TPU-native analogue of Ray's GCS key-value service
(``src/ray/gcs/gcs_server/gcs_kv_manager.cc``; Serve persists controller
checkpoints through it via ``serve/_private/storage/kv_store.py``). The
reference offers two backends — Redis (persistent, enables GCS fault
tolerance) and in-memory (``src/ray/gcs/store_client/redis_store_client.h``,
``in_memory_store_client.h``); here the equivalents are a process-local dict
and an atomic-rename JSON file that survives controller restarts.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional


class KVStore:
    """Thread-safe in-memory KV (ref in_memory_store_client)."""

    def __init__(self) -> None:
        self._data: Dict[str, str] = {}
        self._lock = threading.Lock()

    def get(self, key: str) -> Optional[str]:
        with self._lock:
            return self._data.get(key)

    def put(self, key: str, value: str) -> None:
        with self._lock:
            self._data[key] = value
            self._persist()

    def delete(self, key: str) -> bool:
        with self._lock:
            existed = self._data.pop(key, None) is not None
            if existed:
                self._persist()
            return existed

    def keys(self, prefix: str = "") -> List[str]:
        with self._lock:
            return sorted(k for k in self._data if k.startswith(prefix))

    def _persist(self) -> None:  # overridden by FileKVStore
        pass


class NativeKVStore(KVStore):
    """Backend on the C++ KV (native/src/rdb_native.cc): same string API
    plus versioned long-poll ``watch`` — the GCS-KV + long-poll pairing the
    reference splits across ``gcs_kv_manager.cc`` and
    ``serve/_private/long_poll.py``."""

    def __init__(self) -> None:
        from ray_dynamic_batching_tpu.runtime import native

        self._kv = native.KVStore()

    def get(self, key: str) -> Optional[str]:
        hit = self._kv.get(key)
        return None if hit is None else hit[0].decode()

    def get_versioned(self, key: str):
        hit = self._kv.get(key)
        return None if hit is None else (hit[0].decode(), hit[1])

    def put(self, key: str, value: str) -> None:
        self._kv.put(key, value.encode())

    def delete(self, key: str) -> bool:
        return self._kv.delete(key)

    def keys(self, prefix: str = "") -> List[str]:
        return sorted(self._kv.keys(prefix))

    def watch(self, key: str, have_version: int = 0,
              timeout_ms: int = 1000) -> int:
        """Block until the key's version advances; 0 on timeout."""
        return self._kv.watch(key, have_version, timeout_ms)

    def close(self) -> None:
        self._kv.close()


class FileKVStore(KVStore):
    """KV persisted to a JSON file via atomic rename (ref Redis-backed GCS
    storage enabling head-node fault tolerance)."""

    def __init__(self, path: str) -> None:
        super().__init__()
        self.path = path
        if os.path.exists(path):
            with open(path) as f:
                self._data.update(json.load(f))

    def _persist(self) -> None:
        tmp = self.path + ".tmp"
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(self._data, f)
        os.replace(tmp, self.path)
