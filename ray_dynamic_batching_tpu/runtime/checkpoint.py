"""Checkpoint / resume for model weights and train state.

The reference serves models straight from a Python registry and reloads
them on placement (``293-project/src/scheduler.py:507-515``) — its only
checkpointing is control-plane state in GCS KV (SURVEY.md §5). On TPU,
model placement means restoring weights into HBM with the right shardings,
so weight checkpointing is a first-class subsystem here:

- :class:`CheckpointManager` — step-indexed, keep-last-N, atomic
  (write-to-tmp + rename), orbax-style management over a numpy format.
- Sharding-aware restore: pass ``shardings`` (a pytree of NamedSharding,
  e.g. from ``mesh.param_shardings``) and leaves land on the mesh directly.
- Works for bare params or full train state (params + opt state + step).

Control-plane checkpointing (serve controller -> KV under a checkpoint key)
lives in :mod:`ray_dynamic_batching_tpu.serve.controller`; this module is
the data-plane (weights) side.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
from pathlib import Path
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_dynamic_batching_tpu.utils.logging import get_logger
from ray_dynamic_batching_tpu.utils.pytree import flatten_with_paths

logger = get_logger("checkpoint")

_STEP_DIR = re.compile(r"^step_(\d+)$")


def save_pytree(path: os.PathLike, tree: Any) -> None:
    """Single-checkpoint save: npz of leaves + json manifest, committed by
    rename. Overwriting an existing checkpoint swaps via two renames, so
    the vulnerable window is microseconds (not a whole rmtree)."""
    path = Path(path)
    tmp = path.with_name(path.name + ".tmp")
    if tmp.exists():
        shutil.rmtree(tmp)
    tmp.mkdir(parents=True)
    flat = flatten_with_paths(tree)  # raises on path collisions
    arrays = {k: np.asarray(v) for k, v in flat.items()}
    np.savez(tmp / "leaves.npz", **arrays)
    treedef = jax.tree_util.tree_structure(tree)
    (tmp / "manifest.json").write_text(
        json.dumps({
            "keys": list(arrays.keys()),
            "treedef": str(treedef),
            "dtypes": {k: str(v.dtype) for k, v in flat.items()},
        })
    )
    old = path.with_name(path.name + ".old")
    if old.exists():
        shutil.rmtree(old)
    if path.exists():
        path.rename(old)
    tmp.rename(path)
    if old.exists():
        shutil.rmtree(old)


def restore_pytree(
    path: os.PathLike,
    target: Any,
    shardings: Optional[Any] = None,
) -> Any:
    """Restore into the structure of ``target`` (an abstract or concrete
    pytree). With ``shardings`` (matching pytree of NamedSharding), leaves
    are placed on the mesh; otherwise on the default device."""
    path = Path(path)
    manifest = json.loads((path / "manifest.json").read_text())
    saved_dtypes = manifest.get("dtypes", {})
    flat_target = flatten_with_paths(target)  # ordered: flatten order
    flat_shard = flatten_with_paths(shardings) if shardings is not None else {}
    leaves = []
    with np.load(path / "leaves.npz") as data:
        missing = [k for k in flat_target if k not in data]
        if missing:
            raise KeyError(
                f"checkpoint {path} missing {len(missing)} leaves, "
                f"first: {missing[:3]}"
            )
        for key, tgt in flat_target.items():
            arr = data[key]
            if arr.dtype.kind == "V" and key in saved_dtypes:
                # custom float (bfloat16 etc): npz round-trips it as raw
                # void bytes; re-view with the recorded dtype
                arr = arr.view(jnp.dtype(saved_dtypes[key]))
            arr = arr.astype(getattr(tgt, "dtype", arr.dtype))
            if key in flat_shard:
                leaves.append(jax.device_put(arr, flat_shard[key]))
            else:
                leaves.append(jnp.asarray(arr))
    treedef = jax.tree_util.tree_structure(target)
    return jax.tree_util.tree_unflatten(treedef, leaves)


class CheckpointManager:
    """Step-indexed checkpoint directory with retention — orbax-style
    management (step dirs, keep-last-N gc, atomic rename-commit) over a
    self-contained numpy format (npz leaves + json manifest), so restores
    have no library-version coupling and custom float dtypes (bfloat16)
    round-trip by raw view.

    Layout: ``root/step_<N>/{leaves.npz,manifest.json,metadata.json}``;
    ``latest_step()`` finds the newest.
    """

    def __init__(self, root: os.PathLike, max_to_keep: int = 3):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.max_to_keep = max_to_keep
        self._lock = threading.Lock()

    # --- introspection ----------------------------------------------------
    def steps(self) -> List[int]:
        out = []
        for child in self.root.iterdir():
            m = _STEP_DIR.match(child.name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def _dir(self, step: int) -> Path:
        return self.root / f"step_{step}"

    # --- save / restore ---------------------------------------------------
    def save(self, step: int, tree: Any, metadata: Optional[Dict] = None) -> Path:
        with self._lock:
            d = self._dir(step)
            save_pytree(d, tree)
            if metadata is not None:
                (d / "metadata.json").write_text(json.dumps(metadata))
            self._gc()
            return d

    def restore(
        self,
        target: Any,
        step: Optional[int] = None,
        shardings: Optional[Any] = None,
    ) -> Any:
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.root}")
        return restore_pytree(self._dir(step), target, shardings)

    def metadata(self, step: Optional[int] = None) -> Optional[Dict]:
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        meta = self._dir(step) / "metadata.json"
        return json.loads(meta.read_text()) if meta.exists() else None

    def delete(self, step: int) -> None:
        with self._lock:
            d = self._dir(step)
            if d.exists():
                shutil.rmtree(d)

    def _gc(self) -> None:
        steps = self.steps()
        while len(steps) > self.max_to_keep:
            victim = steps.pop(0)
            shutil.rmtree(self._dir(victim), ignore_errors=True)
            logger.info("checkpoint gc: removed step_%d", victim)


def save_train_state(
    manager: CheckpointManager,
    step: int,
    params: Any,
    opt_state: Any = None,
    **metadata: Any,
) -> Path:
    """Convenience: params (+ optional optimizer state) under one step."""
    tree = {"params": params}
    if opt_state is not None:
        tree["opt_state"] = opt_state
    return manager.save(step, tree, metadata={"step": step, **metadata})


def restore_train_state(
    manager: CheckpointManager,
    params_target: Any,
    opt_state_target: Any = None,
    step: Optional[int] = None,
    params_shardings: Optional[Any] = None,
    opt_state_shardings: Optional[Any] = None,
):
    """Inverse of :func:`save_train_state`; returns (params, opt_state|None,
    step restored)."""
    step = step if step is not None else manager.latest_step()
    if step is None:
        raise FileNotFoundError(f"no checkpoints under {manager.root}")
    target = {"params": params_target}
    if opt_state_target is not None:
        target["opt_state"] = opt_state_target
    # leaves with no sharding entry restore unsharded; either shardings
    # argument may be given independently of the other
    shardings = {}
    if params_shardings is not None:
        shardings["params"] = params_shardings
    if opt_state_shardings is not None and opt_state_target is not None:
        shardings["opt_state"] = opt_state_shardings
    restored = restore_pytree(
        manager._dir(step), target, shardings or None
    )
    return restored["params"], restored.get("opt_state"), step
