"""Pipeline parallelism: GPipe-style microbatched stages over the ``pp`` axis.

Pipeline parallelism is absent from the reference (SURVEY.md §2.4: "no
pipeline engine"; its closest primitive is compiled actor-to-actor DAG
channels, ``python/ray/dag/``). TPU-first design: instead of actor pipelines
with NCCL channels, the stages live on a ``pp`` mesh axis inside ONE jitted
SPMD program — a partial-manual ``shard_map`` is manual over ``pp`` only, so
each device runs its stage's layers while dp/tp/ep sharding inside the stage
stays under GSPMD. Activations hop stage→stage via ``ppermute`` over ICI;
the classic GPipe schedule (M microbatches, S stages, M + S - 1 ticks) keeps
every shape static so XLA compiles one program for the whole pipeline.

The transformer is split layer-wise: embedding and LM head stay outside the
pipeline (replicated/tp-sharded under GSPMD); the L decoder layers are
stacked into leading-dim arrays and split contiguously over stages (device s
holds layers [s*L/S, (s+1)*L/S)).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ray_dynamic_batching_tpu.models.causal_lm import CausalLM
from ray_dynamic_batching_tpu.models.decoder import DecoderLayer, RMSNorm
from ray_dynamic_batching_tpu.parallel.mesh import _feasible_spec


class PipelinedCausalLM:
    """A CausalLM split into pipeline stages over the mesh's ``pp`` axis.

    Same ``apply(params, tokens, attn_mask) -> logits`` contract as
    :class:`CausalLM`, but params are ``{"outer": ..., "layers": ...}`` with
    the layer stack stacked along a leading [L] dim sharded over ``pp``.
    """

    def __init__(self, model: CausalLM, mesh: Mesh, n_microbatches: int = 2):
        cfg = model.cfg
        S = mesh.shape.get("pp", 1)
        if cfg.num_layers % max(S, 1) != 0:
            raise ValueError(
                f"{cfg.num_layers} layers not divisible into {S} stages"
            )
        if mesh.shape.get("sp", 1) != 1:
            raise ValueError("pipeline stages require sp=1 (dense attention)")
        self.model = model
        self.cfg = cfg
        self.mesh = mesh
        self.n_stages = S
        self.n_microbatches = n_microbatches
        self.dtype = model.dtype
        self._layer_mod = DecoderLayer(cfg, dtype=model.dtype)
        self._shardings = None  # memoized (init eval_shape is not free)

    # --- params ----------------------------------------------------------
    def init(self, rng: jax.Array) -> Dict[str, Any]:
        full = self.model.init(rng)
        return self.split_params(full)

    def split_params(self, full: Dict[str, Any]) -> Dict[str, Any]:
        """Restructure flat model params into outer + stacked layers [L]."""
        p = dict(full["params"])
        layers = [p.pop(f"layer{i}") for i in range(self.cfg.num_layers)]
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)
        return {"outer": p, "layers": stacked}

    def merge_params(self, split: Dict[str, Any]) -> Dict[str, Any]:
        """Inverse of :meth:`split_params` (for checkpoint interchange)."""
        p = dict(split["outer"])
        L = self.cfg.num_layers
        for i in range(L):
            p[f"layer{i}"] = jax.tree_util.tree_map(
                lambda x: x[i], split["layers"]
            )
        return {"params": p}

    def shardings(self, abstract: Optional[Dict[str, Any]] = None):
        """NamedShardings: stacked layers get P("pp", <model TP/EP rule>);
        outer params follow the model's rules."""
        caller_abstract = abstract
        if abstract is None and self._shardings is not None:
            return self._shardings
        if abstract is None:
            abstract = jax.eval_shape(lambda: self.init(jax.random.PRNGKey(0)))
        rules = self.model.sharding_rules()

        def spec_for(path: str, prefix_pp: bool) -> P:
            for pat, spec in rules:
                if re.search(pat, path):
                    return P("pp", *spec) if prefix_pp else spec
            return P("pp") if prefix_pp else P()

        from ray_dynamic_batching_tpu.utils.pytree import path_str

        def tree_specs(tree, prefix_pp: bool):
            flat = jax.tree_util.tree_flatten_with_path(tree)[0]
            paths = ["/" + path_str(path) for path, _ in flat]
            # degrade indivisible dims to replication, like mesh.param_shardings
            specs = [
                _feasible_spec(spec_for(p, prefix_pp), leaf.shape, self.mesh)
                for p, (_, leaf) in zip(paths, flat)
            ]
            treedef = jax.tree_util.tree_structure(tree)
            return jax.tree_util.tree_unflatten(treedef, specs)

        result = {
            "outer": jax.tree_util.tree_map(
                lambda s: NamedSharding(self.mesh, s),
                tree_specs(abstract["outer"], False),
            ),
            "layers": jax.tree_util.tree_map(
                lambda s: NamedSharding(self.mesh, s),
                tree_specs(abstract["layers"], True),
            ),
        }
        if caller_abstract is None:  # don't poison the memo with a
            self._shardings = result  # caller-supplied tree
        return result

    def shard_init(self, rng: jax.Array) -> Dict[str, Any]:
        params = self.init(rng)
        return jax.device_put(params, self.shardings())

    # --- forward ---------------------------------------------------------
    def _embed(self, outer, tokens, positions):
        cfg = self.cfg
        embed = nn.Embed(
            cfg.vocab_size, cfg.d_model, dtype=self.dtype,
            param_dtype=jnp.float32, name="tok_embed",
        )
        x = embed.apply({"params": outer["tok_embed"]}, tokens)
        if cfg.pos == "learned":
            pos_embed = nn.Embed(
                cfg.max_seq_len, cfg.d_model, dtype=self.dtype,
                param_dtype=jnp.float32,
            )
            x = x + pos_embed.apply({"params": outer["pos_embed"]}, positions)
        return x

    def _head(self, outer, x):
        cfg = self.cfg
        if cfg.norm == "rms":
            x = RMSNorm().apply({"params": outer["final_norm"]}, x)
        else:
            x = nn.LayerNorm(dtype=jnp.float32).apply(
                {"params": outer["final_norm"]}, x
            )
        if cfg.tie_embeddings:
            embed = nn.Embed(
                cfg.vocab_size, cfg.d_model, dtype=self.dtype,
                param_dtype=jnp.float32,
            )
            return embed.apply(
                {"params": outer["tok_embed"]},
                x.astype(jnp.float32),
                method=nn.Embed.attend,
            )
        return nn.Dense(
            cfg.vocab_size, use_bias=False, dtype=jnp.float32,
            param_dtype=jnp.float32,
        ).apply({"params": outer["lm_head"]}, x)

    def _stage_fn(self, stage_layers, x, positions, token_mask):
        """Apply this stage's Lp layers (leading dim) via lax.scan; returns
        (x, summed MoE aux loss for the stage's layers)."""

        def body(h, layer_params):
            (h, _), state = self._layer_mod.apply(
                {"params": layer_params}, h, positions, None, None, token_mask,
                mutable=["intermediates"],
            )
            aux_leaves = jax.tree_util.tree_leaves(
                state.get("intermediates", {})
            )
            aux = (
                sum(jnp.asarray(a).sum() for a in aux_leaves)
                if aux_leaves
                else jnp.zeros((), jnp.float32)
            )
            return h, aux

        x, aux = jax.lax.scan(body, x, stage_layers)
        return x, aux.sum()

    def apply(
        self, params: Dict[str, Any], tokens: jax.Array, attn_mask: jax.Array
    ) -> jax.Array:
        return self.apply_with_aux(params, tokens, attn_mask)[0]

    def apply_with_aux(
        self, params: Dict[str, Any], tokens: jax.Array, attn_mask: jax.Array
    ) -> Tuple[jax.Array, jax.Array]:
        """Pipelined forward: embed → S stages over pp → head. [B,T]→[B,T,V].

        Also returns the MoE load-balance aux loss summed over layers and
        averaged over microbatches (0 for dense models)."""
        B, T = tokens.shape
        M, S = self.n_microbatches, self.n_stages
        if B % M != 0:
            raise ValueError(f"batch {B} not divisible into {M} microbatches")
        positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
        x = self._embed(params["outer"], tokens, positions)

        if S == 1:
            y, aux = self._stage_fn(
                params["layers"], x, positions, attn_mask
            )
            return self._head(params["outer"], y), aux

        mb = B // M
        x_mb = x.reshape(M, mb, T, -1)
        pos_mb = positions.reshape(M, mb, T)
        msk_mb = attn_mask.reshape(M, mb, T)

        stage_fn = self._stage_fn

        def pipe(layers_stage, x_mb, pos_mb, msk_mb):
            stage = jax.lax.axis_index("pp")
            buf = jnp.zeros_like(x_mb[0])
            outs = jnp.zeros_like(x_mb)
            aux = jnp.zeros((), jnp.float32)
            for t in range(M + S - 1):
                # stage s works on microbatch t - s this tick
                k_idx = jnp.clip(t - stage, 0, M - 1)
                pos_t = jax.lax.dynamic_index_in_dim(
                    pos_mb, k_idx, keepdims=False
                )
                msk_t = jax.lax.dynamic_index_in_dim(
                    msk_mb, k_idx, keepdims=False
                )
                inp = jnp.where(stage == 0, x_mb[min(t, M - 1)], buf)
                out, aux_t = stage_fn(layers_stage, inp, pos_t, msk_t)
                # idle ticks (warmup/drain) compute on garbage — mask their
                # aux contribution so the router loss sees real tokens only
                active = jnp.logical_and(t - stage >= 0, t - stage < M)
                aux = aux + jnp.where(active, aux_t, 0.0)
                w = t - (S - 1)
                if w >= 0:  # last stage emits microbatch w
                    outs = outs.at[w].set(
                        jnp.where(stage == S - 1, out, outs[w])
                    )
                if t != M + S - 2:
                    buf = jax.lax.ppermute(
                        out, "pp", [(i, i + 1) for i in range(S - 1)]
                    )
            # only the last stage holds real outputs; broadcast them.
            # aux: each stage contributes its own layers' loss once per
            # microbatch — psum totals over stages, /M averages microbatches
            return jax.lax.psum(outs, "pp"), jax.lax.psum(aux, "pp") / M

        y, aux = jax.shard_map(
            pipe,
            mesh=self.mesh,
            in_specs=(P("pp"), P(), P(), P()),
            out_specs=(P(), P()),
            axis_names=frozenset({"pp"}),
        )(params["layers"], x_mb, pos_mb, msk_mb)
        y = y.reshape(B, T, -1)
        return self._head(params["outer"], y), aux


def make_pp_train_step(
    pmodel: PipelinedCausalLM,
    optimizer: optax.GradientTransformation,
) -> Callable:
    """Compiled pipelined train step (same contract as make_train_step)."""
    mesh = pmodel.mesh

    from ray_dynamic_batching_tpu.parallel.train import causal_lm_loss

    def loss_fn(params, tokens, attn_mask):
        # PipelinedCausalLM satisfies causal_lm_loss's model contract
        # (.cfg, .apply, .apply_with_aux) — one loss definition, two paths
        return causal_lm_loss(pmodel, params, tokens, attn_mask)

    def step(params, opt_state, tokens, attn_mask):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, attn_mask)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    p_shard = pmodel.shardings()
    data_shard = NamedSharding(mesh, P("dp", None))
    return jax.jit(
        step,
        in_shardings=(p_shard, None, data_shard, data_shard),
        donate_argnums=(0, 1),
    )


def make_pp_train_state(
    pmodel: PipelinedCausalLM,
    optimizer: optax.GradientTransformation,
    rng: Optional[jax.Array] = None,
) -> Tuple[Any, Any]:
    params = pmodel.shard_init(rng if rng is not None else jax.random.PRNGKey(0))
    opt_state = jax.jit(optimizer.init)(params)  # rdb-lint: disable=jit-retrace-hazard (one-shot optimizer-state init at train-state construction; jit only propagates stage shardings to the moment buffers)
    return params, opt_state
