"""Collective communication API — the ``ray.util.collective`` equivalent.

Mirrors the reference's collective surface (group management at
``python/ray/util/collective/collective.py:40-151``; ops allreduce /
allgather / reducescatter / broadcast / send / recv / barrier at
``collective.py:258-651``, NCCL backend ``nccl_collective_group.py:128``,
Gloo backend ``gloo_collective_group.py``) with TPU-native execution:
every op is a ``shard_map`` collective over one or more mesh axes, compiled
by XLA onto ICI (intra-slice) or DCN (when the mesh spans hosts via
``mesh.multihost_init`` — the coordinator plays the reference's GCS-address
role). There is no NCCL/Gloo split: the same program rides whichever fabric
the mesh's devices sit on.

Data model: NCCL-style *stacked* semantics. A group of size G works on
arrays whose leading dim is G, sharded over the group's mesh axes — slot g
is "rank g's buffer". This keeps per-rank semantics identical to the
reference while remaining one global jittable array.

Ops compose under ``jit``: calling them inside a jitted function emits the
collective into the surrounding program (no separate launch per op, unlike
NCCL group calls).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

Axes = Union[str, Tuple[str, ...]]

_REDUCE_OPS = ("sum", "max", "min", "mean")


class CollectiveGroup:
    """A named collective group over one or more mesh axes.

    The group's world size is the product of its axis sizes (reference
    analogue: the actor list passed to ``create_collective_group``,
    ``collective.py:120``)."""

    def __init__(self, mesh: Mesh, axes: Axes = ("dp",), name: str = "default"):
        self.mesh = mesh
        self.axes: Tuple[str, ...] = (axes,) if isinstance(axes, str) else tuple(axes)
        for ax in self.axes:
            if ax not in mesh.shape:
                raise ValueError(f"mesh has no axis {ax!r}")
        self.name = name

    @property
    def size(self) -> int:
        n = 1
        for ax in self.axes:
            n *= self.mesh.shape[ax]
        return n

    # --- helpers ---------------------------------------------------------
    def _spec(self) -> P:
        ax = self.axes[0] if len(self.axes) == 1 else self.axes
        return P(ax)

    def _shard_map(self, body, n_in: int, out_specs=None):
        spec = self._spec()
        return jax.shard_map(
            body,
            mesh=self.mesh,
            in_specs=tuple(spec for _ in range(n_in)),
            out_specs=spec if out_specs is None else out_specs,
            axis_names=frozenset(self.axes),
        )

    def _check_leading(self, x: jax.Array) -> None:
        if x.ndim == 0 or x.shape[0] % self.size != 0:
            raise ValueError(
                f"leading dim of {x.shape} must be divisible by group size "
                f"{self.size} (stacked per-rank layout)"
            )

    def device_put(self, x: jax.Array) -> jax.Array:
        """Place a stacked [G, ...] array with slot g on rank g's device."""
        self._check_leading(x)
        return jax.device_put(
            x, NamedSharding(self.mesh, self._spec())
        )

    def rank_index(self) -> jax.Array:
        """Per-rank linear index, as a stacked [G] array (for tests/debug)."""

        def body(x):
            idx = jnp.zeros((), jnp.int32)
            for ax in self.axes:
                idx = idx * self.mesh.shape[ax] + jax.lax.axis_index(ax)
            return x + idx[None]

        return self._shard_map(body, 1)(
            self.device_put(jnp.zeros((self.size,), jnp.int32))
        )

    # --- ops (reference: collective.py:258-651) --------------------------
    def allreduce(self, x: jax.Array, op: str = "sum") -> jax.Array:
        """Every rank ends with reduce(all ranks' buffers). [G,...] -> [G,...]."""
        if op not in _REDUCE_OPS:
            raise ValueError(f"unknown reduce op {op!r}; one of {_REDUCE_OPS}")
        self._check_leading(x)
        ax = self.axes if len(self.axes) > 1 else self.axes[0]

        def body(v):
            if op == "sum":
                return jax.lax.psum(v, ax)
            if op == "max":
                return jax.lax.pmax(v, ax)
            if op == "min":
                return jax.lax.pmin(v, ax)
            return jax.lax.pmean(v, ax)

        return self._shard_map(body, 1)(x)

    def _check_rank(self, rank: int, what: str) -> None:
        if not 0 <= rank < self.size:
            raise ValueError(
                f"{what}={rank} out of range for group of size {self.size}"
            )

    def reduce(self, x: jax.Array, root: int = 0, op: str = "sum") -> jax.Array:
        """Like allreduce but only rank ``root`` keeps the result; other
        slots are zero (reference semantics: result lives on dst_rank)."""
        self._check_rank(root, "root")
        full = self.allreduce(x, op)

        def body(red):
            keep = self._linear_index() == root
            return jnp.where(keep, red, jnp.zeros_like(red))

        return self._shard_map(body, 1)(full)

    def allgather(self, x: jax.Array) -> jax.Array:
        """Concatenate all ranks' buffers, result replicated to every rank.

        Stacked view: [G, ...] sharded -> [G, ...] fully replicated. A real
        all_gather collective (not a resharding), so it composes under jit."""
        self._check_leading(x)
        ax = self.axes if len(self.axes) > 1 else self.axes[0]

        G = self.size
        chunk = x.shape[0] // G

        def body(v):  # v [chunk, ...] local
            # gather-as-psum: scatter the local chunk into its slot of a
            # zero buffer and sum — psum's output is provably replicated
            # under the varying-or-replicated checker (all_gather's is not,
            # which would reject out_specs P() in partial-manual mode)
            idx = self._linear_index()
            buf = jnp.zeros((G * chunk,) + v.shape[1:], v.dtype)
            start = (idx * chunk,) + (0,) * (v.ndim - 1)
            buf = jax.lax.dynamic_update_slice(buf, v, start)
            return jax.lax.psum(buf, ax)

        return jax.shard_map(
            body,
            mesh=self.mesh,
            in_specs=(self._spec(),),
            out_specs=P(),
            axis_names=frozenset(self.axes),
        )(x)

    def reducescatter(self, x: jax.Array, op: str = "sum") -> jax.Array:
        """Each rank's buffer is pre-chunked [G, chunk]; rank g receives
        reduce over ranks of chunk g. [G, G, ...] -> [G, ...]."""
        if x.ndim < 2 or x.shape[0] != self.size or x.shape[1] % self.size:
            raise ValueError(
                f"reducescatter expects [G, G*chunk, ...] with G == "
                f"{self.size}, got {x.shape}"
            )
        ax = self.axes if len(self.axes) > 1 else self.axes[0]
        if op != "sum":
            raise NotImplementedError("reducescatter supports op='sum'")

        def body(v):  # v [1, G, ...] local
            # tiled psum_scatter keeps the chunk dim: [G, ...] -> [G/n, ...],
            # which is exactly this rank's [1, ...] output slot
            return jax.lax.psum_scatter(
                v[0], ax, scatter_dimension=0, tiled=True
            )

        return self._shard_map(body, 1)(x)

    def broadcast(self, x: jax.Array, root: int = 0) -> jax.Array:
        """All ranks end with rank ``root``'s buffer. [G,...] -> [G,...]."""
        self._check_rank(root, "root")
        self._check_leading(x)
        ax = self.axes if len(self.axes) > 1 else self.axes[0]

        def body(v):
            idx = self._linear_index()
            contrib = jnp.where(idx == root, v, jnp.zeros_like(v))
            return jax.lax.psum(contrib, ax)

        return self._shard_map(body, 1)(x)

    def permute(self, x: jax.Array, perm: Sequence[Tuple[int, int]]) -> jax.Array:
        """Point-to-point: for each (src, dst), dst receives src's buffer
        (the send/recv pair of the reference, ``collective.py:539-651``).
        Ranks not a destination receive zeros."""
        self._check_leading(x)
        if len(self.axes) != 1:
            raise NotImplementedError("permute requires a single-axis group")
        for src, dst in perm:
            self._check_rank(src, "src")
            self._check_rank(dst, "dst")
        ax = self.axes[0]
        perm = list(perm)

        def body(v):
            return jax.lax.ppermute(v, ax, perm)

        return self._shard_map(body, 1)(x)

    def send_recv(self, x: jax.Array, src: int, dst: int) -> jax.Array:
        """One send/recv pair: dst's slot gets src's buffer; all other
        slots get zeros."""
        return self.permute(x, [(src, dst)])

    def barrier(self) -> None:
        """Synchronize the group: a scalar psum every rank must reach
        (reference: ``collective.py:651``). Blocks until executed."""
        ax = self.axes if len(self.axes) > 1 else self.axes[0]

        def body(v):
            return jax.lax.psum(v, ax)

        out = self._shard_map(body, 1)(
            self.device_put(jnp.zeros((self.size,), jnp.int32))
        )
        jax.block_until_ready(out)

    def _linear_index(self):
        idx = jnp.zeros((), jnp.int32)
        for ax in self.axes:
            idx = idx * self.mesh.shape[ax] + jax.lax.axis_index(ax)
        return idx


# --- module-level group registry (reference: collective.py:40-151) --------

_GROUPS: Dict[str, CollectiveGroup] = {}
_LOCK = threading.Lock()


def init_collective_group(
    mesh: Mesh, axes: Axes = ("dp",), group_name: str = "default"
) -> CollectiveGroup:
    """Create and register a named group (``collective.py:40``)."""
    group = CollectiveGroup(mesh, axes, group_name)
    with _LOCK:
        if group_name in _GROUPS:
            raise ValueError(f"collective group {group_name!r} already exists")
        _GROUPS[group_name] = group
    return group


def get_collective_group(group_name: str = "default") -> CollectiveGroup:
    with _LOCK:
        if group_name not in _GROUPS:
            raise KeyError(f"no collective group {group_name!r}")
        return _GROUPS[group_name]


def destroy_collective_group(group_name: str = "default") -> None:
    """(``collective.py:151``)"""
    with _LOCK:
        _GROUPS.pop(group_name, None)


def is_group_initialized(group_name: str = "default") -> bool:
    with _LOCK:
        return group_name in _GROUPS


def allreduce(x, op="sum", group_name="default"):
    return get_collective_group(group_name).allreduce(x, op)


def allgather(x, group_name="default"):
    return get_collective_group(group_name).allgather(x)


def reducescatter(x, op="sum", group_name="default"):
    return get_collective_group(group_name).reducescatter(x, op)


def broadcast(x, root=0, group_name="default"):
    return get_collective_group(group_name).broadcast(x, root)


def send_recv(x, src, dst, group_name="default"):
    return get_collective_group(group_name).send_recv(x, src, dst)


def barrier(group_name="default"):
    get_collective_group(group_name).barrier()
