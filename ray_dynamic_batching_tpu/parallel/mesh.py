"""Device-mesh management — the framework's ICI/DCN substrate.

TPU-native replacement for the reference's collective-group machinery
(``python/ray/util/collective/collective.py:40-151`` — named NCCL/Gloo groups
over actors; ``nccl_collective_group.py:128`` allreduce): instead of explicit
collective calls between actors, the framework lays models out over a
``jax.sharding.Mesh`` and lets XLA insert ``psum``/``all_gather``/
``reduce_scatter`` over ICI under ``jit`` (SURVEY.md §2.4 translation table).

Axes (logical → physical):
- ``dp``: data/replica parallelism (the reference's replica scaling axis)
- ``tp``: tensor parallelism (BASELINE config 4: Llama TP=4 over ICI)
- ``sp``: sequence/context parallelism for long inputs (ring attention)

Multi-host (DCN) boot mirrors the reference's group bootstrap: JAX's
distributed runtime plays the GCS-address role (SURVEY.md §2.4).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ray_dynamic_batching_tpu.models.base import ServableModel, param_path_specs
from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("mesh")

AXIS_ORDER = ("dp", "pp", "sp", "tp", "ep")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Axis sizes for the five-way parallelism mesh.

    dp = data/replica, pp = pipeline stages, sp = sequence (ring attention),
    tp = tensor, ep = expert (MoE). Axes default to 1 (inactive)."""

    dp: int = 1
    sp: int = 1
    tp: int = 1
    pp: int = 1
    ep: int = 1

    @property
    def n_devices(self) -> int:
        return self.dp * self.pp * self.sp * self.tp * self.ep

    @staticmethod
    def auto(n_devices: int, tp: Optional[int] = None, sp: int = 1) -> "MeshConfig":
        """Pick dp x sp x tp for a device count: prefer TP up to 4 (one ICI
        hop on v5e trays), data-parallel beyond."""
        if n_devices % sp != 0:
            raise ValueError(f"sp={sp} does not divide {n_devices} devices")
        if tp is None:
            tp = 1
            for cand in (4, 2):
                if n_devices % (cand * sp) == 0:
                    tp = cand
                    break
        if n_devices % (tp * sp) != 0:
            raise ValueError(
                f"tp={tp} x sp={sp} does not divide {n_devices} devices"
            )
        return MeshConfig(dp=n_devices // (tp * sp), sp=sp, tp=tp)


def build_mesh(
    config: MeshConfig, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    n = config.n_devices
    if len(devices) < n:
        raise ValueError(
            f"mesh needs {n} devices (dp={config.dp} pp={config.pp} "
            f"sp={config.sp} tp={config.tp} ep={config.ep}) but only "
            f"{len(devices)} available"
        )
    arr = np.array(devices[:n]).reshape(
        config.dp, config.pp, config.sp, config.tp, config.ep
    )
    return Mesh(arr, AXIS_ORDER)


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    devices = [device] if device is not None else jax.devices()[:1]
    return Mesh(np.array(devices).reshape(1, 1, 1, 1, 1), AXIS_ORDER)


# --- sharding helpers -----------------------------------------------------

def _feasible_spec(spec: P, shape: Tuple[int, ...], mesh: Mesh) -> P:
    """Drop mesh axes that don't divide the corresponding dim (e.g. GQA with
    kv_heads < tp replicates the kv projections instead of erroring)."""
    out = []
    for i, ax in enumerate(spec):
        if ax is None:
            out.append(None)
            continue
        axes = ax if isinstance(ax, tuple) else (ax,)
        size = 1
        for a in axes:
            size *= mesh.shape[a]
        if i < len(shape) and shape[i] % size == 0:
            out.append(ax)
        else:
            out.append(None)
    return P(*out)


def param_shardings(mesh: Mesh, model: ServableModel, params: Any) -> Any:
    """NamedShardings for every param leaf from the model's sharding rules
    (infeasible axes degrade to replication rather than erroring)."""
    specs = param_path_specs(model, params)
    return jax.tree_util.tree_map(
        lambda leaf, s: NamedSharding(mesh, _feasible_spec(s, leaf.shape, mesh)),
        params,
        specs,
    )


def shard_params(mesh: Mesh, model: ServableModel, params: Any) -> Any:
    """Place params on the mesh per the model's rules (TP weights split over
    the tp axis, everything else replicated)."""
    shardings = param_shardings(mesh, model, params)
    return jax.device_put(params, shardings)


def _sharded_alloc(mesh: Mesh, make_fn, spec) -> Any:
    """Allocate a cache pytree DIRECTLY onto the mesh per its pspec
    dataclass. The buffers never materialize unsharded on any single
    device — a pool sized to fit only when split over the tp chips must
    not OOM chip 0 on the way in."""
    import dataclasses

    shapes = jax.eval_shape(make_fn)

    def _shard(field_spec, field_shape):
        if field_shape is None:  # absent optional plane (e.g. scales)
            return None
        return NamedSharding(
            mesh, _feasible_spec(field_spec, field_shape.shape, mesh)
        )

    # Field-generic so every cache plane — including a quantized cache's
    # scale planes — gets a sharding; a hand-listed constructor here
    # silently dropped new planes once already.
    shardings = type(shapes)(**{
        f.name: _shard(getattr(spec, f.name, None), getattr(shapes, f.name))
        for f in dataclasses.fields(shapes)
    })
    return jax.jit(make_fn, out_shardings=shardings)()  # rdb-lint: disable=jit-retrace-hazard (one-shot cache allocation at engine construction — jit only carries out_shardings so GSPMD places the buffers; never called on the serving path)


def make_sharded_cache(
    mesh: Mesh, model: Any, num_slots: int, max_len: Optional[int] = None
) -> Any:
    """Allocate a model's KV cache onto the mesh per its ``cache_pspec``
    (kv heads over tp)."""
    return _sharded_alloc(
        mesh, lambda: model.make_cache(num_slots, max_len),
        model.cache_pspec(),
    )


def make_sharded_paged_cache(
    mesh: Mesh, model: Any, num_slots: int, num_pages: int,
    page_size: int, max_len: int,
) -> Any:
    """Allocate a model's PAGED KV pool onto the mesh per its
    ``paged_cache_pspec`` (ROADMAP item 2): page planes split on the
    kv-head dim like the slab cache, page table + lengths replicated —
    page indices are shard-invariant, so the host-side free-list
    allocator stays replica-global and untouched."""
    return _sharded_alloc(
        mesh,
        lambda: model.make_paged_cache(
            num_slots, num_pages, page_size, max_len
        ),
        model.paged_cache_pspec(),
    )


def replicate(mesh: Mesh, tree: Any) -> Any:
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


def batch_sharding(mesh: Mesh, extra_dims: int = 1) -> NamedSharding:
    """Shard the leading batch axis over dp; remaining dims replicated."""
    return NamedSharding(mesh, P("dp", *([None] * extra_dims)))


def seq_sharding(mesh: Mesh, extra_dims: int = 0) -> NamedSharding:
    """[B, T, ...] with batch over dp and sequence over sp (long-context)."""
    return NamedSharding(mesh, P("dp", "sp", *([None] * extra_dims)))


# --- multi-host boot (DCN) ------------------------------------------------

def multihost_init(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> Dict[str, int]:
    """Initialize JAX's distributed runtime across hosts (DCN). The
    coordinator plays the role the reference's GCS address plays for
    collective-group bootstrap (SURVEY.md §2.4). No-op when single-process.
    """
    if num_processes is None or num_processes <= 1:
        return {"process_index": 0, "process_count": 1}
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
    }
