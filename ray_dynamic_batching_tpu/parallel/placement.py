"""Placement groups — gang-scheduling chips for replicas and meshes.

Re-creates the reference's placement groups (``python/ray/util/placement_group.py:145``
— bundles of resources placed by strategy; native scheduling in
``gcs_placement_group_scheduler.cc`` and the bundle-aware policies under
``raylet/scheduling/policy/``) and the Serve deployment scheduler's
spread/compact choice (``serve/_private/deployment_scheduler.py``), for TPU
topology: a "node" is a host (process) and the resource is its chips.

A bundle reserves ``chips`` on one node; a group places all its bundles by
strategy:

- ``PACK``         prefer few nodes (co-locate; best-effort)
- ``SPREAD``       prefer distinct nodes (best-effort round-robin)
- ``STRICT_PACK``  all bundles on ONE node, or the group fails
- ``STRICT_SPREAD`` every bundle on a DIFFERENT node, or the group fails

Placed bundles hand back real ``jax.Device`` lists, which plug straight
into ``build_mesh(config, devices=pg.bundle_devices(i))`` — replica-to-chip
pinning is mesh construction, not cgroup games.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
from typing import Any, Dict, List, Optional, Sequence

import jax

from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("placement")

PACK = "PACK"
SPREAD = "SPREAD"
STRICT_PACK = "STRICT_PACK"
STRICT_SPREAD = "STRICT_SPREAD"
_STRATEGIES = (PACK, SPREAD, STRICT_PACK, STRICT_SPREAD)


class PlacementError(RuntimeError):
    """Group infeasible under its strategy (ref: PG stays pending; here we
    fail fast — the caller owns retry policy)."""


@dataclasses.dataclass(frozen=True)
class Bundle:
    chips: int = 1


@dataclasses.dataclass
class PlacementGroup:
    group_id: int
    bundles: List[Bundle]
    strategy: str
    # parallel to bundles: the devices reserved for each
    assignments: List[List[jax.Device]] = dataclasses.field(default_factory=list)

    def bundle_devices(self, index: int) -> List[jax.Device]:
        return list(self.assignments[index])

    @property
    def total_chips(self) -> int:
        return sum(b.chips for b in self.bundles)


def pin_slice(
    manager: "PlacementManager",
    mesh_shape: str,
    strategy: str = STRICT_PACK,
):
    """Pin one ``(model, mesh_shape)`` schedulable unit to silicon
    (ROADMAP item 2): reserve a ``mesh_chips(mesh_shape)``-wide chip SET
    as a single gang bundle and build its TP mesh from exactly those
    devices — the bridge between the planner's mesh-shape string
    (``scheduler/nexus.Session.mesh_shape``) and the devices a
    ``DecodeEngine(mesh=...)`` replica actually runs on.

    ``STRICT_PACK`` by default: a TP slice's collectives ride ICI, so
    the gang must land on ONE host or fail loudly — never silently
    straddle DCN. Returns ``(group, mesh)``; ``mesh`` is None for a
    1-chip shape (callers pin the single device instead — the classic
    path). Release the reservation with ``manager.remove(group)`` when
    the slice dies or the replica is torn down."""
    from ray_dynamic_batching_tpu.profiles.table import mesh_chips

    chips = mesh_chips(mesh_shape)
    pg = manager.create([Bundle(chips=chips)], strategy=strategy)
    if chips == 1:
        return pg, None
    from ray_dynamic_batching_tpu.parallel.mesh import (
        MeshConfig,
        build_mesh,
    )

    return pg, build_mesh(MeshConfig(tp=chips), pg.bundle_devices(0))


class PlacementManager:
    """Chip accounting + strategy placement over the visible devices.

    Nodes are derived from ``device.process_index`` (one node per host —
    exactly the reference's node granularity). A manager instance owns its
    reservations; groups from the same manager never overlap chips.
    """

    def __init__(self, devices: Optional[Sequence[jax.Device]] = None):
        devices = list(devices if devices is not None else jax.devices())
        self._nodes: Dict[int, List[jax.Device]] = {}
        for d in devices:
            self._nodes.setdefault(int(d.process_index), []).append(d)
        self._free: Dict[int, List[jax.Device]] = {
            n: list(ds) for n, ds in self._nodes.items()
        }
        self._groups: Dict[int, PlacementGroup] = {}
        self._next_id = itertools.count(1)
        self._lock = threading.Lock()
        # HBM totals are static per device: query the backend ONCE here,
        # not per resource_view() poll under the lock.
        self._node_hbm: Dict[int, int] = {}
        for n, ds in self._nodes.items():
            hbm = 0
            for d in ds:
                try:
                    hbm += int(d.memory_stats().get("bytes_limit", 0))
                except Exception:  # noqa: BLE001 — CPU devices: no HBM
                    pass
            self._node_hbm[n] = hbm

    # --- introspection ----------------------------------------------------
    def nodes(self) -> Dict[int, int]:
        """node id -> total chips."""
        return {n: len(ds) for n, ds in self._nodes.items()}

    def free_chips(self) -> Dict[int, int]:
        with self._lock:
            return {n: len(ds) for n, ds in self._free.items()}

    def groups(self) -> List[PlacementGroup]:
        with self._lock:
            return list(self._groups.values())

    def resource_view(self) -> Dict[str, Any]:
        """Cluster resource snapshot (ref ``gcs_resource_manager.cc`` — the
        GCS-side node/resource view the dashboard and autoscaler read):
        per-node chip totals, free counts, HBM where the backend reports
        it, and live reservations."""
        with self._lock:
            nodes: Dict[str, Any] = {
                str(n): {
                    "chips_total": len(devs),
                    "chips_free": len(self._free[n]),
                    "platform": devs[0].platform if devs else "none",
                    "hbm_bytes_total": self._node_hbm.get(n, 0),
                }
                for n, devs in self._nodes.items()
            }
            reservations = [
                {
                    "group_id": pg.group_id,
                    "strategy": pg.strategy,
                    "chips": pg.total_chips,
                    # str keys, same namespace as the nodes map
                    "nodes": sorted({
                        str(int(d.process_index))
                        for a in pg.assignments for d in a
                    }),
                }
                for pg in self._groups.values()
            ]
        return {"nodes": nodes, "reservations": reservations}

    # --- placement --------------------------------------------------------
    def create(self, bundles: Sequence[Bundle],
               strategy: str = PACK) -> PlacementGroup:
        """Reserve chips for every bundle atomically (all-or-nothing, like
        the reference's gang placement)."""
        if strategy not in _STRATEGIES:
            raise ValueError(
                f"unknown strategy {strategy!r}; one of {_STRATEGIES}"
            )
        bundles = [
            b if isinstance(b, Bundle) else Bundle(**b) for b in bundles
        ]
        if not bundles or any(b.chips <= 0 for b in bundles):
            raise ValueError("bundles must be non-empty with chips > 0")
        with self._lock:
            assignments = self._place(bundles, strategy)
            # commit
            for devs in assignments:
                for d in devs:
                    self._free[int(d.process_index)].remove(d)
            pg = PlacementGroup(
                group_id=next(self._next_id),
                bundles=list(bundles),
                strategy=strategy,
                assignments=assignments,
            )
            self._groups[pg.group_id] = pg
            logger.info(
                "placed group %d: %s over nodes %s", pg.group_id, strategy,
                sorted({int(d.process_index) for a in assignments for d in a}),
            )
            return pg

    def remove(self, pg: PlacementGroup) -> None:
        """Release the group's chips (ref remove_placement_group)."""
        with self._lock:
            if self._groups.pop(pg.group_id, None) is None:
                return
            for devs in pg.assignments:
                for d in devs:
                    self._free[int(d.process_index)].append(d)

    # --- strategies (lock held) -------------------------------------------
    def _place(self, bundles: List[Bundle], strategy: str
               ) -> List[List[jax.Device]]:
        free = {n: list(ds) for n, ds in self._free.items()}

        def free_desc() -> str:
            # lock is held: errors must never call self.free_chips() (it
            # re-acquires the non-reentrant lock -> deadlock). Report both
            # the committed state and the working state mid-request, since
            # nothing commits on failure and either alone misleads.
            committed = {n: len(ds) for n, ds in self._free.items()}
            working = {n: len(ds) for n, ds in free.items()}
            if committed == working:
                return f"free: {committed}"
            return (f"free: {committed}, after earlier bundles of this "
                    f"request: {working}")

        def take(node: int, k: int) -> List[jax.Device]:
            out = free[node][:k]
            free[node] = free[node][k:]
            return out

        if strategy == STRICT_PACK:
            need = sum(b.chips for b in bundles)
            for node in sorted(free, key=lambda n: len(free[n])):
                if len(free[node]) >= need:
                    return [take(node, b.chips) for b in bundles]
            raise PlacementError(
                f"STRICT_PACK: no node has {need} free chips "
                f"({free_desc()})"
            )

        if strategy == STRICT_SPREAD:
            if len(bundles) > len(free):
                raise PlacementError(
                    f"STRICT_SPREAD: {len(bundles)} bundles > "
                    f"{len(free)} nodes"
                )
            # largest bundles first onto the emptiest fitting nodes
            order = sorted(range(len(bundles)),
                           key=lambda i: -bundles[i].chips)
            assignment: List[Optional[List[jax.Device]]] = [None] * len(bundles)
            used = set()
            for i in order:
                fit = [n for n in free
                       if n not in used and len(free[n]) >= bundles[i].chips]
                if not fit:
                    raise PlacementError(
                        f"STRICT_SPREAD: no distinct node fits bundle "
                        f"{bundles[i]} (free: {free_desc()})"
                    )
                node = max(fit, key=lambda n: len(free[n]))
                used.add(node)
                assignment[i] = take(node, bundles[i].chips)
            return assignment  # type: ignore[return-value]

        if strategy == PACK:
            # fill the fullest-feasible node first (compact)
            out = []
            for b in bundles:
                fit = [n for n in free if len(free[n]) >= b.chips]
                if not fit:
                    raise PlacementError(
                        f"PACK: no node fits bundle {b} "
                        f"({free_desc()})"
                    )
                node = min(fit, key=lambda n: len(free[n]))
                out.append(take(node, b.chips))
            return out

        # SPREAD: emptiest node first, best-effort distinctness
        out = []
        for b in bundles:
            fit = [n for n in free if len(free[n]) >= b.chips]
            if not fit:
                raise PlacementError(
                    f"SPREAD: no node fits bundle {b} "
                    f"({free_desc()})"
                )
            node = max(fit, key=lambda n: len(free[n]))
            out.append(take(node, b.chips))
        return out
