"""Sharded training step for causal LMs (mesh-parallel fine-tune path).

The serving framework's training-side companion (used by the multi-chip
dry-run and fine-tune workflows): a full optax train step jitted over a
``Mesh`` with TP-sharded params (model sharding rules), dp-sharded batches,
and gradient collectives inserted by XLA — the TPU-native equivalent of the
reference's DDP-over-NCCL building blocks (``ray.util.collective``,
SURVEY.md §2.4).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ray_dynamic_batching_tpu.models.causal_lm import CausalLM
from ray_dynamic_batching_tpu.ops import attention as attn_ops
from ray_dynamic_batching_tpu.parallel.mesh import (
    batch_sharding,
    param_shardings,
    seq_sharding,
    shard_params,
)


MOE_AUX_COEF = 0.01  # load-balance loss weight (GShard-style)


def causal_lm_loss(model: CausalLM, params: Any, tokens: jax.Array,
                   attn_mask: jax.Array) -> jax.Array:
    """Next-token cross entropy, ignoring padding; MoE models add the
    router load-balance auxiliary loss."""
    if getattr(model.cfg, "num_experts", 0) > 0:
        logits, aux = model.apply_with_aux(params, tokens, attn_mask)
    else:
        logits, aux = model.apply(params, tokens, attn_mask), 0.0
    targets = tokens[:, 1:]
    shift_logits = logits[:, :-1]
    ce = optax.softmax_cross_entropy_with_integer_labels(shift_logits, targets)
    weights = attn_mask[:, 1:].astype(jnp.float32)
    return (ce * weights).sum() / jnp.maximum(weights.sum(), 1.0) + (
        MOE_AUX_COEF * aux
    )


def make_sharded_train_state(
    model: CausalLM,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    rng: Optional[jax.Array] = None,
) -> Tuple[Any, Any]:
    """Init params on the mesh (TP rules) + matching optimizer state."""
    params = model.init(rng if rng is not None else jax.random.PRNGKey(0))
    params = shard_params(mesh, model, params)
    # init under jit so moment buffers inherit the param shardings via GSPMD
    opt_state = jax.jit(optimizer.init)(params)  # rdb-lint: disable=jit-retrace-hazard (one-shot optimizer-state init at train-state construction, off the serving path)
    return params, opt_state


def make_train_step(
    model: CausalLM,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
) -> Callable:
    """Compiled full train step: grads + optimizer update, donated state.

    With sp > 1 the batch is sharded [dp, sp] (sequence split over sp; T must
    divide by sp) and attention runs as ring attention over ICI — the
    long-context training path (SURVEY.md §5)."""
    sp = mesh.shape.get("sp", 1)

    def step(params, opt_state, tokens, attn_mask):
        # trace-time context: bakes the ring-attention dispatch into the
        # compiled program when the mesh has a real sp axis
        with attn_ops.sequence_parallel(mesh):
            loss, grads = jax.value_and_grad(
                lambda p: causal_lm_loss(model, p, tokens, attn_mask)
            )(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    p_shard = param_shardings(mesh, model, model_abstract_params(model))
    data_shard = (
        seq_sharding(mesh) if sp > 1 else batch_sharding(mesh, extra_dims=1)
    )
    return jax.jit(
        step,
        in_shardings=(p_shard, None, data_shard, data_shard),
        donate_argnums=(0, 1),
    )


def model_abstract_params(model: CausalLM) -> Any:
    return jax.eval_shape(lambda: model.init(jax.random.PRNGKey(0)))
