"""Batch-profile tables — the scheduler's ground truth.

Re-creates the contract of the reference's committed profiler outputs
(``293-project/profiling/*_summary.csv``, loaded by the scheduler at
``293-project/src/scheduler.py:1019-1041``): per-(batch, seq) rows of measured
latency / throughput / memory that drive SLO-aware batch selection.

TPU-first differences from the reference CSVs:
- rows exist only at *bucket* sizes (each bucket is one compiled XLA program;
  arbitrary batch sizes 1..512 are not "free" like eager CUDA — SURVEY.md §7
  hard part (a)), and lookups round **up** to the nearest profiled bucket;
- each row carries ``hbm_bytes`` (total program footprint incl. weights) and
  ``compile_ms`` so the planner can budget HBM and amortize compiles;
- a ``seq_len`` column generalizes the table to shape-bucketed LLM prefill
  (0 = fixed-shape vision input);
- a ``mesh`` column generalizes the table to mesh-sliced placements
  (ROADMAP item 2): ``"1x4"`` rows describe the model compiled over a
  4-chip TP slice — ``latency_ms`` is the whole-slice step latency,
  ``hbm_bytes`` the PER-CHIP footprint (what each chip's budget must
  absorb: weights/tp + its activation shard), and throughput the whole
  slice's. Single-chip rows are ``mesh="1x1"``, the loader default, so
  every committed table reads unchanged and every lookup that doesn't
  ask for a mesh keeps seeing exactly the rows it always did;
- a ``spec`` column generalizes the table to speculative decoding
  (ISSUE 13): ``spec="on"`` rows describe one VERIFY ROUND (draft +
  window verify), converted to an effective per-step cost by
  :func:`expected_tokens_per_round` at the session's acceptance rate.
  ``"off"`` is the loader default — pre-spec tables read unchanged.
"""

from __future__ import annotations

import csv
import io
import json
import math
from dataclasses import asdict, dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


@dataclass(frozen=True)
class ProfileRow:
    batch_size: int
    seq_len: int                 # 0 for fixed-shape models
    latency_ms: float            # mean step latency at this bucket
    latency_std_ms: float
    hbm_bytes: int               # total device footprint (weights+activations)
    compile_ms: float            # one-time XLA compile cost for this bucket
    throughput_sps: float = 0.0  # batch_size / latency
    mesh: str = "1x1"            # mesh shape this row was measured at
    # Speculative-decoding axis (ISSUE 13): "off" rows are plain decode
    # steps; "on" rows were measured with a draft model attached, and
    # their latency_ms is the cost of ONE VERIFY ROUND (draft k+1 steps
    # + the target's window verify). The effective per-step cost at
    # acceptance rate a is latency_ms / expected_tokens_per_round(a, k)
    # — the conversion every consumer (packer, sim engine) applies, so
    # a spec row never pretends a round is a step. Pre-spec tables load
    # as "off" and default lookups are byte-identical.
    spec: str = "off"

    def with_throughput(self) -> "ProfileRow":
        tput = self.batch_size / (self.latency_ms / 1000.0) if self.latency_ms else 0.0
        return ProfileRow(
            self.batch_size,
            self.seq_len,
            self.latency_ms,
            self.latency_std_ms,
            self.hbm_bytes,
            self.compile_ms,
            tput,
            self.mesh,
            self.spec,
        )


def mesh_chips(mesh: str) -> int:
    """Chip count of a mesh-shape string (``"1x4"`` -> 4). The shared
    parse — the packer's chip-set sizing, the replan matcher's width
    compatibility, and the sim's slice accounting all go through here so
    a malformed shape fails identically everywhere."""
    try:
        dims = [int(d) for d in str(mesh).lower().split("x")]
    except ValueError:
        dims = []
    if not dims or any(d <= 0 for d in dims):
        raise ValueError(f"malformed mesh shape {mesh!r} (want e.g. '1x4')")
    n = 1
    for d in dims:
        n *= d
    return n


CSV_FIELDS = [
    "batch_size",
    "seq_len",
    "latency_ms",
    "latency_std_ms",
    "hbm_bytes",
    "compile_ms",
    "throughput_sps",
    "mesh",
    "spec",
]


def expected_tokens_per_round(acceptance: float, spec_tokens: int) -> float:
    """Expected emitted tokens of one speculative verify round when each
    draft token is accepted independently with probability
    ``acceptance`` (the Leviathan et al. expectation): a round emits the
    longest accepted draft prefix plus the target's own correction —
    between 1 and k+1 tokens — so

        E[n] = (1 - a^(k+1)) / (1 - a)      (a < 1; k+1 at a == 1).

    THE shared conversion between a spec profile row's per-ROUND latency
    and an effective per-step cost: the packer, the sim engine, and the
    soak grade all divide by this — one formula, so the planner's belief
    and the simulated timeline can never disagree about what an
    acceptance rate is worth. Clamped to [1, k+1]; a <= 0 (total
    collapse) is exactly 1 token per round."""
    k = max(0, int(spec_tokens))
    a = float(acceptance)
    if a <= 0.0:
        return 1.0
    if a >= 1.0:
        return float(k + 1)
    return (1.0 - a ** (k + 1)) / (1.0 - a)


class BatchProfile:
    """All profiled buckets for one model (one seq bucket group per seq_len)."""

    def __init__(self, model_name: str, rows: Iterable[ProfileRow] = ()):
        self.model_name = model_name
        self.rows: List[ProfileRow] = sorted(
            (r.with_throughput() for r in rows),
            key=lambda r: (r.seq_len, r.batch_size, r.mesh, r.spec),
        )

    # --- construction -----------------------------------------------------
    def add(self, row: ProfileRow) -> None:
        self.rows.append(row.with_throughput())
        self.rows.sort(
            key=lambda r: (r.seq_len, r.batch_size, r.mesh, r.spec)
        )

    # --- lookups (always round batch UP to a profiled bucket) -------------
    def _seq_rows(self, seq_len: int = 0, mesh: str = "1x1",
                  spec: str = "off") -> List[ProfileRow]:
        pool = [r for r in self.rows if r.mesh == mesh and r.spec == spec]
        if not pool and spec != "off":
            # A spec session on a table with no spec rows: fall back to
            # the plain rows (the caller's speedup conversion then sees
            # spec pricing as unavailable — never a KeyError mid-plan).
            pool = [r for r in self.rows if r.mesh == mesh
                    and r.spec == "off"]
        rows = [r for r in pool if r.seq_len == seq_len]
        if not rows and pool:
            # fall back to nearest profiled seq bucket >= requested
            seqs = sorted({r.seq_len for r in pool})
            chosen = next((s for s in seqs if s >= seq_len), seqs[-1])
            rows = [r for r in pool if r.seq_len == chosen]
        return rows

    def meshes(self) -> List[str]:
        """Mesh shapes this table has rows for, smallest slice first —
        the degrade ladder ``scheduler/replan.degrade_sessions`` walks
        when a model's preferred slice width no longer exists."""
        return sorted({r.mesh for r in self.rows}, key=mesh_chips)

    def buckets(self, seq_len: int = 0, mesh: str = "1x1") -> List[int]:
        return [r.batch_size for r in self._seq_rows(seq_len, mesh)]

    def specs(self) -> List[str]:
        """Spec arms this table has rows for ("off" first)."""
        return sorted({r.spec for r in self.rows})

    def bucket_for(self, batch_size: int, seq_len: int = 0,
                   mesh: str = "1x1", spec: str = "off"
                   ) -> Optional[ProfileRow]:
        """Smallest profiled bucket >= batch_size (None if beyond the table)."""
        for r in self._seq_rows(seq_len, mesh, spec):
            if r.batch_size >= batch_size:
                return r
        return None

    def row_for(self, batch_size: int, seq_len: int = 0,
                mesh: str = "1x1", spec: str = "off"
                ) -> Optional[ProfileRow]:
        for r in self._seq_rows(seq_len, mesh, spec):
            if r.batch_size == batch_size:
                return r
        return None

    def latency_ms(self, batch_size: int, seq_len: int = 0,
                   mesh: str = "1x1") -> float:
        row = self.bucket_for(batch_size, seq_len, mesh)
        if row is None:
            raise KeyError(
                f"{self.model_name}: no profiled bucket >= batch {batch_size}"
            )
        return row.latency_ms

    def largest_within_latency(
        self, max_latency_ms: float, seq_len: int = 0,
        hbm_budget_bytes: Optional[int] = None, mesh: str = "1x1",
    ) -> Optional[ProfileRow]:
        """Largest bucket whose latency (and HBM) fit — the Nexus 'saturate'
        selection rule (ref nexus.py:154-165), against measured buckets."""
        best = None
        for r in self._seq_rows(seq_len, mesh):
            if r.latency_ms <= max_latency_ms and (
                hbm_budget_bytes is None or r.hbm_bytes <= hbm_budget_bytes
            ):
                best = r
        return best

    def max_throughput(self, seq_len: int = 0, mesh: str = "1x1") -> float:
        rows = self._seq_rows(seq_len, mesh)
        return max((r.throughput_sps for r in rows), default=0.0)

    def weights_hbm_bytes(self, mesh: Optional[str] = None,
                          spec: Optional[str] = None) -> int:
        """Lower bound on resident footprint: min over rows (≈ weights).

        ``mesh`` restricts to rows measured at that shape — necessary
        on mixed-mesh tables, where per-chip footprints differ by slice
        width (a 1x2 row carries twice the weight shard of a 1x4 row)
        and the unrestricted min would always answer with the WIDEST
        mesh's shard, underpricing uploads to narrower shapes. ``spec``
        restricts analogously on mixed-arm tables: a spec row's
        footprint includes the draft model's weights, which the plain
        rows' min would shave off. Falls back progressively (drop the
        spec restriction, then the mesh one) when the table has no rows
        at the requested combination — the pre-mesh behavior, and the
        safe lower bound when a shape is missing."""
        if mesh is not None:
            if spec is not None:
                at_both = min(
                    (r.hbm_bytes for r in self.rows
                     if r.mesh == mesh and r.spec == spec),
                    default=0,
                )
                if at_both > 0:
                    return at_both
            at_mesh = min(
                (r.hbm_bytes for r in self.rows if r.mesh == mesh),
                default=0,
            )
            if at_mesh > 0:
                return at_mesh
        return min((r.hbm_bytes for r in self.rows), default=0)

    # --- persistence (the CSV/JSON contract) ------------------------------
    def to_csv(self, path: Optional[str] = None) -> str:
        buf = io.StringIO()
        w = csv.DictWriter(buf, fieldnames=CSV_FIELDS)
        w.writeheader()
        for r in self.rows:
            w.writerow(asdict(r))
        text = buf.getvalue()
        if path:
            with open(path, "w") as f:
                f.write(text)
        return text

    @classmethod
    def from_csv(cls, model_name: str, text_or_path: str) -> "BatchProfile":
        if "\n" not in text_or_path:
            with open(text_or_path) as f:
                text = f.read()
        else:
            text = text_or_path
        rows = []
        for rec in csv.DictReader(io.StringIO(text)):
            rows.append(
                ProfileRow(
                    batch_size=int(rec["batch_size"]),
                    seq_len=int(rec.get("seq_len", 0) or 0),
                    latency_ms=float(rec["latency_ms"]),
                    latency_std_ms=float(rec.get("latency_std_ms", 0) or 0),
                    hbm_bytes=int(float(rec.get("hbm_bytes", 0) or 0)),
                    compile_ms=float(rec.get("compile_ms", 0) or 0),
                    # Pre-mesh tables have no column: single-chip rows.
                    mesh=str(rec.get("mesh") or "1x1"),
                    # Pre-spec tables have no column: plain decode rows.
                    spec=str(rec.get("spec") or "off"),
                )
            )
        return cls(model_name, rows)

    def to_json(self) -> str:
        return json.dumps(
            {"model": self.model_name, "rows": [asdict(r) for r in self.rows]},
            indent=2,
        )

    @classmethod
    def from_json(cls, text: str) -> "BatchProfile":
        obj = json.loads(text)
        return cls(obj["model"], [ProfileRow(**r) for r in obj["rows"]])

    def report(self) -> str:
        """Human-readable report (analogue of the reference's report.txt)."""
        lines = [f"# Batch profile: {self.model_name}", ""]
        best_t = max(self.rows, key=lambda r: r.throughput_sps, default=None)
        best_l = min(self.rows, key=lambda r: r.latency_ms, default=None)
        if best_t:
            lines.append(
                f"best throughput: {best_t.throughput_sps:.1f} samples/s "
                f"@ batch {best_t.batch_size} seq {best_t.seq_len} "
                f"({best_t.latency_ms:.2f} ms)"
            )
        if best_l:
            lines.append(
                f"best latency: {best_l.latency_ms:.2f} ms @ batch "
                f"{best_l.batch_size} seq {best_l.seq_len}"
            )
        lines.append("")
        lines.append(
            f"{'batch':>6} {'seq':>6} {'lat_ms':>10} {'std':>8} "
            f"{'tput':>10} {'hbm_mb':>9} {'compile_ms':>10}"
        )
        for r in self.rows:
            lines.append(
                f"{r.batch_size:>6} {r.seq_len:>6} {r.latency_ms:>10.2f} "
                f"{r.latency_std_ms:>8.2f} {r.throughput_sps:>10.1f} "
                f"{r.hbm_bytes / 1e6:>9.1f} {r.compile_ms:>10.0f}"
            )
        return "\n".join(lines) + "\n"


def bucket_up(value: int, buckets: Sequence[int]) -> Optional[int]:
    """Smallest bucket >= value; None if value exceeds every bucket."""
    for b in sorted(buckets):
        if b >= value:
            return b
    return None


def default_batch_buckets(max_batch: int, min_batch: int = 1) -> List[int]:
    """Power-of-two buckets — one XLA program each, bounded jit-cache size."""
    out = []
    b = min_batch
    while b <= max_batch:
        out.append(b)
        b *= 2
    return out


def default_seq_buckets(max_seq: int, min_seq: int = 32) -> List[int]:
    out = []
    s = min_seq
    while s <= max_seq:
        out.append(s)
        s *= 2
    return out


class ProfileStore:
    """Named profile collection the scheduler reads (ref: profile CSVs dir)."""

    def __init__(self) -> None:
        self._profiles: Dict[str, BatchProfile] = {}

    def put(self, profile: BatchProfile) -> None:
        self._profiles[profile.model_name] = profile

    def get(self, model_name: str) -> BatchProfile:
        if model_name not in self._profiles:
            raise KeyError(f"no profile for model {model_name!r}")
        return self._profiles[model_name]

    def __contains__(self, model_name: str) -> bool:
        return model_name in self._profiles

    def models(self) -> List[str]:
        return sorted(self._profiles)

    def load_dir(self, path: str) -> None:
        import os

        for fn in os.listdir(path):
            if fn.endswith(".csv"):
                name = fn[: -len(".csv")]
                self.put(BatchProfile.from_csv(name, os.path.join(path, fn)))
