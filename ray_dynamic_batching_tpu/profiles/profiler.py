"""Offline model profiler — sweeps (batch, seq) buckets on the live backend.

TPU-native re-design of the reference's ``ModelProfiler``
(``293-project/profiling/ModelProfiler.py:92-109`` CUDA-event timing,
``:85-90`` peak memory via ``max_memory_allocated``, ``:163-211`` OOM
tolerance + early stop; driven by ``run_profiler.py:191-196`` batch sweep
1→512). Differences forced by the XLA compilation model:

- Buckets, not arbitrary sizes: every (batch, seq) is a separate compiled
  program, so the sweep walks power-of-two buckets and records ``compile_ms``
  (the reference assumes any batch is instantly runnable — SURVEY.md §7(a)).
- Memory is read from XLA's compiled-program ``memory_analysis()`` (argument +
  output + temp + generated code size), not an allocator high-water mark —
  exact, available without running, and includes the weights the program holds
  resident in HBM.
- Timing dispatches the **already-compiled** executable (the same one the
  compile_ms/memory numbers describe — one compile per bucket) many times
  and fetches one scalar at the end: on the axon TPU tunnel
  ``block_until_ready`` reports completion early, so only a host fetch
  observes real execution time; in-order device execution makes the final
  fetch cover every dispatched step.
- OOM tolerance: RESOURCE_EXHAUSTED from compile or run marks the bucket
  infeasible; after ``max_consecutive_errors`` the sweep stops early.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_dynamic_batching_tpu.models.base import ServableModel
from ray_dynamic_batching_tpu.profiles.table import (
    BatchProfile,
    ProfileRow,
    default_batch_buckets,
)
from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("profiler")


def _is_oom(err: Exception) -> bool:
    msg = str(err)
    return "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg or "OOM" in msg


def _fetch_scalar(out) -> float:
    """Host fetch of one scalar — the only reliable completion signal on the
    axon tunnel, where ``block_until_ready`` returns early."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    return float(np.ravel(np.asarray(leaf))[0])


def timed_steps_ms(compiled, params, inputs, iters: int, warmup: int = 1):
    """Per-step latency samples for an already-compiled executable.

    Dispatches ``iters`` async calls and fetches one scalar from the last
    output: the device executes programs in order, so the final fetch
    observes every step, and per-call dispatch overhead is included — which
    is exactly the serving hot path (the engine dispatches each batch from
    the host too). Reuses the executable the scheduler's compile_ms/memory
    numbers describe, so each bucket pays XLA compilation exactly once.
    """
    out = None
    for _ in range(max(warmup, 1)):
        out = compiled(params, *inputs)
    _fetch_scalar(out)
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = compiled(params, *inputs)
        _fetch_scalar(out)
        samples.append((time.perf_counter() - t0) * 1000.0 / iters)
    return samples


class ModelProfiler:
    """Profiles one model's apply fn across shape buckets."""

    def __init__(
        self,
        model: ServableModel,
        params=None,
        warmup_iters: int = 2,
        timing_iters: int = 5,
        max_consecutive_errors: int = 3,
        donate: bool = False,
    ):
        from ray_dynamic_batching_tpu.utils.compile_cache import maybe_enable

        maybe_enable()  # sweep re-runs reuse compiled buckets from disk
        self.model = model
        self.params = params
        self.warmup_iters = warmup_iters
        self.timing_iters = timing_iters
        self.max_consecutive_errors = max_consecutive_errors

    def _ensure_params(self):
        if self.params is None:
            self.params = self.model.init(jax.random.PRNGKey(0))
        return self.params

    def profile_bucket(
        self, batch_size: int, seq_len: int = 0
    ) -> Optional[ProfileRow]:
        """Compile + time one bucket; None if infeasible (OOM)."""
        params = self._ensure_params()
        inputs = self.model.example_inputs(batch_size, seq_len or None)
        fn = jax.jit(self.model.apply)
        try:
            t0 = time.perf_counter()
            lowered = fn.lower(params, *inputs)
            compiled = lowered.compile()
            compile_ms = (time.perf_counter() - t0) * 1000.0

            mem = compiled.memory_analysis()
            hbm_bytes = 0
            if mem is not None:
                hbm_bytes = int(
                    getattr(mem, "argument_size_in_bytes", 0)
                    + getattr(mem, "output_size_in_bytes", 0)
                    + getattr(mem, "temp_size_in_bytes", 0)
                    + getattr(mem, "generated_code_size_in_bytes", 0)
                    - getattr(mem, "alias_size_in_bytes", 0)
                )

            samples = timed_steps_ms(
                compiled, params, inputs,
                iters=max(self.timing_iters, 2), warmup=self.warmup_iters,
            )
        except Exception as e:  # noqa: BLE001 — XLA raises backend-specific types
            if _is_oom(e):
                logger.warning(
                    "%s batch=%d seq=%d infeasible (OOM)",
                    self.model.name, batch_size, seq_len,
                )
                return None
            raise
        return ProfileRow(
            batch_size=batch_size,
            seq_len=seq_len,
            latency_ms=float(np.mean(samples)),
            latency_std_ms=float(np.std(samples)),
            hbm_bytes=hbm_bytes,
            compile_ms=compile_ms,
        )

    def sweep(
        self,
        batch_buckets: Optional[Sequence[int]] = None,
        seq_buckets: Sequence[int] = (0,),
        max_batch: int = 512,
    ) -> BatchProfile:
        """Full sweep (ref: ProfilerRunner loop, run_profiler.py:191-211)."""
        buckets = list(batch_buckets or default_batch_buckets(max_batch))
        profile = BatchProfile(self.model.name)
        for seq in seq_buckets:
            consecutive_errors = 0
            for b in buckets:
                row = self.profile_bucket(b, seq)
                if row is None:
                    consecutive_errors += 1
                    if consecutive_errors >= self.max_consecutive_errors:
                        logger.warning(
                            "%s: stopping sweep at seq=%d after %d errors",
                            self.model.name, seq, consecutive_errors,
                        )
                        break
                    continue
                consecutive_errors = 0
                profile.add(row)
                logger.info(
                    "%s b=%d s=%d: %.2f ms, %.1f sps, %.0f MB, compile %.0f ms",
                    self.model.name, b, seq, row.latency_ms,
                    row.with_throughput().throughput_sps,
                    row.hbm_bytes / 1e6, row.compile_ms,
                )
        return profile

    def write_outputs(self, profile: BatchProfile, out_dir: str) -> Tuple[str, str, str]:
        """Persist summary.csv / detailed.json / report.txt (reference contract,
        ``ModelProfiler.py:224-371``)."""
        return write_profile_outputs(profile, out_dir)


def write_profile_outputs(
    profile: BatchProfile, out_dir: str
) -> Tuple[str, str, str]:
    """Shared writer for every profile family (forward-pass, decode,
    prefill): summary.csv / detailed.json / report.txt keyed by the
    profile's model_name."""
    import os

    os.makedirs(out_dir, exist_ok=True)
    base = os.path.join(out_dir, profile.model_name)
    csv_path, json_path, report_path = (
        base + "_summary.csv", base + "_detailed.json", base + "_report.txt",
    )
    profile.to_csv(csv_path)
    with open(json_path, "w") as f:
        f.write(profile.to_json())
    with open(report_path, "w") as f:
        f.write(profile.report())
    return csv_path, json_path, report_path
