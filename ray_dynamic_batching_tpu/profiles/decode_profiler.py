"""Decode-phase profiler: measured tables for the LLM serving control loop.

The reference's core control theory is profiled-latency-driven planning —
its committed profiler CSVs ARE the scheduler's input
(``293-project/profiling/*_summary.csv`` consumed at
``293-project/src/scheduler.py:1019-1041``; packing logic
``293-project/src/nexus.py:129-296``). The forward-pass profiler covers
the vision/encoder path; this module extends the same committed-table
contract to the continuous-batching DECODE engine, whose cost axes are
different:

- **Decode step**: per-substep latency + program HBM vs
  ``num_slots`` (batch occupancy) x ``max_len`` (KV capacity). Static
  shapes make attention cost a function of CAPACITY, not fill level, so a
  fresh cache times identically to a mid-generation one — one row per
  (slots, capacity) config covers the whole sequence.
- **Prefill**: admission-group latency vs (prompt bucket x group width)
  — the TTFT-side cost.

Rows reuse :class:`~ray_dynamic_batching_tpu.profiles.table.ProfileRow`
(decode: ``batch_size``=num_slots, ``seq_len``=KV capacity, throughput =
tokens/s at full occupancy; prefill: ``batch_size``=group width,
``seq_len``=prompt bucket), so the CSV/report/store machinery and the
committed-table contract are identical across profile families. Tables
land as ``<model>_decode_summary.csv`` / ``<model>_prefill_summary.csv``
and feed :meth:`LLMDeployment.plan_from_tables`, which derives num_slots /
decode_horizon / ttft_horizon from measurement + SLOs instead of the
analytic HBM model.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_dynamic_batching_tpu.engine.decode import DecodeEngine
from ray_dynamic_batching_tpu.engine.queue import RequestQueue
from ray_dynamic_batching_tpu.models.base import ServableModel
from ray_dynamic_batching_tpu.profiles.profiler import _is_oom
from ray_dynamic_batching_tpu.profiles.table import BatchProfile, ProfileRow
from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("decode_profiler")


def _program_hbm(compiled) -> int:
    mem = compiled.memory_analysis()
    if mem is None:
        return 0
    return int(
        getattr(mem, "argument_size_in_bytes", 0)
        + getattr(mem, "output_size_in_bytes", 0)
        + getattr(mem, "temp_size_in_bytes", 0)
        + getattr(mem, "generated_code_size_in_bytes", 0)
        - getattr(mem, "alias_size_in_bytes", 0)
    )


class DecodeProfiler:
    """Sweeps a model's decode engine across (num_slots, capacity) and
    (prompt bucket, group) configs on the live backend."""

    def __init__(
        self,
        model: ServableModel,
        params=None,
        timing_iters: int = 8,
        warmup_iters: int = 2,
        max_consecutive_errors: int = 2,
    ):
        from ray_dynamic_batching_tpu.utils.compile_cache import maybe_enable

        maybe_enable()
        self.model = model
        self.params = params
        self.timing_iters = max(2, timing_iters)
        self.warmup_iters = max(1, warmup_iters)
        self.max_consecutive_errors = max_consecutive_errors

    def _ensure_params(self):
        if self.params is None:
            self.params = self.model.init(jax.random.PRNGKey(0))
        return self.params

    def _engine(self, num_slots: int, max_len: int,
                prompt_bucket: int, group: int) -> DecodeEngine:
        queue = RequestQueue(self.model.name, max_len=max(64, num_slots))
        return DecodeEngine(
            self.model, self._ensure_params(), queue,
            num_slots=num_slots, max_len=max_len,
            prompt_buckets=[prompt_bucket], decode_horizon=1,
            max_admissions_per_step=group,
        )

    # --- decode step -------------------------------------------------------
    def profile_decode_config(
        self, num_slots: int, max_len: int
    ) -> Optional[ProfileRow]:
        """One (slots, capacity) config: AOT-compile the engine's own
        decode program (donation included — the serving path's exact
        memory behavior), read its HBM footprint from XLA's memory
        analysis, then time chained single-substep dispatches with one
        scalar fetch per timing block (tunnel-safe completion signal).
        None if the program is infeasible (OOM)."""
        engine = self._engine(num_slots, max_len, prompt_bucket=8, group=1)
        try:
            B = num_slots
            (samp_f, samp_i, bias_ids, bias_vals) = \
                engine._sampling_arrays()
            # Rows: pending tokens / active mask / sample index — the
            # engine's single per-dispatch upload, all slots active.
            step_state = jnp.stack([
                jnp.ones((B,), jnp.int32),
                jnp.ones((B,), jnp.int32),
                jnp.zeros((B,), jnp.int32),
            ])
            fn = jax.jit(
                engine._decode_impl, donate_argnums=(1, 8),
                static_argnums=(3,),
            )
            args = (engine.params, engine._cache, step_state, 1,
                    samp_f, samp_i, bias_ids, bias_vals, engine._counts)
            t0 = time.perf_counter()
            compiled = fn.lower(*args).compile()
            compile_ms = (time.perf_counter() - t0) * 1000.0
            hbm_bytes = _program_hbm(compiled)

            cache, counts = engine._cache, engine._counts
            run_args = lambda: (engine.params, cache, step_state,  # noqa: E731
                                samp_f, samp_i, bias_ids,
                                bias_vals, counts)
            for _ in range(self.warmup_iters):
                packed, cache, counts = compiled(*run_args())
            float(np.asarray(packed)[0, 0])
            samples = []
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(self.timing_iters):
                    packed, cache, counts = compiled(*run_args())
                float(np.asarray(packed)[0, 0])  # host fetch = completion
                samples.append(
                    (time.perf_counter() - t0) * 1000.0 / self.timing_iters
                )
        except Exception as e:  # noqa: BLE001 — XLA raises backend types
            if _is_oom(e):
                logger.warning(
                    "%s decode slots=%d cap=%d infeasible (OOM)",
                    self.model.name, num_slots, max_len,
                )
                return None
            raise
        finally:
            engine.release_buffers()
        return ProfileRow(
            batch_size=num_slots,
            seq_len=max_len,
            latency_ms=float(np.mean(samples)),
            latency_std_ms=float(np.std(samples)),
            hbm_bytes=hbm_bytes,
            compile_ms=compile_ms,
        )

    # --- prefill -----------------------------------------------------------
    def profile_prefill_config(
        self, prompt_bucket: int, group: int, max_len: int
    ) -> Optional[ProfileRow]:
        """One (prompt bucket, group width) admission program."""
        num_slots = max(2, group)
        engine = self._engine(num_slots, max_len, prompt_bucket, group)
        try:
            tokmask = jnp.stack([
                jnp.ones((group, prompt_bucket), jnp.int32),
                jnp.ones((group, prompt_bucket), jnp.int32),
            ])
            meta_i = jnp.stack([
                jnp.arange(group, dtype=jnp.int32) % num_slots,
                jnp.zeros((group,), jnp.int32),
                jnp.zeros((group,), jnp.int32),
                jnp.zeros((group,), jnp.int32),
            ])
            meta_f = jnp.stack([
                jnp.zeros((group,), jnp.float32),
                jnp.ones((group,), jnp.float32),
            ])
            bias_ids = jnp.zeros((group, engine.max_bias_entries), jnp.int32)
            bias_vals = jnp.zeros(
                (group, engine.max_bias_entries), jnp.float32
            )
            fn = jax.jit(engine._prefill_impl, donate_argnums=(2,))
            args = (engine.params, tokmask, engine._cache, meta_i, meta_f,
                    bias_ids, bias_vals)
            t0 = time.perf_counter()
            compiled = fn.lower(*args).compile()
            compile_ms = (time.perf_counter() - t0) * 1000.0
            hbm_bytes = _program_hbm(compiled)

            cache = engine._cache
            for _ in range(self.warmup_iters):
                first, cache = compiled(engine.params, tokmask, cache,
                                        meta_i, meta_f, bias_ids, bias_vals)
            float(np.asarray(first)[0])
            samples = []
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(self.timing_iters):
                    first, cache = compiled(engine.params, tokmask, cache,
                                            meta_i, meta_f, bias_ids,
                                            bias_vals)
                float(np.asarray(first)[0])
                samples.append(
                    (time.perf_counter() - t0) * 1000.0 / self.timing_iters
                )
        except Exception as e:  # noqa: BLE001
            if _is_oom(e):
                logger.warning(
                    "%s prefill bucket=%d group=%d infeasible (OOM)",
                    self.model.name, prompt_bucket, group,
                )
                return None
            raise
        finally:
            engine.release_buffers()
        return ProfileRow(
            batch_size=group,
            seq_len=prompt_bucket,
            latency_ms=float(np.mean(samples)),
            latency_std_ms=float(np.std(samples)),
            hbm_bytes=hbm_bytes,
            compile_ms=compile_ms,
        )

    # --- sweeps ------------------------------------------------------------
    def sweep(
        self,
        slot_buckets: Sequence[int] = (4, 8, 16, 32, 64, 128),
        capacities: Sequence[int] = (256,),
        prompt_buckets: Sequence[int] = (16, 64),
        group_sizes: Sequence[int] = (1, 2, 4),
    ) -> Tuple[BatchProfile, BatchProfile]:
        """Returns (decode profile, prefill profile). Slot sweeps stop at
        the HBM edge (profiler-stopped, not config-stopped) after
        ``max_consecutive_errors`` infeasible configs."""
        decode = BatchProfile(f"{self.model.name}_decode")
        for cap in capacities:
            errors = 0
            for slots in slot_buckets:
                row = self.profile_decode_config(slots, cap)
                if row is None:
                    errors += 1
                    if errors >= self.max_consecutive_errors:
                        break
                    continue
                errors = 0
                decode.add(row)
                logger.info(
                    "%s decode slots=%d cap=%d: %.2f ms/substep "
                    "(%.0f tok/s full), %.0f MB",
                    self.model.name, slots, cap, row.latency_ms,
                    slots * 1000.0 / row.latency_ms, row.hbm_bytes / 1e6,
                )
        prefill = BatchProfile(f"{self.model.name}_prefill")
        cap = max(capacities)
        for bucket in prompt_buckets:
            if bucket >= cap:
                continue
            for group in group_sizes:
                row = self.profile_prefill_config(bucket, group, cap)
                if row is None:
                    continue
                prefill.add(row)
                logger.info(
                    "%s prefill bucket=%d group=%d: %.2f ms",
                    self.model.name, bucket, group, row.latency_ms,
                )
        return decode, prefill
