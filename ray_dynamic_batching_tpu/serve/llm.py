"""LLM serving deployment — continuous-batching decode behind the serve stack.

This is the north-star wiring (BASELINE.json: "the per-replica ModelRunner's
torch forward becomes a jax.jit call"): a deployment whose replicas each own
a :class:`~ray_dynamic_batching_tpu.engine.decode.DecodeEngine` driving
prefill + continuous-batching decode on one chip (or one mesh slice), fed
through the standard proxy → router → handle path the reference uses for
every deployment (``serve/_private/replica.py:515-544`` — the replica's
``handle_request``/``_streaming`` entry points; here the request queue IS the
engine's admission queue, so router assignment and engine admission compose
without a second hop).

The replica surface (queue_len / accepting / assign / healthy / stats) is
inherited from :class:`~ray_dynamic_batching_tpu.serve.replica.Replica`, so
the pow-2 router, autoscaler, and controller state machine treat LLM
replicas exactly like batch replicas. Only the execution loop differs: the
decode engine's own thread replaces the opportunistic-batch loop.

Payload contract (JSON-safe, the proxy passes it straight through)::

    {"tokens": [1, 2, 3],          # prompt token ids (required)
     "max_new_tokens": 64,          # optional
     "temperature": 0.8,            # optional: 0 (default) = greedy
     "top_k": 40,                   # optional: 0 (default) = full vocab
     "seed": 1234,                  # optional: reproducible sampling
     "stream": true}                # optional: tokens stream incrementally

Result: ``DecodeResult`` (tokens, finish_reason, ttft_ms, total_ms).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from ray_dynamic_batching_tpu.engine.decode import DecodeEngine
from ray_dynamic_batching_tpu.engine.queue import RequestQueue
from ray_dynamic_batching_tpu.engine.request import Request, RequestDropped
from ray_dynamic_batching_tpu.serve.replica import Replica
from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("serve.llm")


class LLMReplica(Replica):
    """One or more decode engines behind the standard replica surface.

    ``engine_builders`` maps a KV-capacity bucket (max_len) to a builder
    that receives that bucket's request queue and returns a ready
    (constructed, un-started) :class:`DecodeEngine` — weights loaded and
    sharded however the deployment wants (single chip, TP mesh slice).

    **Capacity buckets are the TPU-first answer to paged KV**: decode
    attention reads the FULL cache capacity every step (static shapes), so
    a short request in a long cache pays long-cache bandwidth per token.
    With several engines at different max_len, admission routes each
    request to the smallest cache that fits prompt + max_new_tokens —
    bandwidth per token scales with the request's own length class, no
    gather-heavy paging kernels needed.

    Engine warmup (XLA compiles for every prompt bucket + both decode
    horizons) runs at construction, mirroring how the controller treats
    slow replica starts: a replica is registered with the router only after
    it can serve its first request at full speed.
    """

    def __init__(
        self,
        replica_id: str,
        deployment: str,
        engine_builders: Dict[int, Callable[[RequestQueue], DecodeEngine]],
        max_ongoing_requests: int = 256,
        warmup: bool = True,
        default_max_new_tokens: int = 64,
    ) -> None:
        super().__init__(
            replica_id=replica_id,
            deployment=deployment,
            fn=self._reject_batch_path,  # engines own execution, not the loop
            max_ongoing_requests=max_ongoing_requests,
        )
        self.default_max_new_tokens = default_max_new_tokens
        # The base class's queue carries no traffic here (admission routes
        # straight to the per-bucket queues below); close it so nothing can
        # mistake it for a live path.
        self.queue.close()
        self.engines: Dict[int, DecodeEngine] = {}
        self._queues: Dict[int, RequestQueue] = {}
        for bucket in sorted(engine_builders):
            q = RequestQueue(
                f"{deployment}:{bucket}", max_len=max_ongoing_requests
            )
            self._queues[bucket] = q
            self.engines[bucket] = engine_builders[bucket](q)
        if warmup:
            for engine in self.engines.values():
                engine.warmup()

    @property
    def engine(self) -> DecodeEngine:
        """The largest-capacity engine (single-engine deployments have
        exactly one; multi-bucket callers should use :attr:`engines`)."""
        return self.engines[max(self.engines)]

    @staticmethod
    def _reject_batch_path(payloads: List[Any]) -> Sequence[Any]:
        raise RuntimeError("LLMReplica executes via its DecodeEngines")

    # --- admission: route by required KV capacity --------------------------
    def _required_capacity(self, payload: Any) -> int:
        max_new = self.default_max_new_tokens
        tokens = payload
        if isinstance(payload, dict):
            tokens = payload.get("tokens", ())
            max_new = int(payload.get("max_new_tokens", max_new))
        try:
            prompt_len = len(tokens)
        except TypeError:
            prompt_len = 1
        return prompt_len + max_new

    def _engine_for(self, payload: Any) -> int:
        need = self._required_capacity(payload)
        for bucket in sorted(self.engines):
            if bucket >= need:
                return bucket
        # Oversized: the largest engine finishes it with reason=capacity
        # (same contract as a single-engine replica).
        return max(self.engines)

    def assign(self, request: Request) -> bool:
        if not self.accepting():
            return False
        q = self._queues[self._engine_for(request.payload)]
        ok = q.add_request(request, reject_on_full=False)
        if ok and request.multiplexed_model_id:
            # Same contract as the base class: warm-model routing needs the
            # LRU recorded on every accepted assignment.
            self.record_multiplexed_model(request.multiplexed_model_id)
        return ok

    # --- lifecycle: the engine loops replace the batch loop ----------------
    def start(self) -> None:
        for engine in self.engines.values():
            engine.start()

    def stop(self, timeout_s: float = 5.0, drain: bool = True) -> None:
        self._stopped = True
        if drain:
            deadline = time.monotonic() + timeout_s
            while self.queue_len() > 0 and time.monotonic() < deadline:
                time.sleep(0.01)  # rdb-lint: disable=event-loop-blocking (control-plane stop() drain poll on the controller's thread; no event loop involved)
        exc = RequestDropped(f"{self.replica_id} stopped")
        # Signal every loop BEFORE joining any, then join under one shared
        # deadline — N wedged engines must cost ~timeout_s total, not
        # N * timeout_s of control-plane stall.
        for engine in self.engines.values():
            engine._run.clear()
        join_deadline = time.monotonic() + timeout_s
        for engine in self.engines.values():
            engine.stop(max(0.1, join_deadline - time.monotonic()))
        for bucket, q in self._queues.items():
            q.close()
            # Requests still mid-decode in engine slots terminate with a
            # rejection — futures/streams must never dangle past death.
            self.engines[bucket].abort_active(exc)
        for req in self.drain_queue():
            # Shed accounting conserves through teardown: drained work is
            # a counted drop, not a vanished request.
            self._queues[self._engine_for(req.payload)].count_external_drop(
                req, reason="closed"
            )
            req.reject(exc)
        # Free HBM (params + caches) so a replacement on the same chip
        # doesn't OOM against this replica's dead buffers — but only if the
        # loop actually exited; a wedged device call may still be touching
        # them, and dropping the references mid-flight trades a leak for a
        # use-after-free-style crash.
        for engine in self.engines.values():
            t = engine._thread
            if t is None or not t.is_alive():
                engine.release_buffers()

    def drain_queue(self) -> List[Request]:
        self._stopped = True
        out: List[Request] = []
        for q in self._queues.values():
            while len(q) > 0:
                out.extend(
                    q.get_batch(self.max_ongoing_requests,
                                discard_stale=False)
                )
        return out

    def slo_compliance(self) -> float:
        """Worst recent compliance across the bucket queues that carry
        this replica's traffic (the base class's queue is closed here, so
        its idle 1.0 would blind the overload governor's compliance
        signal)."""
        qs = list(self._queues.values())
        return min((q.slo_compliance() for q in qs), default=1.0)

    def latency_observation(self) -> tuple:
        """Merged recent-latency sketch across the bucket queues (the
        closed base queue would leave this replica permanently ungraded
        by the gray detector and pin the hedge bar at its floor —
        exactly the blindness :meth:`slo_compliance` fixes for the
        governor)."""
        from ray_dynamic_batching_tpu.utils.sketch import QuantileSketch

        views = [q.latency_window.view() for q in self._queues.values()]
        merged = QuantileSketch.merged(views)
        return (merged.percentile(0.5), merged.percentile(0.95),
                len(merged))

    def prefix_digests(self, limit: int = 128) -> Optional[dict]:
        """Bounded prefix-page digest publication merged across this
        replica's bucket engines (cluster-wide prefix routing, ISSUE 11).
        The controller collects this each control step and pushes it to
        the router's digest directory over the long-poll channel."""
        merged: dict = {}
        page_size = None
        reloaded: List[str] = []
        for engine in self.engines.values():
            fn = getattr(engine, "prefix_digests", None)
            if fn is None:
                continue
            pub = fn(limit)
            if pub is None:
                continue
            page_size = pub["page_size"]
            # Spill round-trip republish (page fabric, satellite fix):
            # forwarded so the controller can force a directory push even
            # when the advertised union is unchanged.
            reloaded.extend(pub.get("reloaded", ()))
            for key, n in pub["digests"].items():
                if len(merged) >= limit:
                    break
                merged.setdefault(key, n)
        if page_size is None:
            return None
        out: dict = {"page_size": page_size, "digests": merged}
        if reloaded:
            out["reloaded"] = reloaded
        return out

    # --- page fabric surface (live migration + prefix push) ---------------
    def live_stream_ids(self) -> List[str]:
        """Migration-eligible stream ids across this replica's bucket
        engines (paged engines only; slab engines migrate nothing)."""
        out: List[str] = []
        for engine in self.engines.values():
            fn = getattr(engine, "live_stream_ids", None)
            if fn is not None and engine.paged:
                out.extend(fn())
        return out

    def request_migration(self, request_id: str, deliver) -> bool:
        """Ask whichever bucket engine holds ``request_id`` to migrate it
        out through ``deliver`` (see DecodeEngine.request_migration)."""
        for engine in self.engines.values():
            if engine.paged and engine.request_migration(
                    request_id, deliver):
                return True
        return False

    def accept_parcel(self, parcel) -> bool:
        """Destination half of the courier edge at replica granularity:
        stream parcels route to the smallest capacity bucket that fits
        the stream's resume length (same bandwidth-per-token rule as
        fresh admissions), falling back to any accepting engine; prefix
        parcels go to the largest engine (where long prompts land)."""
        if self._stopped:
            return False
        if parcel.kind == "stream":
            need = parcel.resume_len
            for bucket in sorted(self.engines):
                if bucket >= need and self.engines[bucket].accept_parcel(
                        parcel):
                    return True
            for bucket in sorted(self.engines, reverse=True):
                if self.engines[bucket].accept_parcel(parcel):
                    return True
            return False
        return self.engine.accept_parcel(parcel)

    def hot_prefixes(self, limit: int = 8) -> List[tuple]:
        """Hit-ranked resident prefix entries across bucket engines, as
        ``(digest_hex, n_pages, hits)`` — the push planner's ranking."""
        out: List[tuple] = []
        for engine in self.engines.values():
            cache = getattr(engine, "paged_prefix", None)
            if cache is None:
                continue
            out.extend(cache.hot(limit))
        out.sort(key=lambda t: -t[2])
        return out[:limit]

    def request_prefix_push(self, digest_hex: str, deliver) -> bool:
        """Export the prefix entry addressed by ``digest_hex`` through
        ``deliver`` from whichever engine holds it."""
        key = bytes.fromhex(digest_hex)
        for engine in self.engines.values():
            cache = getattr(engine, "paged_prefix", None)
            if cache is None or key not in cache._entries:
                continue
            if engine.request_prefix_push(key, deliver):
                return True
        return False

    # --- router-facing surface --------------------------------------------
    def queue_len(self) -> int:
        return sum(
            len(q) + self.engines[b].active_slots
            + self.engines[b]._admitting
            for b, q in self._queues.items()
        )

    def healthy(self, stall_timeout_s: float = 60.0) -> bool:
        """Thread liveness + progress for EVERY engine: the loop refreshes
        its heartbeat only on successful iterations, so a perpetually-
        failing or wedged _step reads unhealthy and the controller replaces
        the replica (same stall contract as the base class)."""
        for engine in self.engines.values():
            t = engine._thread
            if t is None or not t.is_alive():
                return False
            if (time.monotonic() - engine.last_heartbeat) >= stall_timeout_s:
                return False
        return True

    def reconfigure(
        self,
        max_batch_size: Optional[int] = None,
        batch_wait_timeout_s: Optional[float] = None,
        max_ongoing_requests: Optional[int] = None,
        user_config: Optional[dict] = None,
    ) -> None:
        # Slot count / buckets are compile-shape decisions and can't change
        # on a live engine; only admission-side knobs apply. user_config is
        # accepted for base-contract compatibility (the controller passes
        # it to every replica kind) but has no user callable to deliver to.
        if max_ongoing_requests is not None:
            self.max_ongoing_requests = max_ongoing_requests
            for q in self._queues.values():
                q.max_len = max_ongoing_requests

    def stats(self) -> dict:
        s: dict = {}
        if len(self._queues) == 1:
            # Single-bucket replicas keep the flat queue-stat shape external
            # monitors already read (depth, slo_compliance, latency pcts).
            s.update(next(iter(self._queues.values())).stats())
        for bucket, q in self._queues.items():
            engine = self.engines[bucket]
            s[f"bucket_{bucket}"] = {
                **q.stats(),
                "active_slots": float(engine.active_slots),
                "decode_steps": float(engine.steps),
                "completed": float(engine.completed),
            }
        s["ongoing"] = float(self.queue_len())
        s["active_slots"] = float(
            sum(e.active_slots for e in self.engines.values())
        )
        s["decode_steps"] = float(sum(e.steps for e in self.engines.values()))
        s["completed"] = float(
            sum(e.completed for e in self.engines.values())
        )
        return s


class LLMDeployment:
    """Deployment factory the controller consumes via ``make_replica``.

    Builds the model + params ONCE and shares them across replicas (weights
    are immutable at inference; on a single host the HBM cost is paid once —
    the reference reloads weights per worker because CUDA contexts don't
    share, a constraint TPU+JAX doesn't have).
    """

    def __init__(
        self,
        model_name: str,
        num_slots: int = 8,
        max_len: int = 256,
        prompt_buckets: Optional[Sequence[int]] = None,
        eos_token_id: Optional[int] = None,
        default_max_new_tokens: int = 64,
        decode_horizon: int = 8,
        ttft_horizon: Optional[int] = None,
        max_admissions_per_step: int = 2,
        prefix_cache_size: int = 0,
        session_cache_size: int = 0,
        dtype: Any = None,
        params: Any = None,
        model: Any = None,
        warmup: bool = True,
        length_buckets: Optional[Sequence[int]] = None,
        draft_model_name: Optional[str] = None,
        draft_params: Any = None,
        spec_tokens: int = 4,
        checkpoint_dir: Optional[str] = None,
        checkpoint_step: Optional[int] = None,
        quantize_weights: bool = False,
        quantize_kv: bool = False,
        profiles_dir: Optional[str] = None,
        token_slo_ms: Optional[float] = None,
        ttft_slo_ms: Optional[float] = None,
        paged: bool = False,
        page_size: int = 128,
        kv_pool_pages: Optional[int] = None,
        host_spill_pages: int = 0,
        chunked_prefill: Optional[bool] = None,
        prefill_token_budget: Optional[int] = None,
    ) -> None:
        self.model_name = model_name
        self.num_slots = num_slots
        self.max_len = max_len
        self.prompt_buckets = prompt_buckets
        self.eos_token_id = eos_token_id
        self.default_max_new_tokens = default_max_new_tokens
        self.decode_horizon = decode_horizon
        self.ttft_horizon = ttft_horizon
        self.max_admissions_per_step = max_admissions_per_step
        self.prefix_cache_size = prefix_cache_size
        # HBM -> host-RAM spill tier for shed prefix pins (ISSUE 11):
        # pages of host residency per engine; 0 = off.
        self.host_spill_pages = host_spill_pages
        # Session rows are PER ENGINE: handle-level affinity steers a
        # session's turns back to the replica holding its row, but a
        # conversation that outgrows its length bucket lands on a larger
        # engine and re-prefills once (its old entry ages out via LRU) —
        # with multiple length buckets each engine budgets its own cache.
        self.session_cache_size = session_cache_size
        self.warmup = warmup
        # KV-capacity buckets: one engine per entry, requests routed to the
        # smallest cache fitting prompt + max_new (LLMReplica docstring —
        # the static-shape alternative to paged attention). Default: one
        # engine at max_len.
        self.length_buckets = sorted(length_buckets or [max_len])
        # Speculative decoding: a smaller registry model drafts, the target
        # verifies (greedy-exact; see DecodeEngine._spec_impl).
        self.draft_model_name = draft_model_name
        self.spec_tokens = spec_tokens
        self._draft_model = None
        self._draft_params = draft_params
        # Real weights: restored from the checkpoint subsystem instead of a
        # fresh init (the reference reloads torchvision weights per worker,
        # scheduler.py:507-515; here orbax-style trees restore once and are
        # shared across replicas).
        if checkpoint_dir is not None and params is not None:
            raise ValueError(
                "pass either params or checkpoint_dir, not both — the "
                "checkpoint would be silently ignored"
            )
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_step = checkpoint_step
        # Weight-only int8 for the decode engines (engine-owned transform;
        # TP meshes unsupported — see DecodeEngine).
        self.quantize_weights = quantize_weights
        # Int8 KV cache (codes + per-row scales, KVCache docstring):
        # auto slot sizing sees the smaller kv_bytes_per_slot and fits
        # ~2x the slots in the same HBM; the decode-scan bandwidth win
        # additionally requires the dequant fused into the attention
        # read (kernel path) — see KVCache.
        self.quantize_kv = quantize_kv
        # Paged KV pool (ISSUE 7): per-engine free-list pages replace the
        # per-slot slabs — HBM occupancy follows cached tokens, admission
        # waits on pages not slabs, prefix/session reuse is by reference
        # (CoW). Draft models compose (ISSUE 13): speculative rounds
        # draft into scratch pages and commit accepted prefixes by
        # page-table splice — except on a multi-chip (TP) replica, where
        # the pool shards over the mesh's kv-head axis (ROADMAP item 2)
        # and paged+spec+mesh stays excluded (DecodeEngine raises loudly
        # at build, the PR 10 pattern).
        self.paged = bool(paged)
        self.page_size = int(page_size)
        self.kv_pool_pages = kv_pool_pages
        # Token-budget chunked admission (ISSUE 15): None = the engine's
        # default (chunked on paged engines — the universal path — mono
        # on slabs); False forces the legacy monolithic arm (the
        # ``bench.py --prefill mono`` A/B baseline).
        self.chunked_prefill = chunked_prefill
        self.prefill_token_budget = prefill_token_budget
        self._dtype = dtype
        self._model = model
        self._params = params
        # Measured-table control (ref nexus.py:129-296 — profiled-latency-
        # driven planning): when ``profiles_dir`` holds committed
        # ``<model>_decode_summary.csv`` / ``<model>_prefill_summary.csv``
        # tables (tools/run_profiles.py --decode), single-chip engines
        # derive num_slots (if not pinned), decode_horizon, and
        # ttft_horizon from measurement + the token/TTFT SLOs instead of
        # the analytic HBM model — see plan_from_tables.
        self.profiles_dir = profiles_dir
        self.token_slo_ms = token_slo_ms
        self.ttft_slo_ms = ttft_slo_ms
        self._table_plans: Dict[int, Dict[str, int]] = {}
        self._init_lock = threading.Lock()

    def _ensure_model(self) -> None:
        with self._init_lock:
            if self._model is None:
                from ray_dynamic_batching_tpu.models import registry  # noqa: F401
                from ray_dynamic_batching_tpu.models.base import get_model

                kwargs = {"dtype": self._dtype} if self._dtype is not None else {}
                if self.quantize_kv:
                    import jax.numpy as jnp

                    kwargs["kv_dtype"] = jnp.int8
                self._model = get_model(self.model_name, **kwargs)
            elif self.quantize_kv:
                import jax.numpy as jnp

                if getattr(self._model, "kv_dtype", None) is None or (
                        jnp.dtype(self._model.kv_dtype)
                        != jnp.dtype(jnp.int8)):
                    # An injected model instance owns its cache dtype;
                    # silently serving a full-precision cache while the
                    # operator believes int8 is on would skew every
                    # HBM/slot-count decision downstream.
                    raise ValueError(
                        "quantize_kv=True but the injected model was not "
                        "built with kv_dtype=int8 — construct it with "
                        "CausalLM(..., kv_dtype=jnp.int8) or pass "
                        "model_name and let the deployment build it"
                    )
            if self._params is None:
                import jax

                self._params = self._model.init(jax.random.PRNGKey(0))
                if self.checkpoint_dir is not None:
                    from ray_dynamic_batching_tpu.runtime.checkpoint import (
                        CheckpointManager,
                    )

                    self._params = CheckpointManager(
                        self.checkpoint_dir
                    ).restore(self._params, step=self.checkpoint_step)
            if self.quantize_weights:
                from ray_dynamic_batching_tpu.models.quant import (
                    quantize_tree,
                )

                # Quantize ONCE here (idempotent): every length-bucket
                # engine shares the same int8 tree — per-engine
                # quantization would multiply resident copies by the
                # bucket count.
                self._params = quantize_tree(self._params)
            if self.draft_model_name is not None and self._draft_model is None:
                from ray_dynamic_batching_tpu.models.base import get_model

                kwargs = (
                    {"dtype": self._dtype} if self._dtype is not None else {}
                )
                self._draft_model = get_model(self.draft_model_name, **kwargs)
                if self._draft_params is None:
                    import jax

                    self._draft_params = self._draft_model.init(
                        jax.random.PRNGKey(1)
                    )

    def auto_num_slots(self, n_chips: int = 1,
                       max_len: Optional[int] = None,
                       budget_fraction: float = 1.0) -> int:
        """Size the continuous batch from the HBM budget (directive: slots
        from profile/HBM, not a guess): per CHIP, subtract this chip's
        weight shard, apply the planner's HBM fraction
        (``RDB_HBM_PLAN_FRACTION`` — same knob the Nexus packer uses), and
        fill the rest with this chip's KV-row shards. TP replicas shard
        both weights and KV 1/n_chips, so per-chip terms divide through.
        Rounded down to a power of two (aligns prefill group widths)."""
        import jax
        import numpy as np

        from ray_dynamic_batching_tpu.utils.config import get_config

        self._ensure_model()
        cfg = get_config()

        from ray_dynamic_batching_tpu.models.quant import (
            tree_weight_bytes as tree_bytes,
        )

        # Snapshot the write-once model state under the init lock: these
        # attrs are published by _ensure_model under it, and a planner
        # thread may size slots while another deployment thread is still
        # initializing the draft pair.
        with self._init_lock:
            model, params = self._model, self._params
            draft_model = self._draft_model
            draft_params = self._draft_params

        # _ensure_model already quantized the params when requested, so a
        # plain byte count is exact for both modes.
        weights_bytes = tree_bytes(params) / max(1, n_chips)
        budget = float(cfg.hbm_budget_bytes)
        per_slot = float(
            model.kv_bytes_per_slot(max_len or self.max_len)
        ) / max(1, n_chips)
        if draft_model is not None:
            # Speculative decoding doubles the residency story: the draft's
            # weights leave the budget, and every slot also carries a draft
            # KV row (with spec-token headroom) — omit either and the
            # "fits" answer OOMs on the chip.
            weights_bytes += tree_bytes(draft_params) / max(1, n_chips)
            per_slot += float(
                draft_model.kv_bytes_per_slot(
                    (max_len or self.max_len) + self.spec_tokens + 1
                )
            ) / max(1, n_chips)
        if self.session_cache_size > 0:
            # Each stored session turn pins a FULL kv row on device; the
            # cache at capacity is that many phantom slots of residency —
            # and EVERY length-bucket engine holds its own cache with rows
            # sized by ITS bucket, while this call sees only a 1/n_buckets
            # budget slice, so the whole deployment's session residency
            # (summed over buckets) must come off the top here.
            weights_bytes += (
                self.session_cache_size
                * float(sum(
                    model.kv_bytes_per_slot(b)
                    for b in self.length_buckets
                ))
            ) / max(1, n_chips)
        usable = (
            (budget - weights_bytes) * cfg.hbm_plan_fraction * budget_fraction
        )
        n = int(max(1.0, usable / max(per_slot, 1.0)))
        n = min(n, 256)
        n = 2 ** int(np.log2(n)) if n > 1 else 1
        logger.info(
            "%s: auto num_slots=%d (%d chip(s), weights %.0f MB/chip, "
            "%.2f MB/slot/chip, budget %.0f GB/chip x %.2f)",
            self.model_name, n, n_chips, weights_bytes / 1e6,
            per_slot / 1e6, budget / 1e9, cfg.hbm_plan_fraction,
        )
        return n

    def plan_from_tables(
        self,
        decode_profile,
        prefill_profile=None,
        *,
        max_len: Optional[int] = None,
        token_slo_ms: Optional[float] = None,
        ttft_slo_ms: Optional[float] = None,
        num_slots: Optional[int] = None,
    ) -> Dict[str, int]:
        """Derive (num_slots, decode_horizon, ttft_horizon) from MEASURED
        decode tables + SLOs — the reference's profiled-latency control
        theory (``293-project/src/nexus.py:129-296``: committed tables
        drive admission/packing) applied to the decode phase, replacing
        the analytic HBM model of :meth:`auto_num_slots`:

        - **num_slots**: among measured (slots, capacity) configs whose
          program fits the planner's HBM budget and whose per-substep
          latency respects the token SLO, the one with the highest
          full-occupancy token throughput.
        - **decode_horizon**: tokens reach the host only at scan end, so a
          full-batch scan of ``h`` substeps delivers bursts with gaps of
          ``h x step_ms`` — the token-latency SLO bounds ``h``.
        - **ttft_horizon**: an idle-queue arrival waits out at most one
          ttft-tier scan, then prefills; the TTFT budget left after the
          measured prefill latency (largest prompt bucket, group 1),
          with 20% headroom for queue/dispatch, bounds the tier.

        ``num_slots`` pins the slot count (the colocation planner's
        placement dictates it): horizons are then derived from THAT
        config's measured step — horizons computed for a different batch
        size would silently re-break the SLO the scan length encodes.

        Tables are profiled at the model's default (bf16) cache. Planning
        an int8-KV deployment (``quantize_kv=True``) from them is SAFE
        but conservative: the quantized scan is faster and smaller than
        the measured rows, so slot counts and horizons under-promise —
        re-profile with the quantized model to plan at its true capacity.
        """
        from ray_dynamic_batching_tpu.utils.config import get_config

        cfg = get_config()
        budget = cfg.hbm_budget_bytes * cfg.hbm_plan_fraction / max(
            1, len(self.length_buckets)
        )
        max_len = max_len or self.max_len
        token_slo_ms = token_slo_ms or self.token_slo_ms
        ttft_slo_ms = ttft_slo_ms or self.ttft_slo_ms
        candidates = [
            r for r in decode_profile.rows
            if r.seq_len == max_len and r.hbm_bytes > 0
        ]
        if num_slots is not None:
            # Pin BEFORE the budget filter: a caller-pinned config (the
            # colocation planner's placement) was already validated
            # against the planner's own HBM budget — re-filtering it
            # against the deployment's per-bucket slice would reject a
            # measured row that exists and silently fall back to default
            # horizons, the exact burst-SLO breach the pin prevents.
            rows = [r for r in candidates if r.batch_size == num_slots]
            if not rows:
                raise ValueError(
                    f"{self.model_name}: no measured decode row at "
                    f"(slots={num_slots}, cap={max_len}) to derive "
                    "horizons from"
                )
        else:
            rows = [r for r in candidates if r.hbm_bytes <= budget]
        if token_slo_ms is not None:
            fitting = [r for r in rows if r.latency_ms <= token_slo_ms]
            if not fitting and rows:
                # Nothing meets the SLO: serve with the fastest config
                # rather than refusing (the SLO viewer will show red).
                fitting = [min(rows, key=lambda r: r.latency_ms)]
            rows = fitting
        if not rows:
            raise ValueError(
                f"{self.model_name}: no measured decode config at "
                f"capacity {max_len} fits the HBM budget "
                f"({budget / 1e9:.1f} GB) — re-run the decode profiler"
            )
        best = max(rows, key=lambda r: r.batch_size / r.latency_ms)
        step_ms = best.latency_ms
        plan: Dict[str, int] = {"num_slots": int(best.batch_size)}
        horizon = self.decode_horizon
        if token_slo_ms is not None:
            horizon = max(1, int(token_slo_ms // step_ms))
            plan["decode_horizon"] = horizon
        if ttft_slo_ms is not None:
            prefill_ms = 0.0
            if prefill_profile is not None and prefill_profile.rows:
                largest = max(r.seq_len for r in prefill_profile.rows)
                singles = [
                    r for r in prefill_profile.rows
                    if r.seq_len == largest and r.batch_size == 1
                ] or [r for r in prefill_profile.rows
                      if r.seq_len == largest]
                prefill_ms = singles[0].latency_ms
            scan_budget = 0.8 * ttft_slo_ms - prefill_ms
            plan["ttft_horizon"] = int(
                min(max(1, scan_budget // step_ms), horizon)
            )
        logger.info(
            "%s: table plan at cap %d -> %s (step %.2f ms, %d candidate "
            "rows)", self.model_name, max_len, plan, step_ms, len(rows),
        )
        return plan

    def _table_plan(
        self, max_len: int, num_slots: Optional[int] = None,
    ) -> Optional[Dict[str, int]]:
        """Load committed tables from ``profiles_dir`` once per
        (capacity, pinned-slots) config; None when the decode table is
        absent (callers fall back to the analytic path)."""
        import os

        if self.profiles_dir is None:
            return None
        cache_key = (max_len, num_slots)
        if cache_key in self._table_plans:
            return self._table_plans[cache_key]
        from ray_dynamic_batching_tpu.profiles.table import BatchProfile

        decode_csv = os.path.join(
            self.profiles_dir, f"{self.model_name}_decode_summary.csv"
        )
        if not os.path.exists(decode_csv):
            logger.warning(
                "%s: profiles_dir=%s has no decode table — falling back "
                "to the analytic HBM model", self.model_name,
                self.profiles_dir,
            )
            return None
        decode_profile = BatchProfile.from_csv(
            f"{self.model_name}_decode", decode_csv
        )
        prefill_csv = os.path.join(
            self.profiles_dir, f"{self.model_name}_prefill_summary.csv"
        )
        prefill_profile = None
        if os.path.exists(prefill_csv):
            prefill_profile = BatchProfile.from_csv(
                f"{self.model_name}_prefill", prefill_csv
            )
        try:
            plan = self.plan_from_tables(
                decode_profile, prefill_profile, max_len=max_len,
                num_slots=num_slots,
            )
        except ValueError as e:
            # A table that exists but has no row at this capacity (swept at
            # different max_lens) must degrade exactly like a missing
            # table — raising here would crash-loop every replica start
            # until the controller marks the deployment unhealthy.
            logger.warning(
                "%s: committed tables unusable at capacity %d (%s) — "
                "falling back to the analytic HBM model",
                self.model_name, max_len, e,
            )
            plan = None
        self._table_plans[cache_key] = plan
        return plan

    def build_engine(
        self, queue: RequestQueue, device: Any = None, mesh: Any = None,
        max_len: Optional[int] = None, num_slots: Optional[int] = None,
    ) -> DecodeEngine:
        # ``num_slots`` override: the colocation control loop passes the
        # planner's placement shape (scheduler/llm_control.py) — an
        # explicit measured config outranks both the table plan and the
        # analytic HBM model below.
        self._ensure_model()
        # Same snapshot discipline as auto_num_slots: the model/param
        # pairs are published under _init_lock by _ensure_model.
        with self._init_lock:
            model, params = self._model, self._params
            draft_model = self._draft_model
            draft_params = self._draft_params
        max_len = max_len or self.max_len
        num_slots = num_slots if num_slots is not None else self.num_slots
        decode_horizon = self.decode_horizon
        ttft_horizon = self.ttft_horizon
        # Measured tables govern single-chip engines (they are per-chip
        # measurements; a TP mesh shards the program they describe). ANY
        # pinned slot count — the caller's colocation placement or the
        # deployment config's own num_slots — pins the plan to ITS
        # measured row, so the horizons below always describe the config
        # that actually runs, never the table's (different) best row.
        plan = (
            self._table_plan(
                max_len, num_slots=num_slots if num_slots > 0 else None
            )
            if mesh is None else None
        )
        if plan is not None:
            if num_slots <= 0:
                num_slots = plan["num_slots"]
            decode_horizon = plan.get("decode_horizon", decode_horizon)
            ttft_horizon = plan.get("ttft_horizon", ttft_horizon)
        elif num_slots <= 0:
            n_chips = mesh.devices.size if mesh is not None else 1
            num_slots = self.auto_num_slots(
                n_chips, max_len=max_len,
                budget_fraction=1.0 / len(self.length_buckets),
            )
        prompt_buckets = self.prompt_buckets
        if prompt_buckets is not None:
            fitting = [b for b in prompt_buckets if b <= max_len]
            prompt_buckets = fitting or [max_len]
        return DecodeEngine(
            model,
            params,
            queue,
            num_slots=num_slots,
            max_len=max_len,
            prompt_buckets=prompt_buckets,
            eos_token_id=self.eos_token_id,
            default_max_new_tokens=self.default_max_new_tokens,
            decode_horizon=decode_horizon,
            ttft_horizon=ttft_horizon,
            max_admissions_per_step=self.max_admissions_per_step,
            prefix_cache_size=self.prefix_cache_size,
            session_cache_size=self.session_cache_size,
            draft_model=draft_model,
            draft_params=draft_params,
            spec_tokens=self.spec_tokens,
            quantize_weights=self.quantize_weights,
            device=device,
            mesh=mesh,
            paged=self.paged,
            page_size=self.page_size,
            kv_pool_pages=self.kv_pool_pages,
            host_spill_pages=self.host_spill_pages,
            chunked_prefill=self.chunked_prefill,
            prefill_token_budget=self.prefill_token_budget,
        )

    # Controller protocol: factories exposing make_replica own replica
    # construction (the reference's deployment holds its replica class the
    # same way — deployment_state builds ReplicaActor from the deployment's
    # target state). ``devices`` arrives from the replica's placement-group
    # bundle when the deployment reserves chips.
    def make_replica(
        self, replica_id: str, config: Any, devices: Optional[Sequence] = None,
    ) -> LLMReplica:
        device = None
        mesh = None
        if devices and len(devices) > 1 and self.quantize_weights:
            # Fail BEFORE the mesh/engine build (and before the placement
            # group's chips are consumed by a doomed start).
            raise ValueError(
                f"{config.name}: quantize_weights is not supported for "
                "multi-chip (TP) replicas yet — drop chips_per_replica or "
                "the quantization flag"
            )
        if devices and len(devices) > 1:
            # Multi-chip bundle -> TP-sharded replica over its own mesh
            # slice (replica = mesh slice, SURVEY.md §7 stage 6).
            from ray_dynamic_batching_tpu.parallel.mesh import (
                MeshConfig,
                build_mesh,
            )

            mesh = build_mesh(MeshConfig(tp=len(devices)), list(devices))
        elif devices:
            device = devices[0]
        builders = {
            bucket: (
                lambda q, b=bucket: self.build_engine(
                    q, device=device, mesh=mesh, max_len=b
                )
            )
            for bucket in self.length_buckets
        }
        replica = LLMReplica(
            replica_id=replica_id,
            deployment=config.name,
            engine_builders=builders,
            max_ongoing_requests=config.max_ongoing_requests,
            warmup=self.warmup,
            default_max_new_tokens=self.default_max_new_tokens,
        )
        replica.devices = list(devices) if devices else None
        if self.session_cache_size > 0:
            # Session-affinity ids ride the replica's advertised multiplex
            # LRU; with the default bound of 8, more concurrent sessions
            # than that would age each other (and genuine model ids) out
            # of the routing view while their KV rows are still cached.
            replica.max_multiplexed_models = max(
                replica.max_multiplexed_models,
                len(self.length_buckets) * self.session_cache_size + 8,
            )
        return replica

    # Legacy callable protocol (factory() -> fn) is not meaningful here.
    def __call__(self) -> Callable[[List[Any]], Sequence[Any]]:
        raise TypeError(
            "LLMDeployment builds replicas via make_replica; register it "
            "with the controller directly"
        )
