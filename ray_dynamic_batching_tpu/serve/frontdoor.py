"""Sharded front door — N stateless proxy shards, one global budget.

One proxy + one admission table is a single-process ceiling no matter how
fast the engines are (PAPER.md's Serve router tier; ROADMAP item 5). This
module scales the front door OUT while keeping the admission contract
GLOBAL:

- :class:`HashRing` — consistent hashing with virtual nodes. Requests
  route to shards by **affinity key** (session id, else tenant, else the
  request id): a session's turns always land on the same shard (whose
  local state — admission history, keep-alive connection — stays warm),
  and a membership change moves only ~1/N of the key space.
- :class:`GlobalAdmissionLedger` — each shard's admissions land in its
  own :class:`~ray_dynamic_batching_tpu.utils.sketch.QuantileSketch` (the
  PR 8 mergeable-state primitive: per-shard sketches are disjoint, so the
  fleet view is an EXACT merge); shards gossip their serialized sketch
  states and keep peers' latest by replacement (a delta-state CRDT — a
  re-delivered or reordered gossip message cannot double-count). The
  admission decision compares the merged fleet count against the global
  budget line ``burst + rate * elapsed``.
- :class:`FrontDoorShard` — exposes exactly the ``admit(deployment,
  tenant, qos) -> (ok, retry_after_s)`` surface the HTTP/gRPC proxies
  already consult, so a shard drops into ``HTTPProxy(admission=shard)``
  unchanged. Optionally CHAINS a local
  :class:`~ray_dynamic_batching_tpu.serve.admission.AdmissionController`
  so per-(tenant, class) fairness and the overload governor keep working
  per shard under the global cap.
- :class:`FrontDoor` — owns the ring + shards + budgets, runs gossip
  (a deterministic ``gossip_round()`` the simulator drives on virtual
  time; a daemon thread in live mode), and AUDITS the price of
  distribution: :meth:`drift_audit` compares the true fleet admission
  count against the central-oracle allowance and records the
  over/under-admission drift next to every other control-plane decision.

Staleness bound (the contract the soak gate checks): between gossip
rounds each shard is blind to what the other ``N-1`` shards admitted in
the window, so fleet over-admission versus the oracle is bounded by
``(N - 1) * rate * staleness`` (+ one request per shard of rounding) —
tighten the gossip interval and the front door converges on the central
bucket it replaces. That bound only holds while gossip FLOWS: a
partitioned shard's staleness grows without limit, and with it the
over-admission. The ledger therefore enforces its own staleness
contract (ISSUE 12, opt-in via ``staleness_bound_s``): when any
expected peer's newest state is older than the bound, the ledger DEGRADES
fail-closed to a conservative local-fraction budget — own admissions
against ``allowed / N`` — so a gossip-partitioned fleet in aggregate
never exceeds the global allowance, at the price of under-admission
until heal. The transition is audited (``ledger_degraded``), counted
(``rdb_frontdoor_ledger_degraded_total``) and gauged; when gossip
resumes the ledger re-converges to the exact merged fleet count and
exits degraded mode.

Partition seam: peer-state absorption (the partitionable shard↔shard
edge) routes through the control fabric (``serve/fabric.py``), so the
partition soak drops/delays/duplicates gossip with the same seeded
policy the store and the long-poll channel ride.

Clock-injected throughout: the sim twin (sim/frontdoor.py) runs shards,
gossip, and budget math on the virtual clock, byte-deterministically.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_dynamic_batching_tpu.scheduler.audit import AuditLog
from ray_dynamic_batching_tpu.serve.fabric import ControlFabric, default_fabric
from ray_dynamic_batching_tpu.utils.logging import get_logger
from ray_dynamic_batching_tpu.utils import metrics as m
from ray_dynamic_batching_tpu.utils.sketch import QuantileSketch

logger = get_logger("frontdoor")

FRONTDOOR_ADMISSION = m.Counter(
    "rdb_frontdoor_admission_total",
    "Front-door global-budget decisions (outcome: admit | reject)",
    tag_keys=("deployment", "shard", "outcome"),
    bounded_tags={"shard": m.DEFAULT_SHARD_TOP_K},
)
FRONTDOOR_GOSSIP = m.Counter(
    "rdb_frontdoor_gossip_total", "Gossip exchanges completed",
    tag_keys=("shard",),
    bounded_tags={"shard": m.DEFAULT_SHARD_TOP_K},
)
FRONTDOOR_DRIFT = m.Gauge(
    "rdb_frontdoor_budget_drift",
    "Fleet admitted minus central-oracle allowance (positive = "
    "over-admission within the gossip staleness bound)",
    tag_keys=("deployment",),
)
FRONTDOOR_LEDGER_DEGRADED = m.Counter(
    "rdb_frontdoor_ledger_degraded_total",
    "Ledger transitions into fail-closed degraded mode (peer gossip "
    "staler than the bound: admit against the local fraction of the "
    "global budget until heal)",
    tag_keys=("deployment", "shard"),
    bounded_tags={"shard": m.DEFAULT_SHARD_TOP_K},
)
FRONTDOOR_LEDGER_DEGRADED_GAUGE = m.Gauge(
    "rdb_frontdoor_ledger_degraded",
    "1 while the shard's ledger for the deployment is in fail-closed "
    "degraded mode, else 0",
    tag_keys=("deployment", "shard"),
    bounded_tags={"shard": m.DEFAULT_SHARD_TOP_K},
)


def _hash64(key: str) -> int:
    """Deterministic 64-bit ring position (blake2b — NOT Python's
    ``hash``, whose per-process seed would re-deal the ring every
    restart and void session affinity)."""
    return int.from_bytes(
        hashlib.blake2b(key.encode(), digest_size=8).digest(), "big"
    )


class HashRing:
    """Consistent-hash ring with virtual nodes.

    ``vnodes`` points per shard smooth the key-space split (64 gives
    <~15% imbalance across 2-32 shards); removal of a shard hands only
    its arcs to the survivors — the ~1/N movement bound session
    affinity relies on."""

    def __init__(self, shard_ids: List[str], vnodes: int = 64) -> None:
        if vnodes <= 0:
            raise ValueError(f"vnodes must be positive, got {vnodes}")
        self.vnodes = int(vnodes)
        self._points: List[Tuple[int, str]] = []
        self._shards: List[str] = []
        for sid in shard_ids:
            self.add(sid)

    def add(self, shard_id: str) -> None:
        if shard_id in self._shards:
            return
        self._shards.append(shard_id)
        for v in range(self.vnodes):
            self._points.append((_hash64(f"{shard_id}#{v}"), shard_id))
        self._points.sort()

    def remove(self, shard_id: str) -> None:
        if shard_id not in self._shards:
            return
        self._shards.remove(shard_id)
        self._points = [(h, s) for h, s in self._points if s != shard_id]

    def shards(self) -> List[str]:
        return list(self._shards)

    def shard_for(self, key: str) -> str:
        if not self._points:
            raise ValueError("ring has no shards")
        h = _hash64(key)
        i = bisect.bisect_right(self._points, (h, "￿"))
        if i == len(self._points):
            i = 0
        return self._points[i][1]


def affinity_key(payload: Any = None, tenant: Optional[str] = None,
                 request_id: Optional[str] = None) -> str:
    """The ring key: session id wins (a conversation's turns must reuse
    one shard's warm state), then tenant (a tenant's requests share one
    shard's bucket locality), then the request id (stateless spread)."""
    if isinstance(payload, dict) and payload.get("session_id") is not None:
        return f"session:{payload['session_id']}"
    if tenant:
        return f"tenant:{tenant}"
    return f"request:{request_id or ''}"


@dataclass
class GlobalBudget:
    """A deployment's cluster-wide admission contract: the fleet may
    admit at most ``burst + rate_rps * elapsed`` requests in total,
    enforced across every shard through gossip. ``t0`` anchors the
    allowance line; every shard uses the same anchor."""

    rate_rps: float
    burst: float
    t0: float

    def allowed(self, now: float) -> float:
        return self.burst + self.rate_rps * max(0.0, now - self.t0)


class GlobalAdmissionLedger:
    """One shard's view of one deployment's fleet-wide admissions.

    Own admissions are observed into a :class:`QuantileSketch` (value =
    seconds since the budget anchor, so the merged fleet sketch also
    carries the admission-time distribution for the drift audit); peer
    states arrive as serialized sketches and are kept BY REPLACEMENT
    keyed on shard id — merging happens at read time over own + latest
    peers, which makes gossip idempotent (delta-state CRDT) where naive
    fold-on-receive would double-count every re-delivery.

    Staleness contract (fail-closed, opt-in via ``staleness_bound_s``
    > 0 with ``n_shards`` > 1): every absorb stamps arrival time; when
    ANY expected peer's newest state is older than the bound — the
    stalest peer governs, so a PARTIAL partition (same-side gossip
    still fresh, the far side frozen) degrades exactly like a full one;
    a peer never heard from counts from the budget anchor —
    :meth:`check` degrades to own admissions against
    ``allowed / n_shards``. All shards degrading independently still
    sum to at most the global allowance — the partition can only
    UNDER-admit, never over-admit unboundedly. Fresh gossip from every
    peer clears the degradation and the merged count resumes
    (re-convergence is automatic: the CRDT replacement needs no repair
    pass). Departed shards are RETIRED (:meth:`retire_peer`): their
    final history keeps counting but never goes stale, and the live
    fleet width shrinks with them."""

    def __init__(self, shard_id: str, budget: GlobalBudget,
                 n_shards: int = 1,
                 staleness_bound_s: float = 0.0) -> None:
        self.shard_id = shard_id
        self.budget = budget
        self.n_shards = max(1, int(n_shards))
        self.staleness_bound_s = float(staleness_bound_s)
        self._own = QuantileSketch(relative_accuracy=0.01)
        self._peers: Dict[str, Dict[str, Any]] = {}
        self._peer_seen_at: Dict[str, float] = {}
        self._static_peers: set = set()   # departed: final history, exempt
        self.degraded = False
        self.degraded_entries = 0    # transitions INTO degraded mode

    @property
    def own_count(self) -> int:
        return self._own.count

    def peer_count(self) -> int:
        return sum(int(s.get("count", 0)) for s in self._peers.values())

    def merged_count(self) -> int:
        return self._own.count + self.peer_count()

    def merged_sketch(self) -> QuantileSketch:
        """The fleet view, via the PR 8 merge primitive: per-shard
        sketches are disjoint observation sets, so bucket adds are exact
        and the merged count is the true fleet total as of each shard's
        last publication."""
        peers = [QuantileSketch.from_dict(s) for s in self._peers.values()]
        out = QuantileSketch(relative_accuracy=self._own.relative_accuracy)
        out.merge(self._own)
        for p in peers:
            out.merge(p)
        return out

    def peer_staleness_s(self, now: float) -> float:
        """Age of the STALEST live peer's newest state (the budget
        anchor stands in for peers never heard from). The stalest peer
        governs because any invisible slice of the fleet voids the
        merged count — a partial partition must fail closed exactly
        like a full one."""
        live = {sid: t for sid, t in self._peer_seen_at.items()
                if sid not in self._static_peers}
        ages = [now - t for t in live.values()]
        if len(live) < self.n_shards - 1:
            ages.append(now - self.budget.t0)
        return max(0.0, max(ages)) if ages else 0.0

    def stale(self, now: float) -> bool:
        return (self.n_shards > 1
                and self.staleness_bound_s > 0.0
                and self.peer_staleness_s(now) > self.staleness_bound_s)

    def refresh(self, now: float) -> None:
        """Re-evaluate the degraded flag from the staleness contract
        alone (no admission decision): gossip progress and the passage
        of time must move the flag — and the gauge/audit riding it —
        even for a deployment nobody is admitting through."""
        self.degraded = self.stale(now)

    def retire_peer(self, shard_id: str) -> None:
        """A peer left the ring for good: its (final-flushed) history
        keeps counting in the merged view but is exempt from the
        staleness contract, and the live fleet width shrinks — the
        degraded local fraction is a share of the SURVIVORS."""
        self._static_peers.add(shard_id)
        self._peer_seen_at.pop(shard_id, None)
        self.n_shards = max(1, self.n_shards - 1)

    def check(self, now: float) -> Tuple[bool, float]:
        """(would_admit, retry_after_s) against the GLOBAL allowance as
        this shard currently sees it — read-only, so a later local-layer
        reject never burns a global token. The retry hint is when the
        allowance line reaches the known count — exact once gossip
        catches up, conservative before.

        When peer gossip is staler than the bound, the decision
        DEGRADES fail-closed: own admissions against the local fraction
        ``allowed / n_shards`` (flagged on ``self.degraded``; the shard
        audits and meters the transition)."""
        if self.stale(now):
            self.degraded = True
            allowed = self.budget.allowed(now) / self.n_shards
            count = self._own.count
            rate = self.budget.rate_rps / self.n_shards
        else:
            self.degraded = False
            allowed = self.budget.allowed(now)
            count = self.merged_count()
            rate = self.budget.rate_rps
        if count < allowed:
            return True, 0.0
        if rate <= 0.0:
            return False, 60.0  # administratively closed: poll slowly
        return False, (count - allowed + 1.0) / rate

    def commit(self, now: float) -> None:
        """Record one admission (after every layer passed)."""
        self._own.observe(max(0.0, now - self.budget.t0))

    def admit(self, now: float) -> Tuple[bool, float]:
        """check + commit in one step (single-layer callers)."""
        ok, retry_after_s = self.check(now)
        if ok:
            self.commit(now)
        return ok, retry_after_s

    def state(self) -> Dict[str, Any]:
        """This shard's serialized contribution (gossip payload)."""
        return self._own.to_dict()

    def absorb(self, shard_id: str, state: Dict[str, Any],
               now: Optional[float] = None) -> None:
        """Keep ``shard_id``'s latest state by replacement; ``now``
        stamps the arrival for the staleness contract. Idempotent and
        reorder-safe by construction — a duplicated or late gossip
        delivery replaces with the same (or an older) state, never
        double-counts."""
        if shard_id == self.shard_id:
            return
        prev_state = self._peers.get(shard_id)
        # A peer's own-admission count is monotone, so it doubles as the
        # CRDT version: a reordered (late) delivery carrying an OLDER
        # state must not rewind the newer one already absorbed.
        if (prev_state is None
                or int(state.get("count", 0))
                >= int(prev_state.get("count", 0))):
            self._peers[shard_id] = state
        if now is not None:
            prev = self._peer_seen_at.get(shard_id)
            # The freshness stamp is monotone per peer too: a straggler
            # delivery cannot rewind the staleness the contract judges.
            if prev is None or now >= prev:
                self._peer_seen_at[shard_id] = now

    def forget(self, shard_id: str) -> None:
        self._peers.pop(shard_id, None)
        self._peer_seen_at.pop(shard_id, None)


class GossipBus:
    """In-process gossip board: each shard publishes its latest ledger
    states; collectors read every other shard's latest. Deterministic
    (sorted iteration, versioned payloads) so the sim twin's rounds are
    replayable; the live FrontDoor drives it from a daemon thread."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # shard_id -> (version, {deployment: state})
        self._board: Dict[str, Tuple[int, Dict[str, Dict[str, Any]]]] = {}
        self._version = 0

    def publish(self, shard_id: str,
                states: Dict[str, Dict[str, Any]]) -> int:
        with self._lock:
            self._version += 1
            self._board[shard_id] = (self._version, states)
            return self._version

    def collect(self, reader_shard_id: str
                ) -> List[Tuple[str, Dict[str, Dict[str, Any]]]]:
        with self._lock:
            return [
                (sid, states)
                for sid, (_, states) in sorted(self._board.items())
                if sid != reader_shard_id
            ]

    def drop(self, shard_id: str) -> None:
        with self._lock:
            self._board.pop(shard_id, None)


class FrontDoorShard:
    """One stateless front-door shard: global-budget ledgers + optional
    local per-(tenant, class) admission. Exposes the proxies' admission
    surface — ``HTTPProxy(admission=shard, shard_id=shard.shard_id)``
    wires a real HTTP door to it unchanged."""

    def __init__(
        self,
        shard_id: str,
        clock: Callable[[], float] = time.monotonic,
        local: Optional[Any] = None,
        n_shards: int = 1,
        staleness_bound_s: float = 0.0,
    ) -> None:
        self.shard_id = str(shard_id)
        self._clock = clock
        # Optional serve.admission.AdmissionController: per-tenant
        # fairness + overload governor, local to this shard, under the
        # global cap (checked first — the global budget is the outer
        # contract).
        self.local = local
        # Fail-closed staleness contract knobs (0 disables — legacy
        # fail-open); the FrontDoor sets them fleet-wide.
        self.n_shards = max(1, int(n_shards))
        self.staleness_bound_s = float(staleness_bound_s)
        # Audit sink for ledger_degraded transitions (the FrontDoor
        # shares its ring so degradations land next to drift audits).
        self.audit: Optional[AuditLog] = None
        self._lock = threading.Lock()
        self._ledgers: Dict[str, GlobalAdmissionLedger] = {}
        self._was_degraded: Dict[str, bool] = {}
        self.admitted = 0
        self.rejected = 0
        self.degraded_rejects = 0

    def configure(self, deployment: str,
                  budget: Optional[GlobalBudget]) -> None:
        with self._lock:
            if budget is None:
                self._ledgers.pop(deployment, None)
                self._was_degraded.pop(deployment, None)
            else:
                self._ledgers[deployment] = GlobalAdmissionLedger(
                    self.shard_id, budget,
                    n_shards=self.n_shards,
                    staleness_bound_s=self.staleness_bound_s,
                )

    def _note_degradation_edge(self, deployment: str,
                               ledger: GlobalAdmissionLedger,
                               now: float) -> None:
        """Audit + meter the degraded-mode EDGES (called with the shard
        lock held; transitions are rare, the steady state is one dict
        probe + compare)."""
        was = self._was_degraded.get(deployment, False)
        if ledger.degraded == was:
            return
        self._was_degraded[deployment] = ledger.degraded
        tags = {"deployment": deployment, "shard": self.shard_id}
        if ledger.degraded:
            ledger.degraded_entries += 1
            FRONTDOOR_LEDGER_DEGRADED.inc(tags=tags)
            FRONTDOOR_LEDGER_DEGRADED_GAUGE.set(1.0, tags=tags)
            if self.audit is not None:
                self.audit.record(
                    "ledger_degraded",
                    key=deployment,
                    observed={
                        "shard": self.shard_id,
                        "peer_staleness_s": round(
                            ledger.peer_staleness_s(now), 3),
                        "bound_s": ledger.staleness_bound_s,
                        "own_count": ledger.own_count,
                        "local_fraction_allowance": round(
                            ledger.budget.allowed(now) / ledger.n_shards,
                            3),
                    },
                    note="peer gossip staler than the bound: fail-closed "
                         "to the local-fraction budget until heal",
                )
        else:
            FRONTDOOR_LEDGER_DEGRADED_GAUGE.set(0.0, tags=tags)
            if self.audit is not None:
                self.audit.record(
                    "ledger_reconverged",
                    key=deployment,
                    observed={"shard": self.shard_id,
                              "merged_count": ledger.merged_count()},
                    note="gossip resumed inside the bound: merged fleet "
                         "view restored",
                )

    def admit(self, deployment: str, tenant: str = "",
              qos_class: str = "standard") -> Tuple[bool, float]:
        """(admitted, retry_after_s) — global ledger CHECK (read-only),
        then the shard-local controller (which debits its own bucket),
        then the global COMMIT, all under ONE shard lock: a reject at
        either layer burns no global token, and two concurrent requests
        can never both pass the check before either commits (the
        intra-shard TOCTOU would over-admit past the documented
        staleness bound). The local layer is a leaf lock with
        microsecond bucket math, so serializing a shard's admissions
        through it is the cheap, correct trade — shards scale OUT, not
        by intra-shard admission concurrency."""
        with self._lock:
            ledger = self._ledgers.get(deployment)
            if ledger is not None:
                now = self._clock()
                ok, retry_after_s = ledger.check(now)
                self._note_degradation_edge(deployment, ledger, now)
                if not ok:
                    self.rejected += 1
                    if ledger.degraded:
                        self.degraded_rejects += 1
                    outcome = "reject"
                else:
                    outcome = None
            else:
                outcome = None
            if outcome is None and self.local is not None:
                ok, retry_after_s = self.local.admit(deployment, tenant,
                                                     qos_class)
                if not ok:
                    self.rejected += 1
                    outcome = "reject"
            if outcome is None:
                if ledger is not None:
                    ledger.commit(self._clock())
                self.admitted += 1
                ok, retry_after_s = True, 0.0
                outcome = "admit"
        FRONTDOOR_ADMISSION.inc(tags={
            "deployment": deployment, "shard": self.shard_id,
            "outcome": outcome,
        })
        return ok, retry_after_s

    def gossip_states(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {dep: lg.state() for dep, lg in self._ledgers.items()}

    def absorb_states(self, shard_id: str,
                      states: Dict[str, Dict[str, Any]]) -> None:
        """Absorb one peer's ledger states, stamped at DELIVERY time —
        a fabric-delayed absorb arrives late and the staleness contract
        must judge what this shard actually knew, not what was sent."""
        with self._lock:
            now = self._clock()
            for dep, state in states.items():
                ledger = self._ledgers.get(dep)
                if ledger is not None:
                    ledger.absorb(shard_id, state, now=now)

    def ledger(self, deployment: str) -> Optional[GlobalAdmissionLedger]:
        with self._lock:
            return self._ledgers.get(deployment)

    def refresh_degradation(self) -> None:
        """Sweep every ledger's degraded flag from the staleness
        contract and account the edges. Driven by the gossip round, so
        an IDLE deployment still degrades when its peers go silent and
        — critically — re-converges (gauge back to 0, audited) on heal
        instead of standing as a false alarm until the next admission
        happens to arrive."""
        with self._lock:
            now = self._clock()
            for dep, ledger in self._ledgers.items():
                ledger.refresh(now)
                self._note_degradation_edge(dep, ledger, now)

    def ledger_snapshot(self) -> Dict[str, Any]:
        """Degradation view for stats(): transition count + which
        deployments are currently fail-closed."""
        with self._lock:
            return {
                "degraded_entries": sum(lg.degraded_entries
                                        for lg in self._ledgers.values()),
                "degraded_now": sorted(dep for dep, lg in
                                       self._ledgers.items()
                                       if lg.degraded),
            }


class FrontDoor:
    """The sharded front door: ring + shards + budgets + gossip + audit.

    ``clock`` injects the time source (sim: virtual seconds).
    ``local_admission_factory`` builds each shard's optional local
    AdmissionController (per-tenant fairness under the global cap)."""

    def __init__(
        self,
        n_shards: int = 2,
        clock: Callable[[], float] = time.monotonic,
        gossip_interval_s: float = 0.2,
        vnodes: int = 64,
        local_admission_factory: Optional[Callable[[], Any]] = None,
        fabric: Optional[ControlFabric] = None,
        staleness_bound_s: float = 0.0,
    ) -> None:
        if n_shards <= 0:
            raise ValueError(f"n_shards must be positive, got {n_shards}")
        self._clock = clock
        self.gossip_interval_s = float(gossip_interval_s)
        # The shard↔shard absorb edge routes through the fabric so a
        # partition/chaos policy applies to gossip; unconfigured it is
        # the zero-overhead passthrough.
        self.fabric = fabric if fabric is not None else default_fabric()
        # Fail-closed staleness bound per ledger (0 = disabled). A sane
        # arming is a few gossip intervals: missing one round is jitter,
        # missing several is a partition.
        self.staleness_bound_s = float(staleness_bound_s)
        self.bus = GossipBus()
        self.shards: Dict[str, FrontDoorShard] = {}
        ids = [f"fd-{i}" for i in range(n_shards)]
        for sid in ids:
            self.shards[sid] = FrontDoorShard(
                sid, clock=clock,
                local=(local_admission_factory()
                       if local_admission_factory is not None else None),
                n_shards=n_shards,
                staleness_bound_s=self.staleness_bound_s,
            )
        self.ring = HashRing(ids, vnodes=vnodes)
        self._budgets: Dict[str, GlobalBudget] = {}
        # deployment -> admissions by shards REMOVED from the ring:
        # their history must keep counting in the oracle (admissions
        # that happened, happened) or drift_audit under-reports.
        self._departed_admitted: Dict[str, int] = {}
        # Drift audits land next to heals/replans/governor flips — the
        # front door is a control plane and owes the same paper trail.
        # Shards share the ring so ledger_degraded transitions file into
        # the same timeline as the drift they bound.
        self.audit = AuditLog("frontdoor", now=clock)
        for shard in self.shards.values():
            shard.audit = self.audit
        self.gossip_rounds = 0
        self._last_gossip_at = clock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # --- configuration ----------------------------------------------------
    def configure(self, deployment: str, rate_rps: float,
                  burst: float = 0.0) -> None:
        """Install (rate <= 0 removes) a deployment's global budget on
        every shard, anchored at one shared t0."""
        if rate_rps <= 0:
            self._budgets.pop(deployment, None)
            for shard in self.shards.values():
                shard.configure(deployment, None)
            return
        budget = GlobalBudget(
            rate_rps=float(rate_rps),
            burst=float(burst) if burst > 0 else float(rate_rps),
            t0=self._clock(),
        )
        self._budgets[deployment] = budget
        for shard in self.shards.values():
            shard.configure(deployment, budget)

    def budget(self, deployment: str) -> Optional[GlobalBudget]:
        return self._budgets.get(deployment)

    # --- routing + admission ----------------------------------------------
    def shard_for(self, key: str) -> FrontDoorShard:
        return self.shards[self.ring.shard_for(key)]

    def admit(self, deployment: str, payload: Any = None,
              tenant: str = "", qos_class: str = "standard",
              request_id: Optional[str] = None
              ) -> Tuple[str, bool, float]:
        """Route by affinity key, then admit on the owning shard:
        ``(shard_id, admitted, retry_after_s)``."""
        shard = self.shard_for(affinity_key(payload, tenant, request_id))
        ok, retry_after_s = shard.admit(deployment, tenant, qos_class)
        return shard.shard_id, ok, retry_after_s

    # --- gossip -----------------------------------------------------------
    def gossip_round(self) -> None:
        """One full exchange: every shard publishes, every shard absorbs
        every peer's latest. Deterministic (sorted shard order) — the
        sim twin calls this on virtual-time ticks; live mode calls it
        from the gossip thread.

        The PARTITIONABLE edge is peer→shard absorption, routed through
        the fabric with the peer as ``src`` and the reader as ``dst``:
        a node-group partition drops exactly the cross-side exchanges
        while same-side gossip keeps flowing — the asymmetry the
        fail-closed staleness contract is tested against. The board
        publish/collect itself is a process-local snapshot (each shard
        logically owns its slice), so those stay direct."""
        for sid in sorted(self.shards):
            self.bus.publish(sid, self.shards[sid].gossip_states())  # rdb-lint: disable=fabric-discipline (publish lands on the shard's own board slice — the network edge is the peer→shard absorb below)
        for sid in sorted(self.shards):
            shard = self.shards[sid]
            for peer_id, states in self.bus.collect(sid):  # rdb-lint: disable=fabric-discipline (collect reads the local board snapshot; delivery to the reader is the fabric-routed absorb)
                self.fabric.cast(
                    "frontdoor.gossip", shard.absorb_states, peer_id,
                    states, src=peer_id, dst=sid,
                )
            # Degradation edges move with GOSSIP progress, not only
            # admission traffic: an idle deployment's gauge must clear
            # on heal and set on silence all the same.
            shard.refresh_degradation()
            FRONTDOOR_GOSSIP.inc(tags={"shard": sid})
        self.gossip_rounds += 1
        self._last_gossip_at = self._clock()

    def staleness_s(self) -> float:
        return max(0.0, self._clock() - self._last_gossip_at)

    # --- membership -------------------------------------------------------
    def remove_shard(self, shard_id: str) -> None:
        """Take a shard out of the ring (crash or drain): its keys move
        to the survivors (~1/N of the space), its ledger contributions
        REMAIN in peers' views (admissions that happened, happened), and
        it stops receiving traffic."""
        if shard_id not in self.shards:
            return
        self.ring.remove(shard_id)
        departed = self.shards.pop(shard_id)
        # Nobody will ever refresh the departed shard's gauge series
        # again: clear it now or a shard removed mid-degradation stands
        # as a false alarm forever.
        for dep in self._budgets:
            FRONTDOOR_LEDGER_DEGRADED_GAUGE.set(
                0.0, tags={"deployment": dep, "shard": shard_id})
        # Final flush: peers must account the departed shard's full
        # history or the fleet view under-counts forever.
        self.bus.publish(shard_id, departed.gossip_states())  # rdb-lint: disable=fabric-discipline (membership admin runs where the board lives; a shard leaves the ring exactly once, not over a partitionable edge)
        # And the ORACLE must too: true_admitted sums live shards' own
        # counts, so the departed shard's history moves to a baseline.
        for dep in self._budgets:
            ledger = departed.ledger(dep)
            if ledger is not None:
                self._departed_admitted[dep] = (
                    self._departed_admitted.get(dep, 0) + ledger.own_count
                )
        for sid in sorted(self.shards):
            for peer_id, states in self.bus.collect(sid):  # rdb-lint: disable=fabric-discipline (same admin pass: survivors adopt the departed history synchronously so the oracle never under-counts)
                self.shards[sid].absorb_states(peer_id, states)  # rdb-lint: disable=fabric-discipline (membership flush must be atomic with the ring change — deferring it through chaos would double- or zero-count the departed shard)
            # The departed shard's history is final: exempt it from the
            # staleness contract and shrink the live fleet width, or the
            # survivors would degrade fail-closed forever on a peer that
            # can never gossip again.
            for dep in self._budgets:
                ledger = self.shards[sid].ledger(dep)
                if ledger is not None:
                    ledger.retire_peer(shard_id)
            # Ledgers configured AFTER this removal must be born at the
            # surviving fleet width too — a new deployment sized for the
            # old N would wait forever on a peer that no longer exists
            # and degrade fail-closed permanently.
            self.shards[sid].n_shards = len(self.shards)
        self.audit.record(
            "shard_removed",
            observed={"shard": shard_id,
                      "remaining": sorted(self.shards)},
            note="ring re-dealt ~1/N of the key space to survivors",
        )

    # --- drift audit ------------------------------------------------------
    def true_admitted(self, deployment: str) -> int:
        """The oracle count: every shard's OWN admissions plus departed
        shards' history, read directly (no gossip lag) — what a central
        bucket would have counted."""
        total = self._departed_admitted.get(deployment, 0)
        for shard in self.shards.values():
            ledger = shard.ledger(deployment)
            if ledger is not None:
                total += ledger.own_count
        return total

    def drift_bound(self, deployment: str) -> float:
        """The analytic staleness bound: (N-1) * rate * staleness plus
        one request per shard of rounding."""
        budget = self._budgets.get(deployment)
        if budget is None:
            return 0.0
        n = len(self.shards)
        return ((n - 1) * budget.rate_rps
                * max(self.staleness_s(), self.gossip_interval_s)
                + n)

    def drift_audit(self, deployment: str) -> Dict[str, float]:
        """Over/under-admission versus the central oracle, recorded in
        the audit ring and the drift gauge. ``over_admitted`` > 0 is the
        price of distribution and must stay within ``bound``; the soak
        gate pins exactly that."""
        budget = self._budgets.get(deployment)
        if budget is None:
            return {}
        now = self._clock()
        admitted = self.true_admitted(deployment)
        allowed = budget.allowed(now)
        drift = admitted - allowed
        out = {
            "admitted": float(admitted),
            "allowed": round(allowed, 3),
            "over_admitted": round(max(0.0, drift), 3),
            "bound": round(self.drift_bound(deployment), 3),
            "staleness_s": round(self.staleness_s(), 6),
            "shards": float(len(self.shards)),
        }
        FRONTDOOR_DRIFT.set(drift, tags={"deployment": deployment})
        self.audit.record(
            "admission_drift",
            key=deployment,
            observed=out,
            note="fleet admissions vs central-oracle allowance "
                 "(bounded by (N-1)*rate*staleness)",
        )
        return out

    # --- live gossip thread -----------------------------------------------
    def start(self) -> "FrontDoor":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._gossip_loop, name="frontdoor-gossip", daemon=True
        )
        self._thread.start()
        return self

    def _gossip_loop(self) -> None:
        while not self._stop.wait(self.gossip_interval_s):
            try:
                self.gossip_round()
            except Exception:  # noqa: BLE001 — gossip must not die quietly
                logger.exception("gossip round failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def stats(self) -> Dict[str, Any]:
        return {
            "shards": {
                sid: {"admitted": s.admitted, "rejected": s.rejected,
                      "degraded_rejects": s.degraded_rejects,
                      **s.ledger_snapshot()}
                for sid, s in sorted(self.shards.items())
            },
            "gossip_rounds": self.gossip_rounds,
            "staleness_s": round(self.staleness_s(), 6),
        }
