"""Declarative serve config — the ``serve deploy config.yaml`` surface.

Re-creates the reference's config-driven deployment path (pydantic schemas
in ``python/ray/serve/schema.py``, applied by ``serve deploy`` /
``serve.run`` with ``import_path`` app targets): a JSON/YAML document
describing applications and their deployments, validated into dataclasses,
resolved via ``module:attribute`` import paths, and applied to a
controller. TPU addition: a deployment may instead declare a built-in
``llm`` target (model name + decode-engine knobs) — the flagship serving
path needs no user module.

```yaml
applications:
  - name: text
    route_prefix: /classify
    deployments:
      - name: classifier
        import_path: my_pkg.apps:classifier_app   # Deployment or Application
        num_replicas: 2
  - name: chat
    deployments:
      - name: llama
        llm: {model: llama_tiny, num_slots: 8}
        # Multi-tenant QoS (DeploymentConfig fields flow straight through):
        default_qos_class: interactive     # tier for undeclared requests
        admission_rate_rps: 500.0          # per-(tenant, class) bucket
```
"""

from __future__ import annotations

import importlib
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_dynamic_batching_tpu.serve.api import (
    Application,
    Deployment,
    run as _run_app,
)
from ray_dynamic_batching_tpu.serve.autoscaling import AutoscalingConfig
from ray_dynamic_batching_tpu.serve.controller import DeploymentConfig
from ray_dynamic_batching_tpu.serve.handle import DeploymentHandle
from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("serve.schema")

# DeploymentConfig fields a config document may set directly.
_CONFIG_FIELDS = frozenset(DeploymentConfig.__dataclass_fields__) - {
    "name", "autoscaling", "user_config"
}


@dataclass
class DeploymentSchema:
    """One deployment entry (ref schema.py DeploymentSchema)."""

    name: str
    import_path: Optional[str] = None
    llm: Optional[Dict[str, Any]] = None
    init_args: List[Any] = field(default_factory=list)
    init_kwargs: Dict[str, Any] = field(default_factory=dict)
    autoscaling: Optional[Dict[str, Any]] = None
    user_config: Dict[str, Any] = field(default_factory=dict)
    options: Dict[str, Any] = field(default_factory=dict)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "DeploymentSchema":
        if "name" not in d:
            raise ValueError("deployment entry missing 'name'")
        known = {"name", "import_path", "llm", "init_args", "init_kwargs",
                 "autoscaling", "user_config"}
        options = {k: v for k, v in d.items() if k not in known}
        bad = set(options) - _CONFIG_FIELDS
        if bad:
            raise ValueError(
                f"deployment {d['name']!r}: unknown fields {sorted(bad)}"
            )
        return DeploymentSchema(
            name=d["name"],
            import_path=d.get("import_path"),
            llm=d.get("llm"),
            init_args=list(d.get("init_args", ())),
            init_kwargs=dict(d.get("init_kwargs", {})),
            autoscaling=d.get("autoscaling"),
            user_config=dict(d.get("user_config", {})),
            options=options,
        )


@dataclass
class ApplicationSchema:
    """One application: a route prefix plus its deployments (ref
    ServeApplicationSchema)."""

    name: str
    deployments: List[DeploymentSchema]
    route_prefix: Optional[str] = None

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ApplicationSchema":
        if "name" not in d:
            raise ValueError("application entry missing 'name'")
        deps = d.get("deployments") or []
        if not deps:
            raise ValueError(f"application {d['name']!r} has no deployments")
        return ApplicationSchema(
            name=d["name"],
            deployments=[DeploymentSchema.from_dict(x) for x in deps],
            route_prefix=d.get("route_prefix"),
        )


@dataclass
class ServeConfigSchema:
    """Top-level document (ref ServeDeploySchema)."""

    applications: List[ApplicationSchema]

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "ServeConfigSchema":
        apps = d.get("applications") or []
        if not apps:
            raise ValueError("config has no applications")
        names = [a.get("name") for a in apps]
        if len(names) != len(set(names)):
            raise ValueError(f"duplicate application names in {names}")
        schema = ServeConfigSchema(
            applications=[ApplicationSchema.from_dict(a) for a in apps]
        )
        # Deployment names are controller-global: a duplicate ACROSS apps
        # would alias both onto one deployment (old factory, new config)
        # with no error from the controller.
        dep_names = [
            d.name for a in schema.applications for d in a.deployments
        ]
        dupes = {n for n in dep_names if dep_names.count(n) > 1}
        if dupes:
            raise ValueError(f"duplicate deployment names: {sorted(dupes)}")
        return schema


def load_config(path: str) -> ServeConfigSchema:
    """Parse a JSON or YAML config file into the validated schema."""
    with open(path) as f:
        text = f.read()
    if path.endswith((".yaml", ".yml")):
        import yaml  # transformers dependency; present in this image

        doc = yaml.safe_load(text)
    else:
        doc = json.loads(text)
    return ServeConfigSchema.from_dict(doc)


def _import_target(import_path: str) -> Any:
    """Resolve ``module.path:attribute`` (ref common.py import_attr)."""
    if ":" not in import_path:
        raise ValueError(
            f"import_path {import_path!r} must be 'module:attribute'"
        )
    module_name, attr = import_path.split(":", 1)
    module = importlib.import_module(module_name)
    target = module
    for part in attr.split("."):
        target = getattr(target, part)
    return target


def _build_application(spec: DeploymentSchema) -> Any:
    """Deployment entry -> something deployable: an Application from an
    import path, or a built-in LLMDeployment."""
    if (spec.import_path is None) == (spec.llm is None):
        raise ValueError(
            f"deployment {spec.name!r}: exactly one of import_path/llm"
        )
    if spec.llm is not None:
        if spec.init_args or spec.init_kwargs:
            raise ValueError(
                f"deployment {spec.name!r}: llm targets take their knobs "
                "inside the llm mapping; drop init_args/init_kwargs"
            )
        from ray_dynamic_batching_tpu.serve.llm import LLMDeployment

        llm_kwargs = dict(spec.llm)
        model = llm_kwargs.pop("model", None)
        if model is None:
            raise ValueError(f"deployment {spec.name!r}: llm needs 'model'")
        return LLMDeployment(model, **llm_kwargs)
    target = _import_target(spec.import_path)
    if isinstance(target, Application):
        if spec.init_args or spec.init_kwargs:
            raise ValueError(
                f"deployment {spec.name!r}: import_path already bound; "
                "drop init_args/init_kwargs"
            )
        return target
    if isinstance(target, Deployment):
        return target.bind(*spec.init_args, **spec.init_kwargs)
    if callable(target):  # bare class/function: wrap with defaults
        from ray_dynamic_batching_tpu.serve.api import deployment

        return deployment(target).bind(*spec.init_args, **spec.init_kwargs)
    raise TypeError(
        f"deployment {spec.name!r}: {spec.import_path} resolved to "
        f"{type(target).__name__}, not a Deployment/Application/callable"
    )


def apply_config(
    config: ServeConfigSchema,
    controller: Any = None,
) -> Dict[str, DeploymentHandle]:
    """Deploy every application; returns deployment-name -> handle (ref
    serve deploy applying ServeDeploySchema via the controller)."""
    handles: Dict[str, DeploymentHandle] = {}
    for app in config.applications:
        for i, spec in enumerate(app.deployments):
            built = _build_application(spec)
            overrides = dict(spec.options)
            overrides["name"] = spec.name
            if spec.user_config:
                overrides["user_config"] = spec.user_config
            if spec.autoscaling is not None:
                overrides["autoscaling"] = AutoscalingConfig(
                    **spec.autoscaling
                )
            # Route goes to the app's FIRST deployment (the app ingress,
            # ref: one route_prefix per application).
            route = app.route_prefix if i == 0 else None
            if isinstance(built, Application):
                built = Application(
                    built.deployment.options(**overrides),
                    built.args, built.kwargs,
                )
                handles[spec.name] = _run_app(
                    built, route_prefix=route, controller=controller
                )
            else:
                # Built-in deployment object (LLMDeployment): controller
                # factory path with config assembled from the schema.
                from ray_dynamic_batching_tpu.serve.api import (
                    _get_controller,
                    _get_proxy,
                )

                cfg_kwargs = {
                    k: v for k, v in overrides.items() if k != "name"
                }
                cfg = DeploymentConfig(name=spec.name, **cfg_kwargs)
                ctl = controller or _get_controller()
                router = ctl.deploy(cfg, factory=built)
                handles[spec.name] = DeploymentHandle(
                    router, default_qos_class=cfg.default_qos_class
                )
                if route is not None:
                    proxy = _get_proxy()
                    # Same wiring as serve.api.run: the front door must
                    # grade against THIS controller's admission table or
                    # a YAML-configured admission_rate_rps is a no-op.
                    proxy.admission = ctl.admission
                    proxy.router.set_route(route, handles[spec.name])
        logger.info(
            "application %s: deployed %s",
            app.name, [d.name for d in app.deployments],
        )
    return handles


def run_config(path: str, controller: Any = None) -> Dict[str, DeploymentHandle]:
    """``serve deploy <file>`` in one call: load, validate, apply."""
    return apply_config(load_config(path), controller=controller)


def _main() -> int:
    """``python -m ray_dynamic_batching_tpu.serve.schema <config> [--block]``
    — the ``serve deploy`` CLI role."""
    import sys
    import time

    args = [a for a in sys.argv[1:] if a != "--block"]
    if not args:
        print("usage: python -m ray_dynamic_batching_tpu.serve.schema "
              "<config.{json,yaml}> [--block]", file=sys.stderr)
        return 2
    handles = run_config(args[0])
    from ray_dynamic_batching_tpu.serve.api import get_proxy

    proxy = get_proxy()
    print(json.dumps({
        "deployments": sorted(handles),
        "http": f"http://127.0.0.1:{proxy.port}" if proxy else None,
    }))
    if "--block" in sys.argv:
        try:
            while True:  # rdb-lint: disable=unbounded-retry (CLI --block foreground park, not a retry loop; the only exit is KeyboardInterrupt by design)
                time.sleep(3600)  # rdb-lint: disable=event-loop-blocking (CLI --block foreground park; blocking is the point of the flag)
        except KeyboardInterrupt:
            pass
        from ray_dynamic_batching_tpu.serve.api import shutdown

        shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
