"""gRPC ingress proxy — the second front door.

Re-creates Ray Serve's ``gRPCProxy`` (``_private/proxy.py:558``): the same
route table and deployment handles as the HTTP proxy, behind gRPC. The
environment ships ``grpcio`` but no protoc codegen plugin, so the service
is registered through grpc's generic-handler API with JSON messages —
schema-light, but the full gRPC machinery (HTTP/2 transport, deadlines,
streaming, status codes) is real:

- ``/rdb.Serve/Predict``        unary-unary   {"deployment", "payload", ...}
  → {"result": ...}
- ``/rdb.Serve/PredictStream``  unary-stream  one message per streamed
  chunk, then {"result": ...} (token streaming, ref proxy.py:959)
- ``/rdb.Serve/Healthz``        unary-unary   liveness

Deployment resolution reuses :class:`ProxyRouter` with the HTTP path
convention (``/api/{deployment}``), so both proxies share one route table.
"""

from __future__ import annotations

import json
import time
from concurrent import futures as cf
from typing import Any, Iterator, Optional

from ray_dynamic_batching_tpu.engine.request import (
    BadRequest,
    DEFAULT_QOS_CLASS,
    DEFAULT_TENANT,
    StreamClosed,
    normalize_qos,
)
from ray_dynamic_batching_tpu.serve.failover import reject_disposition
from ray_dynamic_batching_tpu.serve.proxy import ProxyRouter, _to_jsonable
from ray_dynamic_batching_tpu.utils.logging import get_logger
from ray_dynamic_batching_tpu.utils import metrics as m
from ray_dynamic_batching_tpu.utils.tracing import parse_traceparent, tracer

logger = get_logger("grpc_proxy")

GRPC_REQUESTS = m.Counter(
    "rdb_grpc_requests_total", "gRPC requests",
    tag_keys=("method", "code", "shard"),
    bounded_tags={"shard": m.DEFAULT_SHARD_TOP_K},
)

try:  # grpcio is present in the image; gate anyway (env contract)
    import grpc

    HAVE_GRPC = True
except ImportError:  # pragma: no cover - exercised only without grpcio
    grpc = None
    HAVE_GRPC = False


def _identity(b: bytes) -> bytes:
    return b


class GRPCProxy:
    """gRPC server bridging the shared route table to deployment handles."""

    def __init__(
        self,
        router: ProxyRouter,
        host: str = "127.0.0.1",
        port: int = 0,
        request_timeout_s: float = 60.0,
        max_workers: int = 16,
        admission=None,
        shard_id: str = "0",
    ) -> None:
        if not HAVE_GRPC:
            raise RuntimeError("grpcio is not installed")
        # Front-door shard identity (serve/frontdoor.py): tags every gRPC
        # metric family; "0" is the unsharded default.
        self.shard_id = str(shard_id)
        # Optional serve.admission.AdmissionController — same instance
        # (and therefore the same buckets/governor state) as the HTTP
        # proxy's, so a tenant cannot dodge its budget by switching doors.
        self.admission = admission
        self.router = router
        self.host = host
        self.port = port
        self.request_timeout_s = request_timeout_s
        self._server: Optional["grpc.Server"] = None
        self._max_workers = max_workers

    def _count(self, method: str, code: str) -> None:
        GRPC_REQUESTS.inc(tags={"method": method, "code": code,
                                "shard": self.shard_id})

    # --- handlers ----------------------------------------------------------
    def _resolve(self, body: dict):
        deployment = body.get("deployment")
        if not deployment:
            return None, 'missing "deployment"'
        matched = self.router.match(f"/api/{deployment}")
        if matched is None:
            return None, f"no route for deployment {deployment!r}"
        return matched[1], None

    def _predict(self, request: bytes, context) -> bytes:
        try:
            body = json.loads(request or b"{}")
        except json.JSONDecodeError as e:
            self._count("Predict", "INVALID")
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"bad JSON: {e}")
        handle, err = self._resolve(body)
        if handle is None:
            self._count("Predict", "NOT_FOUND")
            context.abort(grpc.StatusCode.NOT_FOUND, err)
        tenant, qos = self._identity(body, context, "Predict")
        # Ingest span for the gRPC front door; a ``traceparent`` field in
        # the JSON body (the generic-handler transport has no per-call
        # metadata plumbing here) joins the caller's trace. Admission AND
        # dispatch happen inside the span: the admission.check child must
        # nest under the request trace (an orphan-trace hop never shows
        # in the request's budget ledger), and the routed request
        # inherits the context; the result wait is accounted by the
        # proxy-side future timeout.
        with tracer().attach_context(
            parse_traceparent(body.get("traceparent")),
            "grpc.predict",
            lane="grpc", deployment=body.get("deployment"),
        ):
            self._admit(body.get("deployment"), tenant,
                        self._effective_qos(handle, qos), context,
                        "Predict")
            future = handle.remote(
                body.get("payload"),
                slo_ms=body.get("slo_ms"),
                multiplexed_model_id=body.get("multiplexed_model_id"),
                tenant=tenant,
                qos_class=qos,
            )
        timeout = self._budget(context)
        try:
            result = future.result(timeout=timeout)
        except TimeoutError:
            self._count("Predict", "DEADLINE")
            context.abort(
                grpc.StatusCode.DEADLINE_EXCEEDED, "request timed out"
            )
        except Exception as e:  # noqa: BLE001 — status mapping below
            code, status = self._error_status(e)
            self._count("Predict", code)
            context.abort(status, str(e))
        self._count("Predict", "OK")
        return json.dumps({"result": _to_jsonable(result)}).encode()

    def _identity(self, body: dict, context, method: str):
        """(tenant, declared qos_class or None) from the request body —
        top-level fields win, then fields embedded in the payload dict
        (the handle reads those too, so the admitter must grade the SAME
        identity the request will serve at). An unknown class is the
        client's fault (INVALID_ARGUMENT), validated HERE so it cannot
        escape handle.remote as an unhandled servicer error. None means
        "undeclared" — the handle's per-deployment default applies."""
        payload = body.get("payload")
        nested = payload if isinstance(payload, dict) else {}
        tenant = (body.get("tenant") or nested.get("tenant")
                  or DEFAULT_TENANT)
        declared = body.get("qos_class") or nested.get("qos_class")
        if not declared:
            return tenant, None
        try:
            return tenant, normalize_qos(declared)
        except BadRequest as e:
            self._count(method, "INVALID_ARGUMENT")
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))

    @staticmethod
    def _effective_qos(handle, qos):
        """The class admission grades when none was declared: the
        handle's deployment default (what the Request will serve at)."""
        if qos is not None:
            return qos
        return getattr(handle, "default_qos_class", DEFAULT_QOS_CLASS)

    def _admit(self, deployment: str, tenant: str, qos: str,
               context, method: str) -> None:
        """Token-bucket admission BEFORE routing: a reject costs the
        client this RPC and a computed retry hint (trailing metadata
        ``retry-after-s``), not a queue slot."""
        if self.admission is None:
            return
        # Same ledger hop as the HTTP door (admission.check).
        with tracer().span("admission.check", lane="grpc",
                           tenant=tenant, qos_class=qos):
            ok, retry_after_s = self.admission.admit(deployment, tenant, qos)
        if ok:
            return
        self._count(method, "RESOURCE_EXHAUSTED")
        context.set_trailing_metadata(
            (("retry-after-s", f"{retry_after_s:.3f}"),)
        )
        context.abort(
            grpc.StatusCode.RESOURCE_EXHAUSTED,
            f"admission rate exceeded (tenant {tenant!r}, class {qos!r}); "
            f"retry after {retry_after_s:.3f}s",
        )

    @staticmethod
    def _error_status(e: Exception):
        """Status mapping from the ONE shared table
        (``serve/failover.reject_disposition``, also the HTTP proxy's):
        capacity sheds (admission rejects, queue-full drops, stale
        discards) are RESOURCE_EXHAUSTED, retryable system failures and
        exhausted failover budgets are UNAVAILABLE, user errors
        INVALID_ARGUMENT, genuine bugs INTERNAL."""
        disp = reject_disposition(e)
        return disp.grpc_code, getattr(grpc.StatusCode, disp.grpc_code)

    def _budget(self, context) -> float:
        """Remaining time budget: client deadline capped by the server
        timeout (an already-expired deadline is a tiny positive budget, NOT
        'no deadline' — time_remaining() == 0.0 is falsy)."""
        tr = context.time_remaining()
        if tr is None:
            return self.request_timeout_s
        return min(self.request_timeout_s, max(0.001, tr))

    def _predict_stream(
        self, request: bytes, context
    ) -> Iterator[bytes]:
        try:
            body = json.loads(request or b"{}")
        except json.JSONDecodeError as e:
            self._count("PredictStream", "INVALID")
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, f"bad JSON: {e}")
        handle, err = self._resolve(body)
        if handle is None:
            self._count("PredictStream", "NOT_FOUND")
            context.abort(grpc.StatusCode.NOT_FOUND, err)
        tenant, qos = self._identity(body, context, "PredictStream")
        # Admission inside the request span, same as Predict: the
        # admission.check hop must join this trace to be budgetable.
        with tracer().attach_context(
            parse_traceparent(body.get("traceparent")),
            "grpc.predict_stream",
            lane="grpc", deployment=body.get("deployment"),
        ):
            self._admit(body.get("deployment"), tenant,
                        self._effective_qos(handle, qos), context,
                        "PredictStream")
            stream, future = handle.remote_stream(
                body.get("payload"), slo_ms=body.get("slo_ms"),
                tenant=tenant, qos_class=qos,
            )
        # One budget covers the WHOLE stream (chunks + trailer), so a
        # stalled replica can't pin a worker thread for 2x the timeout.
        deadline = time.monotonic() + self._budget(context)

        def remaining() -> float:
            return deadline - time.monotonic()

        error: Optional[Exception] = None
        while True:
            try:
                chunk = stream.get(timeout_s=max(0.001, remaining()))
            except StreamClosed:
                break
            except Exception as e:  # noqa: BLE001 — status carries it below
                error = e
                break
            yield json.dumps({"chunk": _to_jsonable(chunk)}).encode()
        if error is None:
            try:
                result = future.result(timeout=max(0.001, remaining()))
                yield json.dumps({"result": _to_jsonable(result)}).encode()
                self._count("PredictStream", "OK")
                return
            except Exception as e:  # noqa: BLE001
                error = e
        # Replica/timeout errors terminate the RPC with a real gRPC status
        # (same mapping as Predict), not an OK stream with an error body.
        if isinstance(error, TimeoutError):
            self._count("PredictStream", "DEADLINE")
            context.abort(
                grpc.StatusCode.DEADLINE_EXCEEDED, "stream timed out"
            )
        code, status = self._error_status(error)
        self._count("PredictStream", code)
        context.abort(status, str(error))

    def _healthz(self, request: bytes, context) -> bytes:
        return json.dumps({"status": "ok"}).encode()

    # --- lifecycle ---------------------------------------------------------
    def start(self) -> "GRPCProxy":
        if self.admission is None:
            # Default to the module controller's admission table (the
            # same instance the HTTP proxy grades against) so a tenant
            # cannot dodge its budget by switching doors; pass
            # ``admission=`` explicitly to bind a different controller.
            from ray_dynamic_batching_tpu.serve import api as _api

            ctl = getattr(_api, "_controller", None)
            if ctl is not None:
                self.admission = ctl.admission
        rpcs = {
            "Predict": grpc.unary_unary_rpc_method_handler(
                self._predict,
                request_deserializer=_identity,
                response_serializer=_identity,
            ),
            "PredictStream": grpc.unary_stream_rpc_method_handler(
                self._predict_stream,
                request_deserializer=_identity,
                response_serializer=_identity,
            ),
            "Healthz": grpc.unary_unary_rpc_method_handler(
                self._healthz,
                request_deserializer=_identity,
                response_serializer=_identity,
            ),
        }
        self._server = grpc.server(
            cf.ThreadPoolExecutor(
                max_workers=self._max_workers,
                thread_name_prefix="grpc-proxy",
            )
        )
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler("rdb.Serve", rpcs),)
        )
        self.port = self._server.add_insecure_port(f"{self.host}:{self.port}")
        if self.port == 0:
            raise RuntimeError("grpc proxy failed to bind")
        self._server.start()
        logger.info("grpc proxy listening on %s:%d", self.host, self.port)
        return self

    def stop(self, grace_s: float = 1.0) -> None:
        if self._server is not None:
            self._server.stop(grace_s).wait(grace_s + 1)
            self._server = None


class GRPCIngressClient:
    """Minimal client for the generic service (tests, load generators)."""

    def __init__(self, host: str, port: int):
        if not HAVE_GRPC:
            raise RuntimeError("grpcio is not installed")
        self.channel = grpc.insecure_channel(f"{host}:{port}")
        self._predict = self.channel.unary_unary(
            "/rdb.Serve/Predict",
            request_serializer=_identity,
            response_deserializer=_identity,
        )
        self._predict_stream = self.channel.unary_stream(
            "/rdb.Serve/PredictStream",
            request_serializer=_identity,
            response_deserializer=_identity,
        )
        self._healthz = self.channel.unary_unary(
            "/rdb.Serve/Healthz",
            request_serializer=_identity,
            response_deserializer=_identity,
        )

    def predict(self, deployment: str, payload: Any,
                timeout_s: float = 30.0, **opts) -> Any:
        body = json.dumps(
            {"deployment": deployment, "payload": payload, **opts}
        ).encode()
        resp = self._predict(body, timeout=timeout_s)
        return json.loads(resp)["result"]

    def predict_stream(self, deployment: str, payload: Any,
                       timeout_s: float = 30.0) -> Iterator[dict]:
        body = json.dumps(
            {"deployment": deployment, "payload": payload}
        ).encode()
        for msg in self._predict_stream(body, timeout=timeout_s):
            yield json.loads(msg)

    def healthz(self, timeout_s: float = 5.0) -> dict:
        return json.loads(self._healthz(b"{}", timeout=timeout_s))

    def close(self) -> None:
        self.channel.close()
