"""Replicated controller store — the control plane's GCS move.

Re-creates the role Ray's GCS plays for Serve's controller
(``gcs_server`` owning actor/placement/KV state, Serve checkpointing
through it so a controller restart is a recovery, not an outage): every
piece of ``ServeController`` mutable state lives behind a small
versioned key-value store written through TRANSACTIONS, with two
implementations:

- :class:`InMemoryStore` — single-process, the default; transactions
  are atomic batches against a dict (the reference's
  ``in_memory_store_client``).
- :class:`ReplicatedStore` — the same surface over a shared append-only
  :class:`StoreLog` plus a :class:`LeaderLease`. Every transaction
  commits as one log record stamped with the writer's **epoch**; a
  standby replays the log to reconstruct the leader's exact state and
  takes over by acquiring the lease, which BUMPS the epoch and fences
  the log — the old leader's next commit raises
  :class:`StaleEpochError` instead of corrupting state it no longer
  owns (the classic GCS/raft fencing rule: a deposed leader must fail
  loudly, never write quietly).

Why epoch fencing and not just a lock: the failure mode is a leader
that is *slow*, not dead — it wakes up after the standby took over and
tries to finish a half-done reconcile. A lock it still believes it
holds cannot stop it; a monotone epoch checked at the single append
point can, atomically, for every key at once.

The transaction API is deliberately tiny (``get``/``put``/``delete``
staged, committed atomically on context exit, no-op writes elided so a
steady-state control loop appends nothing) because the lint rule
``store-discipline`` (tools/lint/store.py) holds the controller to it:
any bare attribute write to controller-owned state outside a
``with store.txn() as t:`` block is a finding. The discipline is what
keeps "replicated store" from rotting back into "a dict plus hope".

Partition defense (ISSUE 12):

- **One clock.** ``StoreLog`` record stamps, ``LeaderLease`` expiry, and
  the control fabric all read ONE injected clock (live default:
  ``time.monotonic``; sim: the virtual clock). The lease judges expiry
  on ITS OWN clock — the grantor's — so a renewer with a skewed clock
  can never extend real leadership beyond ``duration_s`` of grantor
  time.
- **The fabric seam.** Every cross-component exchange — append, read,
  fence, snapshot, lease acquire/renew — routes through a
  :class:`~ray_dynamic_batching_tpu.serve.fabric.ControlFabric`
  (``fabric-discipline`` lint rule), so a partition or chaos policy
  applies to the store exactly like to gossip.
- **Split-brain self-demotion.** Lease and log are ONE failure domain:
  a leader whose appends fail REACHABILITY (not just epoch) for a
  bounded window self-demotes (``store_unreachable`` audit) and stops
  renewing, instead of serving stale state until the fence finally
  catches it. On heal, the same owner may re-acquire (same epoch, no
  fence) if nobody took over meanwhile.
- **Snapshots + log compaction.** The leader takes an epoch-consistent
  :class:`StoreSnapshot` at the commit point every ``snapshot_every``
  records and truncates the log behind it; standby recovery is
  snapshot + tail replay, so failover time is O(tail), not O(uptime).
  ``read_from`` of a compacted index raises :class:`CompactedLogError`
  loudly — never a silent gap.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_dynamic_batching_tpu.serve.fabric import (
    ControlFabric,
    FabricUnreachable,
    default_fabric,
)
from ray_dynamic_batching_tpu.utils.concurrency import OrderedLock
from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("store")


class StaleEpochError(RuntimeError):
    """A write carried an epoch older than the log's fence: the writer
    was deposed (its lease expired and a standby acquired leadership).
    The only correct reaction is to stop acting as leader — retrying
    would re-submit a decision the new leader may have already
    contradicted."""

    def __init__(self, message: str, epoch: int, fence: int) -> None:
        super().__init__(message)
        self.epoch = epoch
        self.fence = fence


class CompactedLogError(RuntimeError):
    """A read asked for records the log already truncated behind a
    snapshot. Failing LOUDLY here is the contract: silently returning
    the surviving suffix would hand a standby a state with an invisible
    gap — the most dangerous kind of divergence. The reader must
    restore the latest snapshot, then re-read from its index."""

    def __init__(self, message: str, index: int, first_index: int,
                 snapshot_index: int) -> None:
        super().__init__(message)
        self.index = index
        self.first_index = first_index
        self.snapshot_index = snapshot_index


@dataclass
class LogRecord:
    """One committed transaction: the unit of replication."""

    index: int                  # position in the log, 0-based, dense
    epoch: int                  # writer's leadership epoch
    ops: List[Tuple[str, str, Optional[str]]]  # ("put", k, v) | ("delete", k, None)
    wall_time: float = 0.0      # control-plane clock stamp (shared clock)

    def to_dict(self) -> Dict[str, Any]:
        return {"index": self.index, "epoch": self.epoch,
                "ops": [list(op) for op in self.ops],
                "wall_time": self.wall_time}


@dataclass(frozen=True)
class StoreSnapshot:
    """An epoch-consistent image of the store at a commit point.

    ``index`` is the NEXT log index after the last transaction the
    snapshot includes (== the taker's applied_index at the commit
    point); replaying the log from ``index`` on top of ``data`` is
    exactly equivalent to replaying the whole log — even when the tail
    carries a LATER epoch's records (a takeover between snapshot and
    restore): restore sets the reader's cursor to ``index``, so the
    newer-epoch tail replays exactly once, never double-applies."""

    index: int
    epoch: int
    version: int                 # committed-txn watermark at the point
    data: Dict[str, str]


class StoreLog:
    """Shared append-only replication substrate with an epoch fence.

    The log is the ONE serialization point between a live leader and a
    recovering standby: ``append`` atomically checks the writer's epoch
    against the fence and either commits or raises
    :class:`StaleEpochError`. ``fence_to`` only ever raises the fence
    (monotone), so a deposed leader can never re-open its own window.

    Compaction: :meth:`install_snapshot` records the latest snapshot and
    truncates every record below its index — and ONLY below it, so a
    suffix the snapshot does not cover can never be orphaned. ``clock``
    is the shared control-plane clock (the same instance the lease and
    the fabric read)."""

    def __init__(self, clock: Callable[[], float] = time.monotonic) -> None:
        self._records: List[LogRecord] = []
        self._first_index = 0          # index of _records[0] (post-compaction)
        self._snapshot: Optional[StoreSnapshot] = None
        self._fence_epoch = 0
        self._lock = OrderedLock("store_log")
        self._clock = clock
        self.rejected_appends = 0
        self.appended_total = 0        # survives compaction (uptime proxy)

    @property
    def fence_epoch(self) -> int:
        with self._lock:
            return self._fence_epoch

    @property
    def first_index(self) -> int:
        with self._lock:
            return self._first_index

    def __len__(self) -> int:
        """Records currently RETAINED (the replayable tail)."""
        with self._lock:
            return len(self._records)

    def next_index(self) -> int:
        with self._lock:
            return self._first_index + len(self._records)

    def fence_to(self, epoch: int) -> None:
        """Raise the fence (monotone): appends below ``epoch`` now fail."""
        with self._lock:
            self._fence_epoch = max(self._fence_epoch, int(epoch))

    def append(self, epoch: int,
               ops: List[Tuple[str, str, Optional[str]]]) -> int:
        """Commit one transaction's ops at ``epoch``; returns the new
        record's index. Stale epochs are REJECTED atomically under the
        same lock that orders commits — there is no window where a
        deposed leader's record lands between the fence check and the
        append."""
        with self._lock:
            if epoch < self._fence_epoch:
                self.rejected_appends += 1
                raise StaleEpochError(
                    f"append at epoch {epoch} rejected: log fenced at "
                    f"epoch {self._fence_epoch} (a standby took over; "
                    "this writer was deposed)",
                    epoch=epoch, fence=self._fence_epoch,
                )
            rec = LogRecord(
                index=self._first_index + len(self._records), epoch=epoch,
                ops=list(ops), wall_time=self._clock(),
            )
            self._records.append(rec)
            self.appended_total += 1
            return rec.index

    def read_from(self, index: int) -> List[LogRecord]:
        """Records at ``index`` and after. Asking below the compaction
        horizon raises :class:`CompactedLogError` — restore the latest
        snapshot and re-read from its index instead."""
        with self._lock:
            if index < self._first_index:
                raise CompactedLogError(
                    f"read_from({index}) below the compaction horizon "
                    f"(first retained index {self._first_index}): the "
                    "records were truncated behind a snapshot — restore "
                    "it, then replay the tail",
                    index=index, first_index=self._first_index,
                    snapshot_index=(self._snapshot.index
                                    if self._snapshot is not None else -1),
                )
            return list(self._records[index - self._first_index:])

    # --- snapshot + compaction --------------------------------------------
    def install_snapshot(self, snap: StoreSnapshot) -> None:
        """Record ``snap`` as the latest snapshot and truncate the log
        strictly BEHIND it. A snapshot claiming records that were never
        committed (index beyond the log head) or regressing behind the
        current horizon is rejected — truncation can never orphan an
        un-snapshotted suffix because only this method truncates, and
        only up to an index the snapshot provably covers."""
        with self._lock:
            head = self._first_index + len(self._records)
            if snap.index > head:
                raise ValueError(
                    f"snapshot at index {snap.index} claims records the "
                    f"log never committed (head {head}) — refusing to "
                    "truncate an un-snapshotted suffix"
                )
            if snap.index < self._first_index:
                raise ValueError(
                    f"snapshot at index {snap.index} regresses behind the "
                    f"compaction horizon ({self._first_index})"
                )
            self._snapshot = snap
            self._records = self._records[snap.index - self._first_index:]
            self._first_index = snap.index

    def latest_snapshot(self) -> Optional[StoreSnapshot]:
        with self._lock:
            return self._snapshot


class LeaderLease:
    """Time-bounded leadership with a monotone epoch.

    ``acquire(owner)`` succeeds when the lease is free, expired, or
    already held by ``owner``; a NEW holder bumps the epoch. ``renew``
    extends the current holder's window. The clock is injected so the
    simulator drives lease expiry on virtual time and the failover test
    can expire a lease deterministically instead of sleeping.

    Clock-skew contract: expiry is judged on THIS lease's injected
    clock — the grantor's — at the moment of each call. ``renew`` takes
    no timestamp from the renewer, so a renewer whose own clock runs
    fast or slow can never stretch real leadership beyond ``duration_s``
    of grantor time per renewal (pinned by the skew test)."""

    def __init__(self, duration_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.duration_s = float(duration_s)
        self.clock = clock
        self._clock = clock  # internal alias (one source, read everywhere)
        self._lock = OrderedLock("lease")
        self._holder: Optional[str] = None
        self._epoch = 0
        self._expires_at = 0.0

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def holder(self) -> Optional[str]:
        with self._lock:
            if self._holder is not None and self._clock() >= self._expires_at:
                return None  # expired: readable as vacant
            return self._holder

    def expired(self) -> bool:
        with self._lock:
            return self._holder is None or self._clock() >= self._expires_at

    def acquire(self, owner: str) -> Optional[int]:
        """Try to take (or keep) the lease; returns the epoch on success
        (bumped for a NEW holder), None while another holder's lease is
        live. Acquisition by a new holder is the takeover edge."""
        with self._lock:
            now = self._clock()
            if (self._holder is not None and self._holder != owner
                    and now < self._expires_at):
                return None
            if self._holder != owner:
                self._epoch += 1
            self._holder = owner
            self._expires_at = now + self.duration_s
            return self._epoch

    def renew(self, owner: str) -> bool:
        """Extend the holder's window; False when ``owner`` no longer
        holds the lease (it must stop acting as leader)."""
        with self._lock:
            if self._holder != owner or self._clock() >= self._expires_at:
                return False
            self._expires_at = self._clock() + self.duration_s
            return True

    def revoke(self) -> None:
        """Administratively vacate (the chaos harness's controller-kill:
        a crashed leader stops renewing; revoke models the expiry
        without waiting out the wall clock)."""
        with self._lock:
            self._expires_at = 0.0


class _Txn:
    """Staged write set committed atomically on context exit.

    Reads see staged writes (read-your-writes inside the txn); no-op
    puts (value unchanged vs the committed state) are ELIDED so a
    control loop that re-derives the same state every tick appends
    nothing to the log. An exception inside the block discards the
    stage — half a reconcile never commits."""

    def __init__(self, store: "ControllerStore") -> None:
        self._store = store
        self._stage: Dict[str, Optional[str]] = {}  # None = delete

    def get(self, key: str) -> Optional[str]:
        if key in self._stage:
            return self._stage[key]
        return self._store.get(key)

    def put(self, key: str, value: str) -> None:
        if not isinstance(value, str):
            raise TypeError(
                f"store values are strings (JSON); got {type(value).__name__}"
            )
        if self._store.get(key) == value:
            self._stage.pop(key, None)  # no-op write: elide
            return
        self._stage[key] = value

    def put_json(self, key: str, value: Any) -> None:
        """Canonical JSON put — sort_keys so an identical dict is a
        byte-identical (and therefore elided) write."""
        self.put(key, json.dumps(value, sort_keys=True))

    def delete(self, key: str) -> None:
        if self._store.get(key) is not None:
            self._stage[key] = None

    def ops(self) -> List[Tuple[str, str, Optional[str]]]:
        return [
            ("delete", k, None) if v is None else ("put", k, v)
            for k, v in sorted(self._stage.items())
        ]

    def __enter__(self) -> "_Txn":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is None and self._stage:
            self._store._commit(self.ops())
        return False


class ControllerStore:
    """Versioned KV surface the controller writes through transactions.

    ``version`` counts committed transactions — a cheap "did anything
    change" watermark for observers (status/dashboard)."""

    def __init__(self) -> None:
        self._data: Dict[str, str] = {}
        self._lock = OrderedLock("store")
        self._version = 0

    # --- read side --------------------------------------------------------
    def get(self, key: str) -> Optional[str]:
        with self._lock:
            return self._data.get(key)

    def get_json(self, key: str) -> Optional[Any]:
        raw = self.get(key)
        return None if raw is None else json.loads(raw)

    def keys(self, prefix: str = "") -> List[str]:
        with self._lock:
            return sorted(k for k in self._data if k.startswith(prefix))

    def snapshot(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._data)

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    # --- write side -------------------------------------------------------
    def txn(self) -> _Txn:
        """The ONLY write path (store-discipline contract)."""
        return _Txn(self)

    def _apply(self, ops: List[Tuple[str, str, Optional[str]]]) -> None:
        with self._lock:
            for kind, key, value in ops:
                if kind == "put":
                    self._data[key] = value  # type: ignore[assignment]
                elif kind == "delete":
                    self._data.pop(key, None)
                else:
                    raise ValueError(f"unknown store op kind {kind!r}")
            self._version += 1

    def _commit(self, ops: List[Tuple[str, str, Optional[str]]]) -> None:
        self._apply(ops)


class InMemoryStore(ControllerStore):
    """Single-process store: transactions apply atomically, no log."""


@dataclass
class _ReplicaState:
    applied_index: int = 0
    epoch: int = 0
    is_leader: bool = False


class ReplicatedStore(ControllerStore):
    """Log-replicated store with leader lease + epoch fencing.

    Many instances may share one :class:`StoreLog`/:class:`LeaderLease`
    pair (live: one per would-be controller; sim: leaders and standbys
    on the virtual clock). Exactly one is leader at a time; only the
    leader's transactions commit. A standby calls :meth:`catch_up` to
    replay new records and :meth:`acquire_leadership` to take over when
    the lease lapses.

    Partition defense: every log/lease exchange routes through
    ``fabric`` (the message seam), and lease + log are treated as ONE
    failure domain — a leader whose appends are UNREACHABLE for
    ``unreachable_demote_after_s`` self-demotes (audited
    ``store_unreachable``) and stops renewing, so the standby on the
    log's side of the partition takes over within one lease window
    instead of the old leader serving stale state until fenced.
    ``snapshot_every > 0`` arms log compaction: an epoch-consistent
    snapshot at the commit point every N records, recovery = snapshot +
    tail replay (O(tail), not O(uptime))."""

    def __init__(self, log: StoreLog, lease: LeaderLease, owner: str,
                 fabric: Optional[ControlFabric] = None,
                 clock: Optional[Callable[[], float]] = None,
                 snapshot_every: int = 0,
                 unreachable_demote_after_s: Optional[float] = None) -> None:
        super().__init__()
        self.log = log
        self.lease = lease
        self.owner = owner
        self.fabric = fabric if fabric is not None else default_fabric()
        # ONE control-plane clock: default to the lease's (the grantor's)
        # so log stamps, lease expiry, and the demotion window agree.
        self._clock = clock if clock is not None else lease.clock
        self.snapshot_every = int(snapshot_every)
        # Demote well inside one lease window: the standby must find the
        # lease lapsed at most one duration after the leader went blind.
        self.unreachable_demote_after_s = (
            float(unreachable_demote_after_s)
            if unreachable_demote_after_s is not None
            else lease.duration_s / 2.0
        )
        self._unreachable_since: Optional[float] = None
        self._repl = _ReplicaState()
        self.self_demotions = 0
        self.snapshots_taken = 0
        # How the last catch_up reconstructed state (the failover-time
        # ratchet reads this): records replayed, and whether a snapshot
        # seeded the replay.
        self.last_recovery: Dict[str, int] = {
            "snapshot_index": -1, "tail_replayed": 0,
        }
        # Worst single replay any catch_up ever did: the O(tail) ratchet
        # pins this against snapshot_every — with compaction armed it
        # stays bounded no matter how long the log's total history is.
        self.max_tail_replayed = 0
        # Optional structured audit ring (scheduler/audit.py); the
        # controller shares its own so store_unreachable lands next to
        # heals and fences.
        self.audit: Optional[Any] = None

    # --- leadership -------------------------------------------------------
    @property
    def epoch(self) -> int:
        return self._repl.epoch

    def is_leader(self) -> bool:
        return self._repl.is_leader and self.lease.holder() == self.owner

    def acquire_leadership(self) -> Optional[int]:
        """Take the lease (if free/expired), replay the whole log, and
        fence out the previous epoch. Returns the new epoch, or None
        while another leader's lease is live; raises
        :class:`FabricUnreachable` when the log cannot be reached —
        leadership is NOT assumed on a partial acquire (a lease without
        a replayed, fenced log is exactly the split-brain this layer
        exists to prevent). Replay BEFORE fencing would race the old
        leader's final commits; fencing first means everything replayed
        is everything that will ever exist below this epoch."""
        # Probe the log BEFORE touching the lease: same-holder acquire
        # EXTENDS the lease window, so a self-demoted leader that is
        # partitioned from the log but not the lease would otherwise
        # keep re-extending its own lease on every retry and lock the
        # reachable standby out forever — the quiet split-brain this
        # whole layer exists to prevent. No log, no candidacy.
        self.catch_up()  # raises FabricUnreachable when the log is cut off
        epoch = self.fabric.call(
            "lease.acquire", self.lease.acquire, self.owner,
            src=self.owner, dst="lease",
        )
        if epoch is None:
            return None
        self.fabric.call("store.fence", self.log.fence_to, epoch,
                         src=self.owner, dst="log")
        self.catch_up()
        self._unreachable_since = None
        self._repl.epoch = epoch
        self._repl.is_leader = True
        logger.info("%s: leadership acquired at epoch %d (log index %d)",
                    self.owner, epoch, self._repl.applied_index)
        return epoch

    def renew(self) -> bool:
        """Heartbeat; False demotes this instance (stop leading). A
        self-demoted instance (appends unreachable) returns False
        WITHOUT renewing: deliberately letting the lease lapse is what
        hands leadership to a standby that can still reach the log —
        renewing a lease you cannot write under IS the split-brain.

        Lease and log are ONE failure domain: a successful lease renew
        also PROBES the log — a tail read carried on the ``store.append``
        edge, the same channel commits use — so the bounded
        self-demotion window runs even while the control loop is
        quiescent (elided steady-state transactions append nothing, and
        without the probe an idle leader would happily renew through a
        partition it could never write across)."""
        if not self._repl.is_leader:
            return False
        try:
            ok = self.fabric.call("lease.renew", self.lease.renew,
                                  self.owner, src=self.owner, dst="lease")
        except FabricUnreachable:
            ok = False
        if not ok and self._repl.is_leader:
            self._repl.is_leader = False
            logger.warning("%s: lease lost (epoch %d); demoted",
                           self.owner, self._repl.epoch)
            return False
        if ok:
            try:
                # The probe rides the APPEND edge (it is a heartbeat-
                # append in spirit): a fault that eats only appends must
                # open — and keep open — the same unreachability window
                # real commits do. Probing a different edge would let a
                # healthy read channel mask a dead write channel and
                # the leader would renew forever over a log it can
                # never write to.
                self.fabric.call(
                    "store.append", self.log.read_from,
                    self._repl.applied_index, src=self.owner, dst="log",
                )
                self._unreachable_since = None
            except FabricUnreachable:
                self._note_unreachable()  # may self-demote (bounded)
                return self._repl.is_leader
        return ok

    def catch_up(self) -> int:
        """Apply records this instance has not seen; returns how many.
        Standbys call this on their watch loop; a fresh leader calls it
        inside :meth:`acquire_leadership`. When the cursor has fallen
        behind the compaction horizon, restore the latest snapshot and
        replay only the tail — the O(tail) failover path. The snapshot
        may be an OLDER epoch's than the tail (takeover raced the
        snapshot): restore moves the cursor to the snapshot index, so
        the newer-epoch tail applies exactly once."""
        restored_index = -1
        while True:
            try:
                new = self.fabric.call(
                    "store.read", self.log.read_from,
                    self._repl.applied_index, src=self.owner, dst="log",
                )
                break
            except CompactedLogError:
                # The leader may compact AGAIN between our restore and
                # the tail read (it keeps committing while we recover);
                # each retry restores a strictly newer snapshot — the
                # cursor only moves forward — so the loop terminates.
                snap = self.fabric.call(
                    "store.snapshot", self.log.latest_snapshot,
                    src=self.owner, dst="log",
                )
                if snap is None or snap.index <= self._repl.applied_index:
                    # Compacted with no (or a non-advancing) snapshot:
                    # impossible by install_snapshot's construction —
                    # fail loud rather than spin.
                    raise
                self._restore(snap)
                restored_index = snap.index
        for rec in new:
            self._apply(rec.ops)
            self._repl.applied_index = rec.index + 1
        if restored_index >= 0 or new:
            # A no-op poll leaves the stats alone so the LAST real
            # recovery (the failover's snapshot + tail replay — what the
            # O(tail) ratchet grades) stays readable.
            self.last_recovery = {"snapshot_index": restored_index,
                                  "tail_replayed": len(new)}
            self.max_tail_replayed = max(self.max_tail_replayed, len(new))
        return len(new)

    def _restore(self, snap: StoreSnapshot) -> None:
        """Replace local state wholesale with the snapshot image and move
        the replay cursor to its index (never double-apply: everything
        below the index is IN the image, everything at/after it replays
        from the tail)."""
        with self._lock:
            self._data = dict(snap.data)
            self._version = snap.version
        self._repl.applied_index = snap.index
        logger.info("%s: restored snapshot at index %d (epoch %d)",
                    self.owner, snap.index, snap.epoch)

    # --- split-brain defense ----------------------------------------------
    def _note_unreachable(self) -> None:
        now = self._clock()
        if self._unreachable_since is None:
            self._unreachable_since = now
            return
        window = now - self._unreachable_since
        if window >= self.unreachable_demote_after_s and self._repl.is_leader:
            self._repl.is_leader = False
            self.self_demotions += 1
            logger.error(
                "%s: log unreachable for %.3fs (bound %.3fs) at epoch %d — "
                "self-demoting; the lease will lapse and a standby that can "
                "reach the log takes over",
                self.owner, window, self.unreachable_demote_after_s,
                self._repl.epoch,
            )
            if self.audit is not None:
                self.audit.record(
                    "store_unreachable",
                    observed={"owner": self.owner,
                              "epoch": self._repl.epoch,
                              "unreachable_s": round(window, 3),
                              "bound_s": self.unreachable_demote_after_s},
                    note="appends failed reachability for the bounded "
                         "window; self-demoted instead of serving stale "
                         "state until fenced",
                )

    # --- snapshots ---------------------------------------------------------
    def _maybe_snapshot(self) -> None:
        """At the commit point (just appended + applied): if the
        replayable tail outgrew ``snapshot_every``, publish an
        epoch-consistent snapshot and compact the log behind it. A
        snapshot that cannot reach the log is skipped — it is an
        optimization, never a correctness dependency."""
        if self.snapshot_every <= 0:
            return
        if self._repl.applied_index - self.log.first_index \
                < self.snapshot_every:
            return
        snap = StoreSnapshot(
            index=self._repl.applied_index,
            epoch=self._repl.epoch,
            version=self.version,
            data=self.snapshot(),
        )
        try:
            self.fabric.call("store.snapshot", self.log.install_snapshot,
                             snap, src=self.owner, dst="log")
        except FabricUnreachable:
            return
        self.snapshots_taken += 1

    # --- write side (fenced) ----------------------------------------------
    def _commit(self, ops: List[Tuple[str, str, Optional[str]]]) -> None:
        if not self._repl.is_leader:
            raise StaleEpochError(
                f"{self.owner}: commit refused — not the leader "
                f"(epoch {self._repl.epoch}, fence {self.log.fence_epoch})",
                epoch=self._repl.epoch, fence=self.log.fence_epoch,
            )
        try:
            index = self.fabric.call(
                "store.append", self.log.append, self._repl.epoch, ops,
                src=self.owner, dst="log",
            )  # raises StaleEpochError when fenced
        except FabricUnreachable:
            self._note_unreachable()
            raise
        self._unreachable_since = None
        self._apply(ops)
        self._repl.applied_index = index + 1
        self._maybe_snapshot()


class ReplicaCatalog:
    """Process-local registry of LIVE data-plane objects (replicas and
    routers) that survive a controller death.

    In the reference, replica actors and router processes outlive the
    controller; a recovering controller re-syncs with them instead of
    restarting the world. In this in-process re-creation the catalog IS
    that survival: controllers register the objects they start, a
    failover successor adopts whatever is still alive and healthy, and
    only replicas recorded in the store but missing (or dead) here get
    restarted. Clients' handles keep working through a failover because
    the ROUTER object they hold is adopted, not replaced."""

    def __init__(self) -> None:
        self._replicas: Dict[str, Any] = {}
        self._routers: Dict[str, Any] = {}
        # replica_id -> live placement group: chip reservations outlive
        # the controller exactly like the replicas holding them, so a
        # failover successor can release them when it later retires an
        # adopted replica (otherwise the chips leak forever).
        self._pgroups: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def register_replica(self, replica_id: str, replica: Any) -> None:
        with self._lock:
            self._replicas[replica_id] = replica

    def unregister_replica(self, replica_id: str) -> None:
        with self._lock:
            self._replicas.pop(replica_id, None)

    def replica(self, replica_id: str) -> Optional[Any]:
        with self._lock:
            return self._replicas.get(replica_id)

    def register_router(self, deployment: str, router: Any) -> None:
        with self._lock:
            self._routers[deployment] = router

    def unregister_router(self, deployment: str) -> None:
        """Drop a deleted deployment's router: a later redeploy must
        build fresh, never adopt the CLOSED router (whose failover and
        hedge workers are gone for good)."""
        with self._lock:
            self._routers.pop(deployment, None)

    def router(self, deployment: str) -> Optional[Any]:
        with self._lock:
            return self._routers.get(deployment)

    def register_pgroup(self, replica_id: str, pg: Any) -> None:
        with self._lock:
            self._pgroups[replica_id] = pg

    def unregister_pgroup(self, replica_id: str) -> None:
        with self._lock:
            self._pgroups.pop(replica_id, None)

    def pgroup(self, replica_id: str) -> Optional[Any]:
        with self._lock:
            return self._pgroups.get(replica_id)

    def replica_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._replicas)
