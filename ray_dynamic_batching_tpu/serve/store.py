"""Replicated controller store — the control plane's GCS move.

Re-creates the role Ray's GCS plays for Serve's controller
(``gcs_server`` owning actor/placement/KV state, Serve checkpointing
through it so a controller restart is a recovery, not an outage): every
piece of ``ServeController`` mutable state lives behind a small
versioned key-value store written through TRANSACTIONS, with two
implementations:

- :class:`InMemoryStore` — single-process, the default; transactions
  are atomic batches against a dict (the reference's
  ``in_memory_store_client``).
- :class:`ReplicatedStore` — the same surface over a shared append-only
  :class:`StoreLog` plus a :class:`LeaderLease`. Every transaction
  commits as one log record stamped with the writer's **epoch**; a
  standby replays the log to reconstruct the leader's exact state and
  takes over by acquiring the lease, which BUMPS the epoch and fences
  the log — the old leader's next commit raises
  :class:`StaleEpochError` instead of corrupting state it no longer
  owns (the classic GCS/raft fencing rule: a deposed leader must fail
  loudly, never write quietly).

Why epoch fencing and not just a lock: the failure mode is a leader
that is *slow*, not dead — it wakes up after the standby took over and
tries to finish a half-done reconcile. A lock it still believes it
holds cannot stop it; a monotone epoch checked at the single append
point can, atomically, for every key at once.

The transaction API is deliberately tiny (``get``/``put``/``delete``
staged, committed atomically on context exit, no-op writes elided so a
steady-state control loop appends nothing) because the lint rule
``store-discipline`` (tools/lint/store.py) holds the controller to it:
any bare attribute write to controller-owned state outside a
``with store.txn() as t:`` block is a finding. The discipline is what
keeps "replicated store" from rotting back into "a dict plus hope".
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("store")


class StaleEpochError(RuntimeError):
    """A write carried an epoch older than the log's fence: the writer
    was deposed (its lease expired and a standby acquired leadership).
    The only correct reaction is to stop acting as leader — retrying
    would re-submit a decision the new leader may have already
    contradicted."""

    def __init__(self, message: str, epoch: int, fence: int) -> None:
        super().__init__(message)
        self.epoch = epoch
        self.fence = fence


@dataclass
class LogRecord:
    """One committed transaction: the unit of replication."""

    index: int                  # position in the log, 0-based, dense
    epoch: int                  # writer's leadership epoch
    ops: List[Tuple[str, str, Optional[str]]]  # ("put", k, v) | ("delete", k, None)
    wall_time: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {"index": self.index, "epoch": self.epoch,
                "ops": [list(op) for op in self.ops],
                "wall_time": self.wall_time}


class StoreLog:
    """Shared append-only replication substrate with an epoch fence.

    The log is the ONE serialization point between a live leader and a
    recovering standby: ``append`` atomically checks the writer's epoch
    against the fence and either commits or raises
    :class:`StaleEpochError`. ``fence_to`` only ever raises the fence
    (monotone), so a deposed leader can never re-open its own window.
    """

    def __init__(self, now: Callable[[], float] = time.time) -> None:
        self._records: List[LogRecord] = []
        self._fence_epoch = 0
        self._lock = threading.Lock()
        self._now = now
        self.rejected_appends = 0

    @property
    def fence_epoch(self) -> int:
        with self._lock:
            return self._fence_epoch

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def fence_to(self, epoch: int) -> None:
        """Raise the fence (monotone): appends below ``epoch`` now fail."""
        with self._lock:
            self._fence_epoch = max(self._fence_epoch, int(epoch))

    def append(self, epoch: int,
               ops: List[Tuple[str, str, Optional[str]]]) -> int:
        """Commit one transaction's ops at ``epoch``; returns the new
        record's index. Stale epochs are REJECTED atomically under the
        same lock that orders commits — there is no window where a
        deposed leader's record lands between the fence check and the
        append."""
        with self._lock:
            if epoch < self._fence_epoch:
                self.rejected_appends += 1
                raise StaleEpochError(
                    f"append at epoch {epoch} rejected: log fenced at "
                    f"epoch {self._fence_epoch} (a standby took over; "
                    "this writer was deposed)",
                    epoch=epoch, fence=self._fence_epoch,
                )
            rec = LogRecord(
                index=len(self._records), epoch=epoch, ops=list(ops),
                wall_time=self._now(),
            )
            self._records.append(rec)
            return rec.index

    def read_from(self, index: int) -> List[LogRecord]:
        with self._lock:
            return list(self._records[index:])


class LeaderLease:
    """Time-bounded leadership with a monotone epoch.

    ``acquire(owner)`` succeeds when the lease is free, expired, or
    already held by ``owner``; a NEW holder bumps the epoch. ``renew``
    extends the current holder's window. The clock is injected so the
    simulator drives lease expiry on virtual time and the failover test
    can expire a lease deterministically instead of sleeping."""

    def __init__(self, duration_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.duration_s = float(duration_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._holder: Optional[str] = None
        self._epoch = 0
        self._expires_at = 0.0

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def holder(self) -> Optional[str]:
        with self._lock:
            if self._holder is not None and self._clock() >= self._expires_at:
                return None  # expired: readable as vacant
            return self._holder

    def expired(self) -> bool:
        with self._lock:
            return self._holder is None or self._clock() >= self._expires_at

    def acquire(self, owner: str) -> Optional[int]:
        """Try to take (or keep) the lease; returns the epoch on success
        (bumped for a NEW holder), None while another holder's lease is
        live. Acquisition by a new holder is the takeover edge."""
        with self._lock:
            now = self._clock()
            if (self._holder is not None and self._holder != owner
                    and now < self._expires_at):
                return None
            if self._holder != owner:
                self._epoch += 1
            self._holder = owner
            self._expires_at = now + self.duration_s
            return self._epoch

    def renew(self, owner: str) -> bool:
        """Extend the holder's window; False when ``owner`` no longer
        holds the lease (it must stop acting as leader)."""
        with self._lock:
            if self._holder != owner or self._clock() >= self._expires_at:
                return False
            self._expires_at = self._clock() + self.duration_s
            return True

    def revoke(self) -> None:
        """Administratively vacate (the chaos harness's controller-kill:
        a crashed leader stops renewing; revoke models the expiry
        without waiting out the wall clock)."""
        with self._lock:
            self._expires_at = 0.0


class _Txn:
    """Staged write set committed atomically on context exit.

    Reads see staged writes (read-your-writes inside the txn); no-op
    puts (value unchanged vs the committed state) are ELIDED so a
    control loop that re-derives the same state every tick appends
    nothing to the log. An exception inside the block discards the
    stage — half a reconcile never commits."""

    def __init__(self, store: "ControllerStore") -> None:
        self._store = store
        self._stage: Dict[str, Optional[str]] = {}  # None = delete

    def get(self, key: str) -> Optional[str]:
        if key in self._stage:
            return self._stage[key]
        return self._store.get(key)

    def put(self, key: str, value: str) -> None:
        if not isinstance(value, str):
            raise TypeError(
                f"store values are strings (JSON); got {type(value).__name__}"
            )
        if self._store.get(key) == value:
            self._stage.pop(key, None)  # no-op write: elide
            return
        self._stage[key] = value

    def put_json(self, key: str, value: Any) -> None:
        """Canonical JSON put — sort_keys so an identical dict is a
        byte-identical (and therefore elided) write."""
        self.put(key, json.dumps(value, sort_keys=True))

    def delete(self, key: str) -> None:
        if self._store.get(key) is not None:
            self._stage[key] = None

    def ops(self) -> List[Tuple[str, str, Optional[str]]]:
        return [
            ("delete", k, None) if v is None else ("put", k, v)
            for k, v in sorted(self._stage.items())
        ]

    def __enter__(self) -> "_Txn":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is None and self._stage:
            self._store._commit(self.ops())
        return False


class ControllerStore:
    """Versioned KV surface the controller writes through transactions.

    ``version`` counts committed transactions — a cheap "did anything
    change" watermark for observers (status/dashboard)."""

    def __init__(self) -> None:
        self._data: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._version = 0

    # --- read side --------------------------------------------------------
    def get(self, key: str) -> Optional[str]:
        with self._lock:
            return self._data.get(key)

    def get_json(self, key: str) -> Optional[Any]:
        raw = self.get(key)
        return None if raw is None else json.loads(raw)

    def keys(self, prefix: str = "") -> List[str]:
        with self._lock:
            return sorted(k for k in self._data if k.startswith(prefix))

    def snapshot(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._data)

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    # --- write side -------------------------------------------------------
    def txn(self) -> _Txn:
        """The ONLY write path (store-discipline contract)."""
        return _Txn(self)

    def _apply(self, ops: List[Tuple[str, str, Optional[str]]]) -> None:
        with self._lock:
            for kind, key, value in ops:
                if kind == "put":
                    self._data[key] = value  # type: ignore[assignment]
                elif kind == "delete":
                    self._data.pop(key, None)
                else:
                    raise ValueError(f"unknown store op kind {kind!r}")
            self._version += 1

    def _commit(self, ops: List[Tuple[str, str, Optional[str]]]) -> None:
        self._apply(ops)


class InMemoryStore(ControllerStore):
    """Single-process store: transactions apply atomically, no log."""


@dataclass
class _ReplicaState:
    applied_index: int = 0
    epoch: int = 0
    is_leader: bool = False


class ReplicatedStore(ControllerStore):
    """Log-replicated store with leader lease + epoch fencing.

    Many instances may share one :class:`StoreLog`/:class:`LeaderLease`
    pair (live: one per would-be controller; sim: leaders and standbys
    on the virtual clock). Exactly one is leader at a time; only the
    leader's transactions commit. A standby calls :meth:`catch_up` to
    replay new records and :meth:`acquire_leadership` to take over when
    the lease lapses.
    """

    def __init__(self, log: StoreLog, lease: LeaderLease, owner: str) -> None:
        super().__init__()
        self.log = log
        self.lease = lease
        self.owner = owner
        self._repl = _ReplicaState()

    # --- leadership -------------------------------------------------------
    @property
    def epoch(self) -> int:
        return self._repl.epoch

    def is_leader(self) -> bool:
        return self._repl.is_leader and self.lease.holder() == self.owner

    def acquire_leadership(self) -> Optional[int]:
        """Take the lease (if free/expired), replay the whole log, and
        fence out the previous epoch. Returns the new epoch, or None
        while another leader's lease is live. Replay BEFORE fencing
        would race the old leader's final commits; fencing first means
        everything replayed is everything that will ever exist below
        this epoch."""
        epoch = self.lease.acquire(self.owner)
        if epoch is None:
            return None
        self.log.fence_to(epoch)
        self.catch_up()
        self._repl.epoch = epoch
        self._repl.is_leader = True
        logger.info("%s: leadership acquired at epoch %d (log index %d)",
                    self.owner, epoch, self._repl.applied_index)
        return epoch

    def renew(self) -> bool:
        """Heartbeat; False demotes this instance (stop leading)."""
        ok = self.lease.renew(self.owner)
        if not ok and self._repl.is_leader:
            self._repl.is_leader = False
            logger.warning("%s: lease lost (epoch %d); demoted",
                           self.owner, self._repl.epoch)
        return ok

    def catch_up(self) -> int:
        """Apply records this instance has not seen; returns how many.
        Standbys call this on their watch loop; a fresh leader calls it
        inside :meth:`acquire_leadership`."""
        new = self.log.read_from(self._repl.applied_index)
        for rec in new:
            self._apply(rec.ops)
            self._repl.applied_index = rec.index + 1
        return len(new)

    # --- write side (fenced) ----------------------------------------------
    def _commit(self, ops: List[Tuple[str, str, Optional[str]]]) -> None:
        if not self._repl.is_leader:
            raise StaleEpochError(
                f"{self.owner}: commit refused — not the leader "
                f"(epoch {self._repl.epoch}, fence {self.log.fence_epoch})",
                epoch=self._repl.epoch, fence=self.log.fence_epoch,
            )
        index = self.log.append(self._repl.epoch, ops)  # raises when fenced
        self._apply(ops)
        self._repl.applied_index = index + 1


class ReplicaCatalog:
    """Process-local registry of LIVE data-plane objects (replicas and
    routers) that survive a controller death.

    In the reference, replica actors and router processes outlive the
    controller; a recovering controller re-syncs with them instead of
    restarting the world. In this in-process re-creation the catalog IS
    that survival: controllers register the objects they start, a
    failover successor adopts whatever is still alive and healthy, and
    only replicas recorded in the store but missing (or dead) here get
    restarted. Clients' handles keep working through a failover because
    the ROUTER object they hold is adopted, not replaced."""

    def __init__(self) -> None:
        self._replicas: Dict[str, Any] = {}
        self._routers: Dict[str, Any] = {}
        # replica_id -> live placement group: chip reservations outlive
        # the controller exactly like the replicas holding them, so a
        # failover successor can release them when it later retires an
        # adopted replica (otherwise the chips leak forever).
        self._pgroups: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def register_replica(self, replica_id: str, replica: Any) -> None:
        with self._lock:
            self._replicas[replica_id] = replica

    def unregister_replica(self, replica_id: str) -> None:
        with self._lock:
            self._replicas.pop(replica_id, None)

    def replica(self, replica_id: str) -> Optional[Any]:
        with self._lock:
            return self._replicas.get(replica_id)

    def register_router(self, deployment: str, router: Any) -> None:
        with self._lock:
            self._routers[deployment] = router

    def unregister_router(self, deployment: str) -> None:
        """Drop a deleted deployment's router: a later redeploy must
        build fresh, never adopt the CLOSED router (whose failover and
        hedge workers are gone for good)."""
        with self._lock:
            self._routers.pop(deployment, None)

    def router(self, deployment: str) -> Optional[Any]:
        with self._lock:
            return self._routers.get(deployment)

    def register_pgroup(self, replica_id: str, pg: Any) -> None:
        with self._lock:
            self._pgroups[replica_id] = pg

    def unregister_pgroup(self, replica_id: str) -> None:
        with self._lock:
            self._pgroups.pop(replica_id, None)

    def pgroup(self, replica_id: str) -> Optional[Any]:
        with self._lock:
            return self._pgroups.get(replica_id)

    def replica_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._replicas)
