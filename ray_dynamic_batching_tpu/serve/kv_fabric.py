"""KV page fabric transfer plane — couriers for live-stream and prefix
parcels between replicas (ISSUE 18 tentpole).

The engine half (``engine/pagefabric.py``) freezes and splices parcels;
this module is the plane that MOVES them. Every delivery crosses the
ControlFabric seam on one of two canonical edges:

- ``courier.migrate`` — a live stream's page set + cursor, source
  replica -> destination replica. Source-side commit happens only on an
  acknowledged True from the destination's ``accept_parcel``, so a
  courier death, a partition window opening mid-parcel, or a
  destination refusal all degrade the same way: the source slot keeps
  decoding as if the directive never arrived, and the drain loop
  retries on its next pass.
- ``courier.push`` — a hot prefix entry pushed speculatively to a peer
  that does not hold it. Pushes are pure optimizations: every failure
  mode is "skip", bounded per destination by a push budget so a flash
  crowd's replication never floods a loaded replica.

Pricing lives with the replanner (``scheduler/replan.py``:
``COURIER_MS_PER_MB``) so migrations compete in the same objective as
resharding; this module only reports parcel bytes.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

from ray_dynamic_batching_tpu.serve.fabric import (
    ControlFabric,
    FabricUnreachable,
    default_fabric,
)
from ray_dynamic_batching_tpu.utils.concurrency import OrderedLock
from ray_dynamic_batching_tpu.utils.logging import get_logger
from ray_dynamic_batching_tpu.utils import metrics as m

logger = get_logger("kv_fabric")

# Parcel deliveries by courier edge and outcome. Edge values are the two
# canonical courier edges; bounded anyway (fabric.py discipline) so a
# mislabeled caller cannot mint series.
PARCELS = m.Counter(
    "rdb_fabric_parcels_total",
    "KV page parcels by courier edge and outcome "
    "(shipped | refused | failed)",
    tag_keys=("edge", "outcome"),
    bounded_tags={"edge": 8},
)
PREFIX_PUSHES = m.Counter(
    "rdb_prefix_pushes_total",
    "Hot prefix entries pushed to peer replicas ahead of demand",
    tag_keys=("deployment",),
    bounded_tags={"deployment": 8},
)


class KVPageFabric:
    """Courier endpoints + the two control-plane moves built on them:
    zero-drop stream drains and budgeted prefix push replication.

    Replica objects are in-process here (the single-host posture every
    serve seam in this repo takes); the ControlFabric call is the
    network seam a multi-host courier would cross, which is exactly
    where the chaos/partition harness injects failure.
    """

    def __init__(self, fabric: Optional[ControlFabric] = None,
                 push_budget: int = 2) -> None:
        self.fabric = fabric or default_fabric()
        # Per-destination cap on prefix parcels per push tick: push
        # replication must warm peers, not stampede them.
        self.push_budget = int(push_budget)
        self._lock = OrderedLock("metrics")
        self.parcels_shipped = 0
        self.parcels_refused = 0
        self.parcels_failed = 0
        self.prefix_pushed = 0

    def _count(self, edge: str, outcome: str) -> None:
        PARCELS.inc(tags={"edge": edge, "outcome": outcome})
        with self._lock:
            if outcome == "shipped":
                self.parcels_shipped += 1
            elif outcome == "refused":
                self.parcels_refused += 1
            else:
                self.parcels_failed += 1

    # --- courier edges -----------------------------------------------------
    def _deliver(self, edge: str, dst: Any, src_id: str) -> Any:
        """Build the deliver callback the source engine invokes with the
        frozen parcel (ON the source engine's thread). Returns True only
        when the destination ACCEPTED — the source's commit gate."""
        def deliver(parcel: Any) -> bool:
            try:
                ok = bool(self.fabric.call(
                    edge, dst.accept_parcel, parcel,
                    src=src_id, dst=dst.replica_id,
                ))
            except FabricUnreachable:
                # Partition/chaos mid-parcel: the stream was never torn
                # down at the source (commit requires this True), so the
                # failure costs one retry, zero tokens.
                self._count(edge, "failed")
                return False
            self._count(edge, "shipped" if ok else "refused")
            return ok
        return deliver

    def migrate(self, src: Any, dst: Any, request_id: str) -> bool:
        """Direct a single live stream from ``src`` to ``dst``. Returns
        whether the source enqueued the directive (delivery and commit
        happen on the source engine's thread at its next service
        point)."""
        return src.request_migration(
            request_id, self._deliver("courier.migrate", dst, src.replica_id)
        )

    # --- zero-drop drain ---------------------------------------------------
    def drain_streams(self, src: Any, peers: Sequence[Any],
                      timeout_s: float = 30.0,
                      poll_s: float = 0.02) -> Dict[str, int]:
        """Migrate every live stream off ``src`` to the least-loaded
        peer — the zero-drop replacement for the drain-evict-requeue a
        rolling update or scale-down used to cost. Re-requests remaining
        streams each pass (directives are idempotent: a stream that
        finished or already moved is skipped at service time) until the
        replica reports none left or the deadline passes; streams still
        live at timeout fall back to the old stop() semantics, so the
        worst case equals the status quo, never worse."""
        stats = {"requested": 0, "remaining": 0}
        if not peers or not hasattr(src, "live_stream_ids"):
            stats["remaining"] = len(getattr(
                src, "live_stream_ids", lambda: [])())
            return stats
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            live = src.live_stream_ids()
            if not live:
                break
            ranked = sorted(peers, key=lambda r: r.queue_len())
            for i, rid in enumerate(live):
                dst = ranked[i % len(ranked)]
                if self.migrate(src, dst, rid):
                    stats["requested"] += 1
            time.sleep(poll_s)  # rdb-lint: disable=event-loop-blocking (control-plane drain poll on the controller's deferred-action path; no event loop involved)
        stats["remaining"] = len(src.live_stream_ids())
        if stats["remaining"]:
            logger.warning(
                "%s: %d stream(s) still live after %.1fs drain window — "
                "falling back to stop() drain semantics",
                src.replica_id, stats["remaining"], timeout_s,
            )
        return stats

    # --- prefix push replication ------------------------------------------
    def push_hot_prefixes(self, deployment: str, replicas: Sequence[Any],
                          directory: Any = None,
                          limit: int = 8) -> int:
        """One push tick: rank each replica's hot resident prefixes and
        push entries to the least-loaded peers that do not already hold
        them (holder set from the router directory snapshot when given),
        at most ``push_budget`` parcels per destination per tick."""
        live = [r for r in replicas
                if hasattr(r, "hot_prefixes") and not getattr(
                    r, "_stopped", False)]
        if len(live) < 2:
            return 0
        holders: Dict[str, set] = {}
        if directory is not None:
            snap = directory.snapshot()
            for rid, digests in snap.get("replicas", {}).items():
                for hexkey in digests:
                    holders.setdefault(hexkey, set()).add(rid)
        budget = {r.replica_id: self.push_budget for r in live}
        pushed = 0
        for src in live:
            for hexkey, _pages, _hits in src.hot_prefixes(limit):
                have = holders.setdefault(hexkey, set())
                have.add(src.replica_id)
                targets = sorted(
                    (r for r in live
                     if r.replica_id not in have
                     and budget[r.replica_id] > 0),
                    key=lambda r: r.queue_len(),
                )
                if not targets:
                    continue
                dst = targets[0]
                ok = src.request_prefix_push(
                    hexkey,
                    self._deliver("courier.push", dst, src.replica_id),
                )
                if ok:
                    budget[dst.replica_id] -= 1
                    # Optimistic holder mark: the push is in flight; a
                    # failed delivery just means one redundant retry in
                    # a later tick once the directory catches up.
                    have.add(dst.replica_id)
                    pushed += 1
                    PREFIX_PUSHES.inc(tags={"deployment": deployment})
        if pushed:
            with self._lock:
                self.prefix_pushed += pushed
        return pushed

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "parcels_shipped": self.parcels_shipped,
                "parcels_refused": self.parcels_refused,
                "parcels_failed": self.parcels_failed,
                "prefix_pushed": self.prefix_pushed,
            }
