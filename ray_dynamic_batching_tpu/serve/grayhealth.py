"""Gray-failure detection — straggler scoring against peer consensus.

PR 4's taxonomy is binary: ``healthy()`` is a bool, ``ReplicaDeadError``
is the only replica-level failure, and a replica running 5-10x slow (a
thermally throttled chip, a wedged DMA queue, a noisy neighbor) holds
its breaker closed forever because every slow batch still SUCCEEDS.
Dean & Barroso ("The Tail at Scale", CACM 2013) show exactly this class
of degradation dominates tail latency at fan-out — and the PR-8 sketch
substrate makes per-replica latency distributions cheap enough to
compare continuously. This module is the detector on top of them:

- **Scoring** (:func:`grade_observations`, pure): each replica's recent
  latency (p50, p95) is compared against the MEDIAN of its peers for
  the same deployment. A replica is an *outlier* when its p50 or p95
  exceeds ``ratio x peer-median`` (relative — absolute thresholds can't
  serve a fleet where one model answers in 2 ms and another in 2 s).
  Replicas without enough samples, or without enough graded peers to
  form a consensus, are UNGRADED — never guilty by absence of data.
- **Hysteresis state machine** (:class:`GrayHealthMonitor`):
  ``healthy -> suspect -> probation -> ejected``, driven by consecutive
  outlier ticks (one slow batch is noise; N consecutive graded ticks is
  a straggler), with the reverse edges ``suspect/probation -> healthy``
  after consecutive clear ticks. Probation drains the replica from the
  router's power-of-two candidate pool but keeps PROBING it (one
  request per probe interval — the breaker's half-open arm,
  generalized), so a healed replica earns its way back. Ejection is the
  terminal verdict: the replica feeds the existing engine-death replan
  /heal path and the planner reclaims the chip.
- **Capacity pricing**: :meth:`GrayHealthMonitor.capacity_factor` maps
  states onto the fraction of a chip the planner may still count
  (``scheduler/replan.decide_replan(capacity_factors=...)``) —
  probation is fractional capacity, not alive/dead.

The monitor is shared verbatim by the serve tier (controller ticks it
with per-replica queue sketches) and the simulator (``sim/control.py``
ticks it with observed/expected step-latency ratios) — the no-drift
discipline every cross-tier policy here follows. Every transition lands
in the audit ring next to heals and breaker trips.
"""

from __future__ import annotations

import math
import statistics
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_dynamic_batching_tpu.utils.concurrency import assert_owner
from ray_dynamic_batching_tpu.utils.logging import get_logger
from ray_dynamic_batching_tpu.utils import metrics as m

logger = get_logger("grayhealth")

GRAY_STATES = ("healthy", "suspect", "probation", "ejected")

GRAY_TRANSITIONS = m.Counter(
    "rdb_gray_transitions_total",
    "Gray-health state transitions (to: suspect | probation | ejected | "
    "healthy)",
    tag_keys=("deployment", "to"),
)


@dataclass(frozen=True)
class GrayHealthPolicy:
    """Detection knobs — ratios are RELATIVE to the peer consensus.

    The defaults are deliberately conservative (3x the peer median,
    two consecutive graded ticks per escalation): a false probation
    costs real capacity, while a true straggler is caught within a few
    monitor intervals either way. ``eject_after=0`` disables automatic
    ejection — probation already removes the replica from the serving
    pool, and ejection (replace/reclaim) is an operator-level policy a
    deployment opts into."""

    p50_ratio: float = 3.0        # outlier when p50 > ratio * peer median
    p95_ratio: float = 3.0        # ... or p95 > ratio * peer median p95
    min_abs_ms: float = 1.0       # ignore sub-floor latencies (ratio noise)
    min_samples: int = 8          # sketch samples needed to grade a replica
    min_peers: int = 2            # graded peers needed for a consensus
    suspect_after: int = 2        # consecutive outlier ticks -> suspect
    probation_after: int = 2      # further outlier ticks -> probation
    eject_after: int = 0          # probation ticks still-outlier -> ejected
                                  # (0 = never auto-eject)
    heal_after: int = 2           # consecutive clear ticks -> healthy
    probation_capacity: float = 0.35   # planner's fractional-chip price
    probe_interval_s: float = 0.25     # probation probe admission cadence


# One observation per replica per tick: (p50_ms, p95_ms, sample_count).
Observation = Tuple[float, float, int]


def grade_observations(
    observations: Dict[str, Observation], policy: GrayHealthPolicy
) -> Dict[str, Optional[bool]]:
    """Pure scoring: replica id -> True (outlier) / False (clear) /
    None (ungraded: too few samples, or too few graded peers to form a
    consensus). Shared by the live controller tick and the sim monitor
    so detection thresholds tuned in the sim transfer unchanged."""
    graded = {
        rid: obs for rid, obs in observations.items()
        if obs[2] >= policy.min_samples
    }
    out: Dict[str, Optional[bool]] = {rid: None for rid in observations}
    for rid, (p50, p95, _n) in graded.items():
        peers = [o for pid, o in graded.items() if pid != rid]
        if len(peers) < policy.min_peers:
            continue
        peer_p50 = median_or_zero([o[0] for o in peers])
        peer_p95 = median_or_zero([o[1] for o in peers])
        out[rid] = bool(
            (p50 > policy.min_abs_ms and p50 > policy.p50_ratio * peer_p50)
            or (p95 > policy.min_abs_ms
                and p95 > policy.p95_ratio * peer_p95)
        )
    return out


def median_or_zero(values: List[float]) -> float:
    """``statistics.median`` with the empty-input -> 0.0 convention the
    grader and the hedge threshold share (no consensus = no bar)."""
    return float(statistics.median(values)) if values else 0.0


def rank_percentile(samples: List[float], p: float) -> float:
    """The live ``RollingWindow.percentile`` rule (nearest-rank via
    ceil), over an explicit sample list. One definition for every
    ratio-window grader (live scheduler, sim) — no drift."""
    if not samples:
        return 0.0
    data = sorted(samples)
    idx = min(len(data) - 1, max(0, math.ceil(p * len(data)) - 1))
    return data[idx]


def ratio_observations(
    drained_by_id: Dict[str, List[float]],
    windows: Dict[str, List[List[float]]],
    window_ticks: int,
    probes: Optional[Dict[str, float]] = None,
) -> Dict[str, Observation]:
    """Fold one monitor tick's drained observed/expected ratio lists
    into the per-replica tick windows and produce grade-ready
    ``(p50, p95, n)`` observations. Shared VERBATIM by
    ``LiveScheduler.check_gray_health`` and the sim twin.

    Windows are TICK-bounded (last ``window_ticks`` drains): a 10x-slow
    engine finishes ~10x fewer batches per tick, so slow evidence must
    stay visible across ticks, while a heal flushes within
    ``window_ticks``. ``probes`` maps replica id -> synthetic probe
    ratio used when that replica's drain came back EMPTY (the sim's
    probation probe; the live tier has no ground truth to synthesize
    and passes none — an idled probationed engine holds state there)."""
    obs: Dict[str, Observation] = {}
    for rid, drained in drained_by_id.items():
        if not drained and probes is not None and rid in probes:
            drained = [probes[rid]]
        window = windows.setdefault(rid, [])
        window.append(drained)
        del window[:-window_ticks]
        samples = [x for tick in window for x in tick]
        obs[rid] = (
            rank_percentile(samples, 0.5),
            rank_percentile(samples, 0.95),
            len(samples),
        )
    return obs


@dataclass
class _ReplicaGrayState:
    state: str = "healthy"
    outlier_streak: int = 0
    clear_streak: int = 0
    probation_ticks: int = 0
    last_probe_at: float = 0.0
    since: float = 0.0            # clock() at the last transition


class GrayHealthMonitor:
    """Per-deployment gray-health state machine over a replica set.

    Thread-safe (the controller tick, the router's candidate filter and
    status() readers race); the injected ``clock`` keeps the simulator
    deterministic (virtual seconds) while live callers default to
    ``time.monotonic``."""

    def __init__(
        self,
        scope: str,
        policy: Optional[GrayHealthPolicy] = None,
        clock=time.monotonic,
    ) -> None:
        self.scope = scope
        self.policy = policy or GrayHealthPolicy()
        self._clock = clock
        self._lock = threading.Lock()
        self._states: Dict[str, _ReplicaGrayState] = {}
        # Optional decision ring (scheduler/audit.AuditLog): gray
        # transitions are control-plane decisions and belong in the same
        # timeline as heals, breaker trips and governor transitions.
        self.audit = None
        # Bounded ring: a long-lived live monitor with a flapping
        # replica must not grow without limit; the cap is far above any
        # sim scenario's timeline (reports read the whole deque).
        self.transitions: deque = deque(maxlen=4096)

    # --- state machine ----------------------------------------------------
    def _st(self, rid: str) -> _ReplicaGrayState:
        assert_owner(self._lock)  # callers hold it (tick)
        st = self._states.get(rid)
        if st is None:
            st = self._states[rid] = _ReplicaGrayState(
                since=self._clock()
            )
        return st

    def tick(
        self, observations: Dict[str, Observation]
    ) -> List[Dict[str, Any]]:
        """Grade one monitor tick's observations and advance every
        replica's state machine. Returns the transitions this tick
        caused (also appended to :attr:`transitions` and audited)."""
        verdicts = grade_observations(observations, self.policy)
        fired: List[Dict[str, Any]] = []
        with self._lock:
            for rid, verdict in verdicts.items():
                st = self._st(rid)
                if st.state == "ejected" or verdict is None:
                    # Ungraded ticks hold state: never guilty (or healed)
                    # by absence of data.
                    continue
                if verdict:
                    st.outlier_streak += 1
                    st.clear_streak = 0
                else:
                    st.clear_streak += 1
                    st.outlier_streak = 0
                new_state = self._next_state_locked(st)
                if new_state is not None:
                    fired.append(self._transition_locked(
                        rid, st, new_state, observations[rid]
                    ))
        for t in fired:
            self._publish(t)
        return fired

    def _next_state_locked(
        self, st: _ReplicaGrayState
    ) -> Optional[str]:
        p = self.policy
        if st.state == "healthy":
            if st.outlier_streak >= p.suspect_after:
                return "suspect"
        elif st.state == "suspect":
            if st.outlier_streak >= p.probation_after:
                return "probation"
            if st.clear_streak >= p.heal_after:
                return "healthy"
        elif st.state == "probation":
            if st.outlier_streak:
                st.probation_ticks += 1
            if p.eject_after > 0 and st.probation_ticks >= p.eject_after:
                return "ejected"
            if st.clear_streak >= p.heal_after:
                return "healthy"
        return None

    def _transition_locked(
        self, rid: str, st: _ReplicaGrayState, new_state: str,
        obs: Observation,
    ) -> Dict[str, Any]:
        record = {
            "at": self._clock(),
            "replica": rid,
            "from": st.state,
            "to": new_state,
            "p50_ms": round(obs[0], 3),
            "p95_ms": round(obs[1], 3),
        }
        st.state = new_state
        st.outlier_streak = 0
        st.clear_streak = 0
        st.since = record["at"]
        if new_state != "probation":
            st.probation_ticks = 0
        self.transitions.append(record)
        return record

    def _publish(self, t: Dict[str, Any]) -> None:
        GRAY_TRANSITIONS.inc(tags={"deployment": self.scope,
                                   "to": t["to"]})
        log = logger.warning if t["to"] != "healthy" else logger.info
        log(
            "%s: replica %s gray-health %s -> %s (p50=%.1fms p95=%.1fms)",
            self.scope, t["replica"], t["from"], t["to"],
            t["p50_ms"], t["p95_ms"],
        )
        if self.audit is not None:
            self.audit.record(
                f"gray_{'heal' if t['to'] == 'healthy' else t['to']}",
                key=self.scope,
                observed={"replica": t["replica"], "p50_ms": t["p50_ms"],
                          "p95_ms": t["p95_ms"]},
                before={"state": t["from"]},
                after={"state": t["to"]},
                diff={("readmitted" if t["to"] == "healthy"
                       else "degraded"): t["replica"]},
            )

    # --- routing surface --------------------------------------------------
    def state(self, rid: str) -> str:
        with self._lock:
            st = self._states.get(rid)
            return st.state if st is not None else "healthy"

    def states(self) -> Dict[str, str]:
        with self._lock:
            return {rid: st.state for rid, st in self._states.items()}

    def is_candidate(self, rid: str) -> bool:
        """May this replica sit in the pow-2 candidate pool right now?
        healthy/suspect: yes. probation: only when a probe is due (the
        half-open arm — one request per probe interval keeps its sketch
        fresh so heals are observable). ejected: never."""
        with self._lock:
            st = self._states.get(rid)
            if st is None or st.state in ("healthy", "suspect"):
                return True
            if st.state == "probation":
                return (self._clock() - st.last_probe_at
                        >= self.policy.probe_interval_s)
            return False

    def mark_probe(self, rid: str) -> None:
        """One probation probe dispatched: start the next probe window."""
        with self._lock:
            st = self._states.get(rid)
            if st is not None and st.state == "probation":
                st.last_probe_at = self._clock()

    def capacity_factor(self, rid: str) -> float:
        """The planner's price for this replica/engine: a full chip while
        healthy or merely suspect, a fraction in probation, zero once
        ejected (``scheduler/replan`` folds the displaced load onto
        full-capacity peers)."""
        state = self.state(rid)
        if state == "probation":
            return self.policy.probation_capacity
        if state == "ejected":
            return 0.0
        return 1.0

    def forget(self, rid: str) -> None:
        """Drop a retired/replaced replica's state (the replacement
        starts healthy — it is new hardware, not the old verdict)."""
        with self._lock:
            self._states.pop(rid, None)

    def prune(self, live: set) -> None:
        with self._lock:
            for rid in [r for r in self._states if r not in live]:
                del self._states[rid]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "states": {rid: {
                    "state": st.state,
                    "outlier_streak": st.outlier_streak,
                    "clear_streak": st.clear_streak,
                    "since": st.since,
                } for rid, st in self._states.items()},
                "transitions": list(self.transitions)[-20:],
            }
