"""Serve controller — deployment reconciliation, autoscaling, recovery.

Re-creates Ray Serve's control plane: the singleton ``ServeController``
(``python/ray/serve/_private/controller.py``) reconciling deployment target
state, checkpointing to the GCS KV store under a checkpoint key
(``controller.py:79-80``, save at ``:545``;
``application_state.py:65,1096-1110``) so a restarted controller resumes
where it left off; the deployment state machine scaling replicas up/down and
replacing unhealthy ones (``deployment_state.py``); replica-set changes
pushed to routers over long poll (SURVEY.md §2.3).

TPU-first note: replica startup can imply weight upload + XLA warmup, so the
state machine starts replicas *before* registering them with the router and
drains before stopping — the same rollout discipline Serve uses for slow
torch model loads, with compile time in place of load time.
"""

from __future__ import annotations

import collections
import json
import math
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_dynamic_batching_tpu.parallel.placement import (
    PlacementError,
    PlacementManager,
)
from ray_dynamic_batching_tpu.runtime.kv import KVStore
from ray_dynamic_batching_tpu.scheduler.audit import AuditLog
from ray_dynamic_batching_tpu.serve.admission import (
    AdmissionController,
    AdmissionPolicy,
)
from ray_dynamic_batching_tpu.serve.autoscaling import (
    AutoscalingConfig,
    AutoscalingPolicy,
)
from ray_dynamic_batching_tpu.serve.long_poll import LongPollHost
from ray_dynamic_batching_tpu.serve.replica import Replica
from ray_dynamic_batching_tpu.serve.router import Router
from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("controller")

CHECKPOINT_KEY = "serve:controller:checkpoint"  # ref controller.py:79-80
REPLICA_SET_KEY = "serve:replicas:{deployment}"


@dataclass
class DeploymentConfig:
    """Deployment contract (ref @serve.deployment options + config.py).

    ``chips_per_replica > 0`` makes every replica acquire its chips through
    a placement group before starting (ref: Serve's deployment scheduler
    places replica actors via PGs — ``_private/deployment_scheduler.py``,
    ``gcs_placement_group_scheduler.cc``); ``placement_strategy`` is one of
    PACK/SPREAD/STRICT_PACK/STRICT_SPREAD.
    """

    name: str
    num_replicas: int = 1
    max_batch_size: int = 8
    batch_wait_timeout_s: float = 0.005
    max_ongoing_requests: int = 256
    max_restarts: int = 3
    autoscaling: Optional[AutoscalingConfig] = None
    user_config: Dict[str, Any] = field(default_factory=dict)
    chips_per_replica: int = 0          # 0 = no chip reservation
    placement_strategy: str = "PACK"
    # Code/config version for ROLLING updates (ref deployment_state.py
    # rollout: redeploying a new version gradually replaces replicas with
    # both versions serving and bounded unavailability). "" = unversioned:
    # redeploys reconfigure in place, never roll.
    version: str = ""
    # Fraction of num_replicas that may be down at once mid-rollout (ref
    # Serve's 20% rollout rate); at least one replica always rolls.
    rolling_max_unavailable_fraction: float = 0.2
    # Advertised multiplex-LRU size per replica; serve.run syncs this to a
    # @multiplexed loader's bound so the router never steers traffic to a
    # replica whose cache already evicted the model.
    max_multiplexed_models: int = 8
    # --- multi-tenant QoS (serve/admission.py) ---
    # Service tier for requests that declare none (interactive | standard
    # | best_effort) — the deployment's contract, stamped by the handle.
    default_qos_class: str = "standard"
    # Per-(tenant, class) token-bucket admission rate consulted by the
    # proxies BEFORE queueing; 0 = no admission control (admit all).
    admission_rate_rps: float = 0.0
    admission_burst: float = 0.0       # 0 -> defaults to the rate
    # --- gray-failure defense (serve/grayhealth.py) ---
    # Hedged dispatch for interactive-class requests ("The Tail at
    # Scale"): when a primary dispatch exceeds the deployment's profiled
    # p95 with no output, re-dispatch to a different replica and let the
    # first winner cancel the loser. Per-deployment opt-in — the extra
    # dispatches are the wrong trade under queue-bound overload.
    hedge_interactive: bool = False
    # Probation ticks of sustained slowness before a straggler replica
    # is EJECTED (replaced like a dead one, chip reclaimed). 0 = detect
    # and probation only, never auto-eject.
    gray_eject_after: int = 0

    def to_json(self) -> Dict[str, Any]:
        d = {
            "name": self.name,
            "num_replicas": self.num_replicas,
            "max_batch_size": self.max_batch_size,
            "batch_wait_timeout_s": self.batch_wait_timeout_s,
            "max_ongoing_requests": self.max_ongoing_requests,
            "max_restarts": self.max_restarts,
            "user_config": self.user_config,
            "chips_per_replica": self.chips_per_replica,
            "placement_strategy": self.placement_strategy,
            "max_multiplexed_models": self.max_multiplexed_models,
            "version": self.version,
            "rolling_max_unavailable_fraction":
                self.rolling_max_unavailable_fraction,
            "default_qos_class": self.default_qos_class,
            "admission_rate_rps": self.admission_rate_rps,
            "admission_burst": self.admission_burst,
            "hedge_interactive": self.hedge_interactive,
            "gray_eject_after": self.gray_eject_after,
        }
        if self.autoscaling is not None:
            d["autoscaling"] = vars(self.autoscaling)
        return d

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "DeploymentConfig":
        auto = d.pop("autoscaling", None)
        cfg = DeploymentConfig(**d)
        if auto is not None:
            cfg.autoscaling = AutoscalingConfig(**auto)
        return cfg


@dataclass
class _DeploymentState:
    """Live state for one deployment (ref DeploymentState)."""

    config: DeploymentConfig
    factory: Callable[[], Callable[[List[Any]], Sequence[Any]]]
    replicas: List[Replica] = field(default_factory=list)
    router: Optional[Router] = None
    policy: Optional[AutoscalingPolicy] = None
    restarts: int = 0
    next_replica_ordinal: int = 0
    unhealthy: bool = False  # restart budget spent; held until redeploy
    # replica_id -> its placement group (only when chips_per_replica > 0)
    pgroups: Dict[str, Any] = field(default_factory=dict)


class ServeController:
    """Singleton control loop owning deployments, routers, and scaling."""

    def __init__(
        self,
        kv: Optional[KVStore] = None,
        long_poll: Optional[LongPollHost] = None,
        control_interval_s: float = 0.5,
        placement: Optional[PlacementManager] = None,
    ) -> None:
        self.kv = kv or KVStore()
        self.long_poll = long_poll or LongPollHost()
        self.placement = placement
        self.control_interval_s = control_interval_s
        self._deployments: Dict[str, _DeploymentState] = {}
        self._factories: Dict[str, Callable] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_checkpoint: Optional[str] = None
        # Structured decision ring (scheduler/audit.py): deploys, scale
        # moves, heals, rollouts — surfaced per deployment in status().
        self.audit = AuditLog("serve")
        # Token-bucket admission + overload governor (serve/admission.py):
        # the proxies consult it pre-queue; this control loop feeds it
        # queue-depth/compliance signals each step, and its governor
        # transitions land in the SAME audit ring as heals and replans.
        self.admission = AdmissionController()
        self.admission.audit = self.audit

    # --- deploy API (ref serve.run / deploy) ------------------------------
    def register_factory(
        self,
        name: str,
        factory: Callable[[], Callable[[List[Any]], Sequence[Any]]],
    ) -> None:
        """Factories are code, not state: after a controller restart the
        checkpoint restores *configs* and factories must be re-registered
        (the reference re-imports deployment code the same way)."""
        self._factories[name] = factory

    def deploy(
        self,
        config: DeploymentConfig,
        factory: Optional[Callable] = None,
    ) -> Router:
        with self._lock:
            if factory is not None:
                self.register_factory(config.name, factory)
            if config.name not in self._factories:
                raise KeyError(f"no factory registered for {config.name!r}")
            from ray_dynamic_batching_tpu.serve.failover import (
                HedgeManager,
                HedgePolicy,
            )
            from ray_dynamic_batching_tpu.serve.grayhealth import (
                GrayHealthPolicy,
            )

            state = self._deployments.get(config.name)
            if state is None:
                state = _DeploymentState(
                    config=config,
                    factory=self._factories[config.name],
                    router=Router(
                        config.name,
                        gray_policy=GrayHealthPolicy(
                            eject_after=config.gray_eject_after
                        ),
                        hedge_policy=(HedgePolicy()
                                      if config.hedge_interactive
                                      else None),
                    ),
                )
                # Breaker trip/recover events are control-plane decisions:
                # they share the controller's audit ring with heals and
                # scale moves (one timeline per deployment).
                state.router.audit = self.audit
                self._deployments[config.name] = state
            else:
                # Deliver user_config only when it CHANGED (including a
                # change TO {} — clearing must reach the hook): the user's
                # reconfigure can be expensive (weight reloads) and must
                # not re-run because an unrelated knob moved.
                prev_user = state.config.user_config
                prev_version = state.config.version
                state.config = config
                # Gray/hedge knobs live on the ROUTER, not the replicas:
                # a redeploy must reprice them here or status() reports
                # the new config while the router keeps enforcing the
                # old policy until the next controller restart.
                router = state.router
                if config.gray_eject_after != router.gray.policy.eject_after:
                    router.gray.policy = GrayHealthPolicy(
                        eject_after=config.gray_eject_after
                    )
                if config.hedge_interactive and router.hedge is None:
                    router.hedge = HedgeManager(router, HedgePolicy())
                elif not config.hedge_interactive and router.hedge is not None:
                    router.hedge.close()
                    router.hedge = None
                # A redeploy may carry NEW code: future replica starts
                # (rollout replacements included) must build from the
                # freshly registered factory, not the one captured at
                # first deploy.
                state.factory = self._factories[config.name]
                state.restarts = 0  # a fresh deploy resets the budget
                state.unhealthy = False
                if config.version and config.version != prev_version:
                    # Version change -> ROLLING update: old-version
                    # replicas keep serving as-is until _reconcile retires
                    # them in bounded batches (pushing the new config into
                    # doomed replicas would run expensive reconfigures
                    # twice and blur which version produced a response).
                    logger.info(
                        "%s: rolling update %r -> %r over %d replicas",
                        config.name, prev_version, config.version,
                        len(state.replicas),
                    )
                else:
                    # Push changed batching/concurrency knobs to RUNNING
                    # replicas (otherwise re-deploys silently produce a
                    # mixed-config replica set).
                    for r in state.replicas:
                        r.reconfigure(
                            max_batch_size=config.max_batch_size,
                            batch_wait_timeout_s=config.batch_wait_timeout_s,
                            max_ongoing_requests=config.max_ongoing_requests,
                            user_config=(
                                config.user_config
                                if config.user_config != prev_user else None
                            ),
                        )
            if config.autoscaling is not None:
                state.policy = AutoscalingPolicy(
                    config.autoscaling, interval_s=self.control_interval_s
                )
            else:
                state.policy = None  # autoscaling removed -> pin num_replicas
            self.admission.configure(
                config.name,
                AdmissionPolicy(rate_rps=config.admission_rate_rps,
                                burst=config.admission_burst)
                if config.admission_rate_rps > 0 else None,
            )
            self.audit.record(
                "deploy",
                key=config.name,
                before={"replicas": len(state.replicas)},
                after={"replicas": config.num_replicas,
                       "version": config.version},
                diff={"target_replicas": config.num_replicas,
                      "version": config.version},
            )
            deferred = self._reconcile(state)
            self._checkpoint()
        for action in deferred:  # blocking stops run outside the lock
            action()
        return state.router

    def delete_deployment(self, name: str) -> None:
        with self._lock:
            state = self._deployments.pop(name, None)
            if state is None:
                return
            self.admission.configure(name, None)
            victims = state.replicas
            state.replicas = []
            self._publish(state)
            state.router.close()
            self._checkpoint()
            self.audit.record(
                "delete",
                key=name,
                before={"replicas": len(victims)},
                after={"replicas": 0},
                diff={"stopped": [r.replica_id for r in victims]},
            )
        for r in victims:  # blocking drains outside the lock
            r.stop()
            self._release_chips(state, r)

    def get_router(self, name: str) -> Router:
        with self._lock:
            return self._deployments[name].router

    def deployments(self) -> List[str]:
        with self._lock:
            return sorted(self._deployments)

    # --- state machine (ref deployment_state.py scale/heal) ---------------
    def _start_replica(self, state: _DeploymentState) -> Replica:
        cfg = state.config
        rid = f"{cfg.name}#{state.next_replica_ordinal}"
        state.next_replica_ordinal += 1
        # Gang-acquire chips BEFORE building the replica (ref: the
        # deployment scheduler waits on the PG, then places the actor in it
        # — deployment_scheduler.py / gcs_placement_group_scheduler.cc).
        pg = None
        devices = None
        if cfg.chips_per_replica > 0:
            if self.placement is None:
                raise RuntimeError(
                    f"{cfg.name}: chips_per_replica={cfg.chips_per_replica} "
                    "requires a PlacementManager on the controller"
                )
            from ray_dynamic_batching_tpu.parallel.placement import Bundle

            pg = self.placement.create(
                [Bundle(chips=cfg.chips_per_replica)],
                strategy=cfg.placement_strategy,
            )
            devices = pg.bundle_devices(0)
        try:
            factory = state.factory
            if hasattr(factory, "make_replica"):
                # Deployment owns its replica class (e.g. serve.llm.LLMReplica
                # wrapping a decode engine) — mirror of the reference where
                # deployment target state carries the replica actor definition.
                if devices is not None:
                    replica = factory.make_replica(rid, cfg, devices=devices)
                else:
                    replica = factory.make_replica(rid, cfg)
            else:
                replica = Replica(
                    replica_id=rid,
                    deployment=cfg.name,
                    fn=factory(),
                    max_batch_size=cfg.max_batch_size,
                    batch_wait_timeout_s=cfg.batch_wait_timeout_s,
                    max_ongoing_requests=cfg.max_ongoing_requests,
                )
                replica.max_multiplexed_models = cfg.max_multiplexed_models
                if devices is not None:
                    replica.devices = devices
            if cfg.user_config:
                # Initial user_config applies BEFORE serving, for every
                # replica kind (ref: reconfigure runs before the replica
                # serves) — not just the plain-Replica branch.
                replica.reconfigure(user_config=cfg.user_config)
            replica.start()
        except Exception:
            if pg is not None:  # failed start must not leak reserved chips
                self.placement.remove(pg)
            raise
        if pg is not None:
            state.pgroups[rid] = pg
        # Stamp the config version the replica was BUILT from: the rollout
        # stage retires replicas whose stamp differs from the target.
        replica.version = cfg.version
        logger.info(
            "started replica %s%s%s", rid,
            f" (version {cfg.version!r})" if cfg.version else "",
            f" on chips {[str(d) for d in devices]}" if devices else "",
        )
        return replica

    def _release_chips(self, state: _DeploymentState, replica: Replica) -> None:
        pg = state.pgroups.pop(replica.replica_id, None)
        if pg is not None and self.placement is not None:
            self.placement.remove(pg)

    def _redeliver(
        self,
        router: Router,
        requests: List[Any],
        victim_id: str,
        dead: bool = False,
    ) -> None:
        """Salvage a retired replica's queued requests through the
        failover path: deadline-budgeted re-dispatch to a different
        replica, shed accounting when hopeless (terminal rejection
        belongs to the failover layer, not the heal path). ``dead``
        marks a crashed/wedged victim (heal) vs a planned rollout."""
        router.requeue_drained(requests, victim_id, dead=dead)

    def _reconcile(self, state: _DeploymentState) -> List[Callable[[], None]]:
        """Drive actual replica count to target; replace unhealthy.

        Returns deferred (blocking) stop actions — callers run them AFTER
        releasing the controller lock, so a slow drain or a wedged callable
        can't freeze the whole control plane."""
        cfg = state.config
        deferred: List[Callable[[], None]] = []
        # Heal: replace dead replicas up to max_restarts
        # (ref gcs_actor_manager.cc:1361-1393 restart budget). A replica
        # the gray-health monitor EJECTED (sustained straggling through
        # its whole probation) rides the same path: replaced like a dead
        # one, so the planner reclaims the chip from gray failures too.
        alive: List[Replica] = []
        for r in state.replicas:
            ejected = state.router.gray.state(r.replica_id) == "ejected"
            if r.healthy() and not ejected:
                alive.append(r)
                continue
            logger.warning(
                "replica %s %s; replacing", r.replica_id,
                "gray-ejected (straggler)" if ejected else "unhealthy",
            )
            # Salvage queued work, then stop the victim INLINE (its loop is
            # dead or wedged, so the join is bounded) — the replacement may
            # land on the same chips, which must be genuinely free: chip
            # reservation released AND, for engines, HBM buffers dropped
            # (LLMReplica.stop releases them once the loop has exited).
            salvaged = r.drain_queue()
            r.stop(timeout_s=2.0, drain=False)
            self._release_chips(state, r)
            replacement: Optional[Replica] = None
            if state.restarts < cfg.max_restarts:
                state.restarts += 1
                try:
                    replacement = self._start_replica(state)
                    alive.append(replacement)
                except PlacementError as e:
                    # Transient chip shortage is not a crash: hand the
                    # restart back and let a later control step retry via
                    # the scale-up loop below.
                    state.restarts -= 1
                    logger.warning(
                        "%s: replacement blocked: %s", cfg.name, e
                    )
                except Exception:  # noqa: BLE001 — a failing start must not
                    # abort the control step (deferred redeliveries of other
                    # replicas would be dropped); the burned restart counts,
                    # so a crash-looping factory still exhausts its budget.
                    logger.exception(
                        "%s: replacement start failed", cfg.name
                    )
            else:
                state.unhealthy = True
                logger.error(
                    "%s: restart budget (%d) exhausted; deployment "
                    "unhealthy until redeployed",
                    cfg.name, cfg.max_restarts,
                )
            if salvaged:
                deferred.append(
                    lambda reqs=salvaged, rt=state.router, vid=r.replica_id: (
                        self._redeliver(rt, reqs, vid, dead=True)
                    )
                )
            self.audit.record(
                "heal",
                key=cfg.name,
                observed={"unhealthy": r.replica_id,
                          "gray_ejected": ejected,
                          "salvaged_requests": len(salvaged)},
                diff={
                    "replaced": r.replica_id,
                    "replacement": (replacement.replica_id
                                    if replacement is not None else None),
                },
                note=("" if replacement is not None
                      else "restart budget exhausted or start failed"),
            )
        state.replicas = alive
        # Rolling update (ref deployment_state.py rollout): while replicas
        # with a DIFFERENT version stamp exist, retire them in batches of
        # at most ceil(rolling_max_unavailable_fraction * target) — and
        # only as many as keep the serving set at or above
        # target - batch, so both versions serve through the rollout and
        # unavailability stays bounded. Retired replicas drain in the
        # deferred stop (graceful: in-flight work finishes); the scale-up
        # loop below starts their new-version replacements this same pass.
        if cfg.version and not state.unhealthy:
            outdated = [
                r for r in state.replicas
                if getattr(r, "version", "") != cfg.version
            ]
            if outdated:
                batch = max(
                    1, math.ceil(
                        cfg.rolling_max_unavailable_fraction
                        * cfg.num_replicas
                    ),
                )
                floor = cfg.num_replicas - batch
                can_stop = max(0, len(state.replicas) - floor)
                for victim in outdated[: min(batch, can_stop)]:
                    state.replicas.remove(victim)
                    logger.info(
                        "rolling out replica %s (version %r -> %r)",
                        victim.replica_id,
                        getattr(victim, "version", ""), cfg.version,
                    )
                    self.audit.record(
                        "rolling_update",
                        key=cfg.name,
                        before={"version": getattr(victim, "version", "")},
                        after={"version": cfg.version},
                        diff={"retired": victim.replica_id},
                    )
                    victim._stopped = True  # stale handles stop assigning
                    # Same salvage discipline as the heal path: queued
                    # (unstarted) requests move to surviving/new replicas
                    # immediately instead of gambling on the victim's drain
                    # window; only the in-flight batch finishes on the
                    # victim, with a rollout-sized timeout (a busy LLM
                    # replica's batch can legitimately run tens of
                    # seconds — the default 5 s drain would reject it).
                    salvaged = victim.drain_queue()
                    if salvaged:
                        deferred.append(
                            lambda reqs=salvaged, rt=state.router,
                            vid=victim.replica_id: (
                                self._redeliver(rt, reqs, vid)
                            )
                        )
                    deferred.append(
                        lambda v=victim, st=state: (
                            v.stop(timeout_s=60.0),
                            self._release_chips(st, v),
                        )
                    )
        # Scale to target — but an exhausted restart budget stops the
        # crash-loop: no replacements until a fresh deploy() resets it
        # (ref gcs_actor_manager.cc:1361-1393 — actors stay DEAD once
        # max_restarts is spent).
        n_before_scale = len(state.replicas)
        while len(state.replicas) < cfg.num_replicas and not state.unhealthy:
            try:
                state.replicas.append(self._start_replica(state))
            except PlacementError as e:
                # Not enough chips: hold at the current count and retry on
                # later control steps (ref: the PG stays pending).
                logger.warning("%s: scale-up blocked: %s", cfg.name, e)
                break
            except Exception:  # noqa: BLE001 — hold and retry next step
                logger.exception("%s: replica start failed", cfg.name)
                break
        while len(state.replicas) > cfg.num_replicas:
            victim = state.replicas.pop()  # newest first, ref compact strategy
            deferred.append(
                lambda v=victim, st=state: (
                    v.stop(),
                    self._release_chips(st, v),
                )
            )
        if len(state.replicas) != n_before_scale:
            self.audit.record(
                "scale",
                key=cfg.name,
                observed={"target": cfg.num_replicas},
                before={"replicas": n_before_scale},
                after={"replicas": len(state.replicas)},
                diff={"delta": len(state.replicas) - n_before_scale},
            )
        # Publish only on membership change: every publish clears the
        # router's queue-len cache, so steady-state reconciles must be quiet.
        if [r.replica_id for r in state.replicas] != [
            r.replica_id for r in state.router.replicas()
        ]:
            self._publish(state)  # routing stops before deferred drains run
        return deferred

    def _publish(self, state: _DeploymentState) -> None:
        """Push the replica set to routers via long poll (ref long_poll)."""
        state.router.update_replicas(state.replicas)
        self.long_poll.notify_changed(
            REPLICA_SET_KEY.format(deployment=state.config.name),
            [r.replica_id for r in state.replicas],
        )

    # --- control loop -----------------------------------------------------
    def _observe_gray(self, state: "_DeploymentState") -> None:
        """Tick the deployment's gray-health monitor with per-replica
        recent-latency sketches (PR 8's RollingSketch — recency-bounded,
        so the consensus describes the replica NOW). The monitor grades
        only replicas with enough samples and enough graded peers; the
        state machine's hysteresis does the rest."""
        obs = {}
        for r in state.replicas:
            try:
                obs[r.replica_id] = r.latency_observation()
            except Exception:  # noqa: BLE001 — stats must not stop control
                continue
        if len(obs) >= 2:
            state.router.gray.tick(obs)

    def _observe_admission(self, state: "_DeploymentState") -> None:
        """Feed the overload governor this deployment's congestion
        signals: worst replica queue-fill fraction + worst recent SLO
        compliance. Hysteresis and the degrade/recover decision live in
        the AdmissionController; every transition is audited."""
        if self.admission.policy(state.config.name) is None:
            return
        depth_frac = 0.0
        compliance = 1.0
        for r in state.replicas:
            cap = max(1, getattr(r, "max_ongoing_requests", 1))
            try:
                depth_frac = max(depth_frac, r.queue_len() / cap)
                compliance = min(compliance, r.slo_compliance())
            except Exception:  # noqa: BLE001 — stats must not stop control
                continue
        self.admission.observe(state.config.name, depth_frac, compliance)

    def _control_step(self) -> None:
        deferred: List[Callable[[], None]] = []
        with self._lock:
            for state in list(self._deployments.values()):
                self._observe_gray(state)
                self._observe_admission(state)
                if state.policy is not None:
                    metrics = state.router.demand_metrics()
                    target = state.policy.step(
                        metrics["total_ongoing"], len(state.replicas)
                    )
                    if target is not None and target != state.config.num_replicas:
                        logger.info(
                            "%s: autoscale %d -> %d (ongoing=%.0f)",
                            state.config.name, state.config.num_replicas,
                            target, metrics["total_ongoing"],
                        )
                        state.config.num_replicas = target
                try:
                    deferred.extend(self._reconcile(state))
                except Exception:  # noqa: BLE001 — one deployment's failure
                    # must not drop other deployments' deferred actions
                    logger.exception(
                        "%s: reconcile failed", state.config.name
                    )
            self._checkpoint()
        for action in deferred:  # blocking stops run outside the lock
            action()

    def _loop(self) -> None:
        while not self._stop.wait(self.control_interval_s):
            try:
                self._control_step()
            except Exception:  # noqa: BLE001
                logger.exception("control step failed")

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="serve-controller", daemon=True
        )
        self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        with self._lock:
            victims: List[Tuple[_DeploymentState, Replica]] = []
            for state in self._deployments.values():
                victims.extend((state, r) for r in state.replicas)
                state.replicas = []
                state.router.close()
        for state, r in victims:
            r.stop()
            self._release_chips(state, r)

    # --- checkpoint / recovery (ref controller.py:545, app_state:1096) ----
    def _checkpoint(self) -> None:
        payload = json.dumps(
            {
                name: state.config.to_json()
                for name, state in self._deployments.items()
            },
            sort_keys=True,
        )
        # Checkpoint-on-change: steady-state control steps must not rewrite
        # the KV file twice a second.
        if payload != self._last_checkpoint:
            self.kv.put(CHECKPOINT_KEY, payload)
            self._last_checkpoint = payload

    def recover(self) -> List[str]:
        """Restore deployments from the checkpoint (factories must already
        be re-registered). Returns recovered deployment names."""
        raw = self.kv.get(CHECKPOINT_KEY)
        if raw is None:
            return []
        recovered = []
        for name, cfg_json in json.loads(raw).items():
            if name not in self._factories:
                logger.warning(
                    "checkpointed deployment %r has no factory; skipping", name
                )
                continue
            self.deploy(DeploymentConfig.from_json(cfg_json))
            recovered.append(name)
        return recovered

    def status(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                name: {
                    "target_replicas": state.config.num_replicas,
                    "running_replicas": len(state.replicas),
                    "replicas": {
                        r.replica_id: r.stats() for r in state.replicas
                    },
                    "restarts": state.restarts,
                    "healthy": not state.unhealthy,
                    # Per-replica circuit-breaker state + the failover
                    # layer's retry/shed accounting (serve/failover.py) —
                    # the observable half of request-level fault tolerance.
                    "breakers": state.router.breaker_states(),
                    "failover": state.router.failover.stats(),
                    # Gray-health verdicts + hedge accounting (ISSUE 9):
                    # the straggler-defense half of fault tolerance.
                    "gray": state.router.gray.snapshot(),
                    "hedge": (state.router.hedge.stats()
                              if state.router.hedge is not None else None),
                    # Admission governor state (serve/admission.py):
                    # normal vs degraded + whether a policy is installed.
                    "admission": self.admission.snapshot(name),
                    # Per-version replica counts: mid-rollout both the old
                    # and the new version appear here (ref deployment_state
                    # rollout status).
                    "target_version": state.config.version,
                    "versions": dict(collections.Counter(
                        getattr(r, "version", "") for r in state.replicas
                    )),
                    # Recent control-plane decisions about THIS deployment
                    # (deploys, scale moves, heals, rollouts) from the
                    # structured audit ring — filtered BEFORE slicing so a
                    # busy co-deployed app cannot evict this one's view.
                    "audit": self.audit.to_dicts(key=name, last=10),
                }
                for name, state in self._deployments.items()
            }
        return out

    def resources(self) -> Dict[str, Any]:
        """Cluster resource snapshot (separate from the by-name deployment
        map so state/dashboard consumers never see a phantom deployment)."""
        if self.placement is None:
            return {"nodes": {}, "reservations": []}
        return self.placement.resource_view()
