"""Serve controller — deployment reconciliation, autoscaling, recovery.

Re-creates Ray Serve's control plane: the singleton ``ServeController``
(``python/ray/serve/_private/controller.py``) reconciling deployment target
state, checkpointing to the GCS KV store under a checkpoint key
(``controller.py:79-80``, save at ``:545``;
``application_state.py:65,1096-1110``) so a restarted controller resumes
where it left off; the deployment state machine scaling replicas up/down and
replacing unhealthy ones (``deployment_state.py``); replica-set changes
pushed to routers over long poll (SURVEY.md §2.3).

Control-plane scale-out (ISSUE 11): all controller-owned mutable state is
written through the :mod:`~ray_dynamic_batching_tpu.serve.store`
transaction API — the GCS move. With the default :class:`InMemoryStore`
nothing changes operationally; with a :class:`ReplicatedStore` every
transaction lands in a shared epoch-fenced log, a standby controller
replays it and takes over when the leader's lease lapses, and the deposed
leader's next write raises :class:`StaleEpochError` instead of corrupting
state it no longer owns. Live data-plane objects (replicas, routers)
survive the failover through a :class:`ReplicaCatalog`; clients' handles
keep routing throughout because the ROUTER they hold is adopted, never
replaced. The ``store-discipline`` lint rule (tools/lint/store.py) holds
this file to the transaction API.

TPU-first note: replica startup can imply weight upload + XLA warmup, so the
state machine starts replicas *before* registering them with the router and
drains before stopping — the same rollout discipline Serve uses for slow
torch model loads, with compile time in place of load time.
"""

from __future__ import annotations

import collections
import json
import math
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_dynamic_batching_tpu.parallel.placement import (
    PlacementError,
    PlacementManager,
)
from ray_dynamic_batching_tpu.engine.rates import RateRegistry
from ray_dynamic_batching_tpu.runtime.kv import KVStore
from ray_dynamic_batching_tpu.scheduler.audit import AuditLog
from ray_dynamic_batching_tpu.serve.admission import (
    AdmissionController,
    AdmissionPolicy,
)
from ray_dynamic_batching_tpu.serve.autoscaling import (
    AutoscalingConfig,
    AutoscalingPolicy,
)
from ray_dynamic_batching_tpu.serve.fabric import (
    ControlFabric,
    FabricUnreachable,
    default_fabric,
)
from ray_dynamic_batching_tpu.serve.kv_fabric import KVPageFabric
from ray_dynamic_batching_tpu.serve.long_poll import LongPollHost
from ray_dynamic_batching_tpu.utils.concurrency import OrderedLock
from ray_dynamic_batching_tpu.serve.observatory import SLOObservatory
from ray_dynamic_batching_tpu.serve.replica import Replica
from ray_dynamic_batching_tpu.serve.router import Router
from ray_dynamic_batching_tpu.serve.store import (
    ControllerStore,
    InMemoryStore,
    ReplicaCatalog,
    ReplicatedStore,
    StaleEpochError,
)
from ray_dynamic_batching_tpu.utils.logging import get_logger
from ray_dynamic_batching_tpu.utils.sketch import QuantileSketch

logger = get_logger("controller")

CHECKPOINT_KEY = "serve:controller:checkpoint"  # ref controller.py:79-80
REPLICA_SET_KEY = "serve:replicas:{deployment}"
PREFIX_DIGEST_KEY = "serve:prefix_digests:{deployment}"
QUARANTINE_KEY = "serve:quarantine:{deployment}"
STORE_QUARANTINE_KEY = "serve:quarantine/{deployment}"
# Controller-store keys (the replicated state the standby replays).
STORE_CONFIG_KEY = "serve:deployments/{deployment}/config"
STORE_REGISTRY_KEY = "serve:deployments/{deployment}/replicas"
STORE_GOVERNOR_KEY = "serve:governor/{deployment}"
STORE_GRAY_KEY = "serve:gray/{deployment}"


@dataclass
class DeploymentConfig:
    """Deployment contract (ref @serve.deployment options + config.py).

    ``chips_per_replica > 0`` makes every replica acquire its chips through
    a placement group before starting (ref: Serve's deployment scheduler
    places replica actors via PGs — ``_private/deployment_scheduler.py``,
    ``gcs_placement_group_scheduler.cc``); ``placement_strategy`` is one of
    PACK/SPREAD/STRICT_PACK/STRICT_SPREAD.
    """

    name: str
    num_replicas: int = 1
    max_batch_size: int = 8
    batch_wait_timeout_s: float = 0.005
    max_ongoing_requests: int = 256
    max_restarts: int = 3
    autoscaling: Optional[AutoscalingConfig] = None
    user_config: Dict[str, Any] = field(default_factory=dict)
    chips_per_replica: int = 0          # 0 = no chip reservation
    placement_strategy: str = "PACK"
    # Code/config version for ROLLING updates (ref deployment_state.py
    # rollout: redeploying a new version gradually replaces replicas with
    # both versions serving and bounded unavailability). "" = unversioned:
    # redeploys reconfigure in place, never roll.
    version: str = ""
    # Fraction of num_replicas that may be down at once mid-rollout (ref
    # Serve's 20% rollout rate); at least one replica always rolls.
    rolling_max_unavailable_fraction: float = 0.2
    # Advertised multiplex-LRU size per replica; serve.run syncs this to a
    # @multiplexed loader's bound so the router never steers traffic to a
    # replica whose cache already evicted the model.
    max_multiplexed_models: int = 8
    # --- multi-tenant QoS (serve/admission.py) ---
    # Service tier for requests that declare none (interactive | standard
    # | best_effort) — the deployment's contract, stamped by the handle.
    default_qos_class: str = "standard"
    # Per-(tenant, class) token-bucket admission rate consulted by the
    # proxies BEFORE queueing; 0 = no admission control (admit all).
    admission_rate_rps: float = 0.0
    admission_burst: float = 0.0       # 0 -> defaults to the rate
    # --- gray-failure defense (serve/grayhealth.py) ---
    # Hedged dispatch for interactive-class requests ("The Tail at
    # Scale"): when a primary dispatch exceeds the deployment's profiled
    # p95 with no output, re-dispatch to a different replica and let the
    # first winner cancel the loser. Per-deployment opt-in — the extra
    # dispatches are the wrong trade under queue-bound overload.
    hedge_interactive: bool = False
    # Probation ticks of sustained slowness before a straggler replica
    # is EJECTED (replaced like a dead one, chip reclaimed). 0 = detect
    # and probation only, never auto-eject.
    gray_eject_after: int = 0
    # --- metastable-failure defense (serve/retrybudget.py) ---
    # Re-dispatches (failover retries + hedges) allowed per recent
    # first-attempt dispatch; None = track without enforcing. The
    # governor's `congested` verdict zeroes the budget in either mode.
    retry_budget_fraction: Optional[float] = None
    retry_budget_window: int = 512

    def to_json(self) -> Dict[str, Any]:
        d = {
            "name": self.name,
            "num_replicas": self.num_replicas,
            "max_batch_size": self.max_batch_size,
            "batch_wait_timeout_s": self.batch_wait_timeout_s,
            "max_ongoing_requests": self.max_ongoing_requests,
            "max_restarts": self.max_restarts,
            "user_config": self.user_config,
            "chips_per_replica": self.chips_per_replica,
            "placement_strategy": self.placement_strategy,
            "max_multiplexed_models": self.max_multiplexed_models,
            "version": self.version,
            "rolling_max_unavailable_fraction":
                self.rolling_max_unavailable_fraction,
            "default_qos_class": self.default_qos_class,
            "admission_rate_rps": self.admission_rate_rps,
            "admission_burst": self.admission_burst,
            "hedge_interactive": self.hedge_interactive,
            "gray_eject_after": self.gray_eject_after,
            "retry_budget_fraction": self.retry_budget_fraction,
            "retry_budget_window": self.retry_budget_window,
        }
        if self.autoscaling is not None:
            d["autoscaling"] = vars(self.autoscaling)
        return d

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "DeploymentConfig":
        auto = d.pop("autoscaling", None)
        cfg = DeploymentConfig(**d)
        if auto is not None:
            cfg.autoscaling = AutoscalingConfig(**auto)
        return cfg


@dataclass
class _DeploymentState:
    """Live state for one deployment (ref DeploymentState)."""

    config: DeploymentConfig
    factory: Callable[[], Callable[[List[Any]], Sequence[Any]]]
    replicas: List[Replica] = field(default_factory=list)
    router: Optional[Router] = None
    policy: Optional[AutoscalingPolicy] = None
    restarts: int = 0
    next_replica_ordinal: int = 0
    unhealthy: bool = False  # restart budget spent; held until redeploy
    # replica_id -> its placement group (only when chips_per_replica > 0)
    pgroups: Dict[str, Any] = field(default_factory=dict)


class ServeController:
    """Singleton control loop owning deployments, routers, and scaling.

    ``store`` is the transactional home of every piece of mutable
    controller state (GCS move); ``catalog`` registers the live
    data-plane objects so a failover successor adopts them instead of
    cold-starting the world.
    """

    def __init__(
        self,
        kv: Optional[KVStore] = None,
        long_poll: Optional[LongPollHost] = None,
        control_interval_s: float = 0.5,
        placement: Optional[PlacementManager] = None,
        store: Optional[ControllerStore] = None,
        catalog: Optional[ReplicaCatalog] = None,
        fabric: Optional[ControlFabric] = None,
    ) -> None:
        self.kv = kv or KVStore()
        self.long_poll = long_poll or LongPollHost()
        self.placement = placement
        self.control_interval_s = control_interval_s
        self.store = store or InMemoryStore()
        self.catalog = catalog
        # The control-plane message seam: controller→router pushes
        # (long-poll notifies, digest publications) route through it so
        # the partition soak can cut the controller off from its data
        # plane. Unconfigured it is the zero-overhead passthrough.
        self.fabric = fabric if fabric is not None else default_fabric()
        # KV page fabric transfer plane (ISSUE 18): live-stream couriers
        # for zero-drop drains + the prefix push-replication tick. Rides
        # the same ControlFabric, so partition windows cut couriers too.
        self.kv_fabric = KVPageFabric(fabric=self.fabric)
        self._deployments: Dict[str, _DeploymentState] = {}
        self._factories: Dict[str, Callable] = {}
        self._lock = OrderedLock("controller", reentrant=True)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_checkpoint: Optional[str] = None
        # True once this controller was deposed (lease lost / stale-epoch
        # write rejected): it must stop acting as leader, permanently.
        self._fenced = False
        # Structured decision ring (scheduler/audit.py): deploys, scale
        # moves, heals, rollouts — surfaced per deployment in status().
        self.audit = AuditLog("serve")
        # The store's split-brain defense (store_unreachable self-
        # demotion) files into the SAME ring as fences and heals.
        if isinstance(self.store, ReplicatedStore) \
                and self.store.audit is None:
            self.store.audit = self.audit
        # Token-bucket admission + overload governor (serve/admission.py):
        # the proxies consult it pre-queue; this control loop feeds it
        # queue-depth/compliance signals each step, and its governor
        # transitions land in the SAME audit ring as heals and replans.
        self.admission = AdmissionController()
        self.admission.audit = self.audit
        # SLO observatory (serve/observatory.py — the SAME classes the
        # sim ticks on its virtual clock): burn-rate alerts graded from
        # the replicas' per-class queue counters, arrival forecasts
        # scored against the demand the control loop itself aggregates,
        # and sim-fidelity drift replayed every few steps. Demand is
        # observed as per-step enqueued-counter DELTAS — no hot-path
        # instrumentation; integer-second rate buckets make control-
        # tick granularity exact.
        self.rates = RateRegistry()
        self.observatory = SLOObservatory("serve")
        self.observatory.audit = self.audit
        self._observed_enqueued: Dict[str, float] = {}
        # Last-published quarantine fingerprint set per deployment: the
        # gossip tick fans out only on membership change (hit counters
        # mutate constantly and must not re-trigger pushes).
        self._quarantine_published: Dict[str, frozenset] = {}

    # --- deploy API (ref serve.run / deploy) ------------------------------
    def register_factory(
        self,
        name: str,
        factory: Callable[[], Callable[[List[Any]], Sequence[Any]]],
    ) -> None:
        """Factories are code, not state: after a controller restart the
        checkpoint restores *configs* and factories must be re-registered
        (the reference re-imports deployment code the same way)."""
        self._factories[name] = factory

    def _apply_router_policies(self, router: Router,
                               config: DeploymentConfig) -> None:
        """Re-derive the router's gray/hedge policy objects from the
        deployment config. These are data-plane POLICY, not store-owned
        state: a failover successor rebuilds them from the persisted
        config, so bare writes here are correct by construction."""
        from ray_dynamic_batching_tpu.serve.failover import (
            HedgeManager,
            HedgePolicy,
        )
        from ray_dynamic_batching_tpu.serve.grayhealth import GrayHealthPolicy
        from ray_dynamic_batching_tpu.serve.retrybudget import (
            RetryBudgetPolicy,
        )

        if config.gray_eject_after != router.gray.policy.eject_after:
            router.gray.policy = GrayHealthPolicy(
                eject_after=config.gray_eject_after
            )
        if config.hedge_interactive and router.hedge is None:
            router.hedge = HedgeManager(router, HedgePolicy())
        elif not config.hedge_interactive and router.hedge is not None:
            router.hedge.close()
            router.hedge = None
        budget = getattr(router, "retry_budget", None)
        if budget is not None and (
            budget.policy.fraction != config.retry_budget_fraction
            or budget.policy.window != config.retry_budget_window
        ):
            # Reprice keeps the ledger: recent first-attempt volume stays
            # honest across a knob change.
            budget.reconfigure(RetryBudgetPolicy(
                fraction=config.retry_budget_fraction,
                window=config.retry_budget_window,
            ))

    def deploy(
        self,
        config: DeploymentConfig,
        factory: Optional[Callable] = None,
        _recovered: bool = False,
    ) -> Router:
        """``_recovered`` marks the deploy that immediately follows a
        failover adoption: it re-binds the SAME config, so the restart
        budget / unhealthy verdict restored by ``_adopt`` must survive
        (only a genuinely fresh user deploy resets them)."""
        with self._lock:
            if factory is not None:
                self.register_factory(config.name, factory)
            if config.name not in self._factories:
                raise KeyError(f"no factory registered for {config.name!r}")
            from ray_dynamic_batching_tpu.serve.failover import HedgePolicy
            from ray_dynamic_batching_tpu.serve.grayhealth import (
                GrayHealthPolicy,
            )
            from ray_dynamic_batching_tpu.serve.retrybudget import (
                RetryBudgetPolicy,
            )

            state = self._deployments.get(config.name)
            with self.store.txn() as txn:
                if state is None:
                    router = (self.catalog.router(config.name)
                              if self.catalog is not None else None)
                    if router is None:
                        router = Router(
                            config.name,
                            gray_policy=GrayHealthPolicy(
                                eject_after=config.gray_eject_after
                            ),
                            hedge_policy=(HedgePolicy()
                                          if config.hedge_interactive
                                          else None),
                            retry_budget_policy=RetryBudgetPolicy(
                                fraction=config.retry_budget_fraction,
                                window=config.retry_budget_window,
                            ),
                        )
                    else:
                        # Adopted (failover): reprice its policies from
                        # THIS config — the live object may carry the old
                        # leader's knobs.
                        self._apply_router_policies(router, config)
                    state = _DeploymentState(
                        config=config,
                        factory=self._factories[config.name],
                        router=router,
                    )
                    # Breaker trip/recover events are control-plane
                    # decisions: they share the controller's audit ring
                    # with heals and scale moves (one timeline per
                    # deployment).
                    state.router.audit = self.audit
                    self._deployments[config.name] = state
                    if self.catalog is not None:
                        self.catalog.register_router(config.name,
                                                     state.router)
                else:
                    # Deliver user_config only when it CHANGED (including a
                    # change TO {} — clearing must reach the hook): the
                    # user's reconfigure can be expensive (weight reloads)
                    # and must not re-run because an unrelated knob moved.
                    prev_user = state.config.user_config
                    prev_version = state.config.version
                    state.config = config
                    # Gray/hedge knobs live on the ROUTER, not the
                    # replicas: a redeploy must reprice them here or
                    # status() reports the new config while the router
                    # keeps enforcing the old policy until the next
                    # controller restart.
                    self._apply_router_policies(state.router, config)
                    # A redeploy may carry NEW code: future replica starts
                    # (rollout replacements included) must build from the
                    # freshly registered factory, not the one captured at
                    # first deploy.
                    state.factory = self._factories[config.name]
                    if not _recovered:
                        # a fresh deploy resets the budget
                        state.restarts = 0
                        state.unhealthy = False
                    if config.version and config.version != prev_version:
                        # Version change -> ROLLING update: old-version
                        # replicas keep serving as-is until _reconcile
                        # retires them in bounded batches (pushing the new
                        # config into doomed replicas would run expensive
                        # reconfigures twice and blur which version
                        # produced a response).
                        logger.info(
                            "%s: rolling update %r -> %r over %d replicas",
                            config.name, prev_version, config.version,
                            len(state.replicas),
                        )
                    else:
                        # Push changed batching/concurrency knobs to
                        # RUNNING replicas (otherwise re-deploys silently
                        # produce a mixed-config replica set).
                        for r in state.replicas:
                            r.reconfigure(
                                max_batch_size=config.max_batch_size,
                                batch_wait_timeout_s=(
                                    config.batch_wait_timeout_s
                                ),
                                max_ongoing_requests=(
                                    config.max_ongoing_requests
                                ),
                                user_config=(
                                    config.user_config
                                    if config.user_config != prev_user
                                    else None
                                ),
                            )
                if config.autoscaling is not None:
                    state.policy = AutoscalingPolicy(
                        config.autoscaling, interval_s=self.control_interval_s
                    )
                else:
                    # autoscaling removed -> pin num_replicas
                    state.policy = None
                self._persist(txn, state)
            self.admission.configure(
                config.name,
                AdmissionPolicy(rate_rps=config.admission_rate_rps,
                                burst=config.admission_burst)
                if config.admission_rate_rps > 0 else None,
            )
            self.audit.record(
                "deploy",
                key=config.name,
                before={"replicas": len(state.replicas)},
                after={"replicas": config.num_replicas,
                       "version": config.version},
                diff={"target_replicas": config.num_replicas,
                      "version": config.version},
            )
            deferred = self._reconcile(state)
            self._checkpoint()
        for action in deferred:  # blocking stops run outside the lock
            action()
        return state.router

    def delete_deployment(self, name: str) -> None:
        with self._lock:
            with self.store.txn() as txn:
                state = self._deployments.pop(name, None)
                if state is None:
                    return
                txn.delete(STORE_CONFIG_KEY.format(deployment=name))
                txn.delete(STORE_REGISTRY_KEY.format(deployment=name))
                txn.delete(STORE_GOVERNOR_KEY.format(deployment=name))
                txn.delete(STORE_GRAY_KEY.format(deployment=name))
                victims = state.replicas
                state.replicas = []
            if self.catalog is not None:
                # A redeploy must never adopt this CLOSED router.
                self.catalog.unregister_router(name)
            self.admission.configure(name, None)
            self._publish(state)
            state.router.close()
            self._checkpoint()
            self.audit.record(
                "delete",
                key=name,
                before={"replicas": len(victims)},
                after={"replicas": 0},
                diff={"stopped": [r.replica_id for r in victims]},
            )
        for r in victims:  # blocking drains outside the lock
            r.stop()
            self._release_chips(state, r)

    def get_router(self, name: str) -> Router:
        with self._lock:
            return self._deployments[name].router

    def deployments(self) -> List[str]:
        with self._lock:
            return sorted(self._deployments)

    # --- durable mirror (store transactions) ------------------------------
    def _persist(self, txn, state: _DeploymentState) -> None:
        """Write one deployment's durable mirror into the open
        transaction. Canonical JSON + the txn's no-op elision keep the
        steady-state control loop from appending anything to the log."""
        cfg = state.config
        txn.put_json(STORE_CONFIG_KEY.format(deployment=cfg.name),
                     cfg.to_json())
        txn.put_json(STORE_REGISTRY_KEY.format(deployment=cfg.name), {
            "ids": [r.replica_id for r in state.replicas],
            "versions": {r.replica_id: getattr(r, "version", "")
                         for r in state.replicas},
            "ordinal": state.next_replica_ordinal,
            "restarts": state.restarts,
            "unhealthy": state.unhealthy,
            "reserved_chips": sorted(state.pgroups),
        })

    # --- state machine (ref deployment_state.py scale/heal) ---------------
    def _start_replica(self, state: _DeploymentState) -> Replica:
        cfg = state.config
        with self.store.txn() as txn:
            rid = f"{cfg.name}#{state.next_replica_ordinal}"
            state.next_replica_ordinal += 1
            # The ordinal is durable: a failover successor must never
            # mint a replica id the old leader already used.
            self._persist(txn, state)
        # Gang-acquire chips BEFORE building the replica (ref: the
        # deployment scheduler waits on the PG, then places the actor in it
        # — deployment_scheduler.py / gcs_placement_group_scheduler.cc).
        pg = None
        devices = None
        if cfg.chips_per_replica > 0:
            if self.placement is None:
                raise RuntimeError(
                    f"{cfg.name}: chips_per_replica={cfg.chips_per_replica} "
                    "requires a PlacementManager on the controller"
                )
            from ray_dynamic_batching_tpu.parallel.placement import Bundle

            pg = self.placement.create(
                [Bundle(chips=cfg.chips_per_replica)],
                strategy=cfg.placement_strategy,
            )
            devices = pg.bundle_devices(0)
        try:
            factory = state.factory
            if hasattr(factory, "make_replica"):
                # Deployment owns its replica class (e.g. serve.llm.LLMReplica
                # wrapping a decode engine) — mirror of the reference where
                # deployment target state carries the replica actor definition.
                if devices is not None:
                    replica = factory.make_replica(rid, cfg, devices=devices)
                else:
                    replica = factory.make_replica(rid, cfg)
            else:
                replica = Replica(
                    replica_id=rid,
                    deployment=cfg.name,
                    fn=factory(),
                    max_batch_size=cfg.max_batch_size,
                    batch_wait_timeout_s=cfg.batch_wait_timeout_s,
                    max_ongoing_requests=cfg.max_ongoing_requests,
                )
                replica.max_multiplexed_models = cfg.max_multiplexed_models
                if devices is not None:
                    replica.devices = devices
            if cfg.user_config:
                # Initial user_config applies BEFORE serving, for every
                # replica kind (ref: reconfigure runs before the replica
                # serves) — not just the plain-Replica branch.
                replica.reconfigure(user_config=cfg.user_config)
            replica.start()
        except Exception:
            if pg is not None:  # failed start must not leak reserved chips
                self.placement.remove(pg)
            raise
        if pg is not None:
            with self.store.txn() as txn:
                state.pgroups[rid] = pg
                self._persist(txn, state)
            if self.catalog is not None:
                # The reservation survives controller death WITH its
                # replica: a failover successor re-binds it in _adopt so
                # retiring the adopted replica still frees the chips.
                self.catalog.register_pgroup(rid, pg)
        # Stamp the config version the replica was BUILT from: the rollout
        # stage retires replicas whose stamp differs from the target.
        replica.version = cfg.version
        if self.catalog is not None:
            self.catalog.register_replica(rid, replica)
        logger.info(
            "started replica %s%s%s", rid,
            f" (version {cfg.version!r})" if cfg.version else "",
            f" on chips {[str(d) for d in devices]}" if devices else "",
        )
        return replica

    def _release_chips(self, state: _DeploymentState, replica: Replica) -> None:
        pg = state.pgroups.pop(replica.replica_id, None)
        if pg is not None and self.placement is not None:
            self.placement.remove(pg)
        if self.catalog is not None:
            self.catalog.unregister_replica(replica.replica_id)
            self.catalog.unregister_pgroup(replica.replica_id)

    def _redeliver(
        self,
        router: Router,
        requests: List[Any],
        victim_id: str,
        dead: bool = False,
    ) -> None:
        """Salvage a retired replica's queued requests through the
        failover path: deadline-budgeted re-dispatch to a different
        replica, shed accounting when hopeless (terminal rejection
        belongs to the failover layer, not the heal path). ``dead``
        marks a crashed/wedged victim (heal) vs a planned rollout."""
        router.requeue_drained(requests, victim_id, dead=dead)  # rdb-lint: disable=retry-amplification (heal-path salvage of a dead replica's queue — relocation of admitted work, not client-visible retry amplification)

    def _migrate_live_streams(
        self, victim: Replica, state: _DeploymentState,
    ) -> None:
        """Deferred pre-stop directive: migrate the victim's live decode
        streams to surviving replicas through the page fabric (zero-drop
        rolling update / scale-down). Runs OUTSIDE the controller lock —
        it polls the drain for seconds. Peers resolve HERE, at run time,
        so replacements started in the same reconcile pass are already
        in ``state.replicas``. Replica kinds without a fabric surface
        (batch replicas, slab engines) fall through to the stop()'s own
        drain window — exactly the pre-fabric behavior. The heal path
        never routes here: a dead engine cannot export its pages, so
        salvage/requeue remains its only honest option."""
        if not hasattr(victim, "live_stream_ids"):
            return
        peers = [r for r in state.replicas
                 if r is not victim and not getattr(r, "_stopped", False)]
        if not peers:
            return
        stats = self.kv_fabric.drain_streams(victim, peers, timeout_s=20.0)
        if stats["requested"] or stats["remaining"]:
            self.audit.record(
                "live_migration",
                key=state.config.name,
                observed=stats,
                diff={"migrated_from": victim.replica_id},
            )

    def _reconcile(
        self,
        state: _DeploymentState,
        deferred: Optional[List[Callable[[], None]]] = None,
    ) -> List[Callable[[], None]]:
        """Drive actual replica count to target; replace unhealthy.

        Collects deferred (blocking) stop actions into ``deferred`` (the
        caller's list when given) and returns it — callers run them AFTER
        releasing the controller lock, so a slow drain or a wedged
        callable can't freeze the whole control plane. Collecting into
        the CALLER'S list matters on the fencing path: a StaleEpochError
        from a mid-reconcile commit propagates, but the stop/release
        actions already collected must still run (their victims are
        already out of the routing set — leaking their threads and chips
        helps nobody, least of all the successor). The whole pass is one
        store transaction: the durable mirror commits exactly once per
        reconcile, and only when something changed."""
        cfg = state.config
        if deferred is None:
            deferred = []
        with self.store.txn() as txn:
            # Heal: replace dead replicas up to max_restarts
            # (ref gcs_actor_manager.cc:1361-1393 restart budget). A replica
            # the gray-health monitor EJECTED (sustained straggling through
            # its whole probation) rides the same path: replaced like a dead
            # one, so the planner reclaims the chip from gray failures too.
            alive: List[Replica] = []
            for r in state.replicas:
                ejected = state.router.gray.state(r.replica_id) == "ejected"
                if r.healthy() and not ejected:
                    alive.append(r)
                    continue
                logger.warning(
                    "replica %s %s; replacing", r.replica_id,
                    "gray-ejected (straggler)" if ejected else "unhealthy",
                )
                # Salvage queued work, then stop the victim INLINE (its
                # loop is dead or wedged, so the join is bounded) — the
                # replacement may land on the same chips, which must be
                # genuinely free: chip reservation released AND, for
                # engines, HBM buffers dropped (LLMReplica.stop releases
                # them once the loop has exited).
                salvaged = r.drain_queue()
                r.stop(timeout_s=2.0, drain=False)
                self._release_chips(state, r)
                replacement: Optional[Replica] = None
                if state.restarts < cfg.max_restarts:
                    state.restarts += 1
                    try:
                        replacement = self._start_replica(state)
                        alive.append(replacement)
                    except StaleEpochError:
                        # A fenced write means this controller was
                        # deposed: it must STOP mutating, not log-and-
                        # continue — re-raise past the broad handler so
                        # _on_fenced runs (the split-brain guard).
                        raise
                    except PlacementError as e:
                        # Transient chip shortage is not a crash: hand the
                        # restart back and let a later control step retry
                        # via the scale-up loop below.
                        state.restarts -= 1
                        logger.warning(
                            "%s: replacement blocked: %s", cfg.name, e
                        )
                    except Exception:  # noqa: BLE001 — a failing start must
                        # not abort the control step (deferred redeliveries
                        # of other replicas would be dropped); the burned
                        # restart counts, so a crash-looping factory still
                        # exhausts its budget.
                        logger.exception(
                            "%s: replacement start failed", cfg.name
                        )
                else:
                    state.unhealthy = True
                    logger.error(
                        "%s: restart budget (%d) exhausted; deployment "
                        "unhealthy until redeployed",
                        cfg.name, cfg.max_restarts,
                    )
                if salvaged:
                    deferred.append(
                        lambda reqs=salvaged, rt=state.router,
                        vid=r.replica_id: (
                            self._redeliver(rt, reqs, vid, dead=True)
                        )
                    )
                self.audit.record(
                    "heal",
                    key=cfg.name,
                    observed={"unhealthy": r.replica_id,
                              "gray_ejected": ejected,
                              "salvaged_requests": len(salvaged)},
                    diff={
                        "replaced": r.replica_id,
                        "replacement": (replacement.replica_id
                                        if replacement is not None else None),
                    },
                    note=("" if replacement is not None
                          else "restart budget exhausted or start failed"),
                )
            state.replicas = alive
            # Rolling update (ref deployment_state.py rollout): while
            # replicas with a DIFFERENT version stamp exist, retire them in
            # batches of at most
            # ceil(rolling_max_unavailable_fraction * target) — and only as
            # many as keep the serving set at or above target - batch, so
            # both versions serve through the rollout and unavailability
            # stays bounded. Retired replicas drain in the deferred stop
            # (graceful: in-flight work finishes); the scale-up loop below
            # starts their new-version replacements this same pass.
            if cfg.version and not state.unhealthy:
                outdated = [
                    r for r in state.replicas
                    if getattr(r, "version", "") != cfg.version
                ]
                if outdated:
                    batch = max(
                        1, math.ceil(
                            cfg.rolling_max_unavailable_fraction
                            * cfg.num_replicas
                        ),
                    )
                    floor = cfg.num_replicas - batch
                    can_stop = max(0, len(state.replicas) - floor)
                    for victim in outdated[: min(batch, can_stop)]:
                        state.replicas.remove(victim)
                        logger.info(
                            "rolling out replica %s (version %r -> %r)",
                            victim.replica_id,
                            getattr(victim, "version", ""), cfg.version,
                        )
                        self.audit.record(
                            "rolling_update",
                            key=cfg.name,
                            before={"version": getattr(victim, "version", "")},
                            after={"version": cfg.version},
                            diff={"retired": victim.replica_id},
                        )
                        victim._stopped = True  # stale handles stop assigning
                        # Same salvage discipline as the heal path: queued
                        # (unstarted) requests move to surviving/new replicas
                        # immediately instead of gambling on the victim's
                        # drain window; only the in-flight batch finishes on
                        # the victim, with a rollout-sized timeout (a busy
                        # LLM replica's batch can legitimately run tens of
                        # seconds — the default 5 s drain would reject it).
                        salvaged = victim.drain_queue()
                        if salvaged:
                            deferred.append(
                                lambda reqs=salvaged, rt=state.router,
                                vid=victim.replica_id: (
                                    self._redeliver(rt, reqs, vid)
                                )
                            )
                        # Migration directive BEFORE the stop: live
                        # streams move to the surviving set (peers
                        # resolved at run time, after this pass's
                        # scale-up started the replacements) — rolling
                        # updates are zero-drop by construction, the
                        # stop's drain window is the fallback.
                        deferred.append(
                            lambda v=victim, st=state: (
                                self._migrate_live_streams(v, st)
                            )
                        )
                        deferred.append(
                            lambda v=victim, st=state: (
                                v.stop(timeout_s=60.0),
                                self._release_chips(st, v),
                            )
                        )
            # Scale to target — but an exhausted restart budget stops the
            # crash-loop: no replacements until a fresh deploy() resets it
            # (ref gcs_actor_manager.cc:1361-1393 — actors stay DEAD once
            # max_restarts is spent).
            n_before_scale = len(state.replicas)
            while len(state.replicas) < cfg.num_replicas \
                    and not state.unhealthy:
                try:
                    state.replicas.append(self._start_replica(state))
                except StaleEpochError:
                    raise  # deposed: stop mutating (see heal path note)
                except PlacementError as e:
                    # Not enough chips: hold at the current count and retry
                    # on later control steps (ref: the PG stays pending).
                    logger.warning("%s: scale-up blocked: %s", cfg.name, e)
                    break
                except Exception:  # noqa: BLE001 — hold and retry next step
                    logger.exception("%s: replica start failed", cfg.name)
                    break
            while len(state.replicas) > cfg.num_replicas:
                victim = state.replicas.pop()  # newest first, ref compact
                victim._stopped = True  # stale handles stop assigning
                # Zero-drop shrink: same migration-before-stop directive
                # as the rolling update above.
                deferred.append(
                    lambda v=victim, st=state: (
                        self._migrate_live_streams(v, st)
                    )
                )
                deferred.append(
                    lambda v=victim, st=state: (
                        v.stop(),
                        self._release_chips(st, v),
                    )
                )
            if len(state.replicas) != n_before_scale:
                self.audit.record(
                    "scale",
                    key=cfg.name,
                    observed={"target": cfg.num_replicas},
                    before={"replicas": n_before_scale},
                    after={"replicas": len(state.replicas)},
                    diff={"delta": len(state.replicas) - n_before_scale},
                )
            # Publish only on membership change: every publish clears the
            # router's queue-len cache, so steady-state reconciles must be
            # quiet.
            if [r.replica_id for r in state.replicas] != [
                r.replica_id for r in state.router.replicas()
            ]:
                self._publish(state)  # routing stops before deferred drains
            self._persist(txn, state)
        return deferred

    def _publish(self, state: _DeploymentState) -> None:
        """Push the replica set to routers via long poll (ref long_poll).
        The in-process router object updates directly (it is the live
        data plane the catalog adopts across failovers); the long-poll
        NOTIFY — the out-of-process push edge — rides the fabric, so a
        partitioned observer simply keeps its last snapshot and catches
        up on heal (snapshot ids are monotone)."""
        state.router.update_replicas(state.replicas)
        self.fabric.cast(
            "controller.push", self.long_poll.notify_changed,
            REPLICA_SET_KEY.format(deployment=state.config.name),
            [r.replica_id for r in state.replicas],
            src="controller", dst="router",
        )

    # --- control loop -----------------------------------------------------
    def _observe_gray(self, state: "_DeploymentState") -> None:
        """Tick the deployment's gray-health monitor with per-replica
        recent-latency sketches (PR 8's RollingSketch — recency-bounded,
        so the consensus describes the replica NOW). The monitor grades
        only replicas with enough samples and enough graded peers; the
        state machine's hysteresis does the rest."""
        obs = {}
        for r in state.replicas:
            try:
                obs[r.replica_id] = r.latency_observation()
            except Exception:  # noqa: BLE001 — stats must not stop control
                continue
        if len(obs) >= 2:
            state.router.gray.tick(obs)

    def _observe_admission(self, state: "_DeploymentState") -> None:
        """Feed the overload governor this deployment's congestion
        signals: worst replica queue-fill fraction + worst recent SLO
        compliance. Hysteresis and the degrade/recover decision live in
        the AdmissionController; every transition is audited."""
        if self.admission.policy(state.config.name) is None:
            return
        depth_frac = 0.0
        compliance = 1.0
        for r in state.replicas:
            cap = max(1, getattr(r, "max_ongoing_requests", 1))
            try:
                depth_frac = max(depth_frac, r.queue_len() / cap)
                compliance = min(compliance, r.slo_compliance())
            except Exception:  # noqa: BLE001 — stats must not stop control
                continue
        self.admission.observe(state.config.name, depth_frac, compliance)

    def _observe_slo(
        self, state: "_DeploymentState"
    ) -> Tuple[Dict[str, Dict[str, float]], Dict[str, Any]]:
        """One deployment's observatory inputs for this step: the
        replicas' per-class queue counters summed (the SAME cumulative
        ``class_stats()`` slices the sim grades burn from), plus the
        merged per-hop latency sketches (queue.wait from the delay
        windows, engine.step from the service windows). Demand is
        derived here too — the enqueued-counter delta since the last
        step feeds the rate registry and the fidelity replay ring, so
        the hot path carries zero new instrumentation."""
        name = state.config.name
        counters: Dict[str, Dict[str, float]] = {}
        delay_views = []
        service_views = []
        for r in state.replicas:
            try:
                for qos, c in r.queue.class_stats().items():
                    agg = counters.setdefault(qos, {})
                    for k, v in c.items():
                        agg[k] = agg.get(k, 0.0) + v
                delay_views.append(r.queue.queue_delay_window.view())
                service_views.append(r.queue.service_window.view())
            except Exception:  # noqa: BLE001 — stats must not stop control
                continue
        enqueued = sum(c.get("enqueued", 0.0) for c in counters.values())
        delta = enqueued - self._observed_enqueued.get(name, 0.0)
        self._observed_enqueued[name] = enqueued
        if delta > 0:
            self.rates.record(name, int(delta))
            self.observatory.note_arrivals(name, int(delta))
        hops = {
            "queue.wait": QuantileSketch.merged(delay_views),
            "engine.step": QuantileSketch.merged(service_views),
        }
        return counters, hops

    def _publish_prefix_digests(self, state: "_DeploymentState") -> None:
        """Collect each replica's bounded prefix-page digest chains and
        push them to the router's digest directory (+ the long-poll
        channel, so out-of-process routers ride the same mechanism as
        replica-set changes). Cluster-wide prefix routing (ISSUE 11):
        the router scores candidates by longest matching digest chain
        before the pow-2 pick."""
        directory = getattr(state.router, "digests", None)
        if directory is None:
            return
        changed = False
        for r in state.replicas:
            fn = getattr(r, "prefix_digests", None)
            if fn is None:
                continue
            try:
                pub = fn()
            except Exception:  # noqa: BLE001 — stats must not stop control
                continue
            if not pub:
                continue
            try:
                # Digest pushes ride the fabric: a controller partitioned
                # from its routers leaves the directory on its LAST
                # published set (stale steering hints degrade hit rate,
                # never correctness — the replica-level cache still
                # validates) and the next reachable tick republishes.
                if self.fabric.call(
                    "controller.digest_push", directory.publish,
                    r.replica_id, pub["page_size"], pub["digests"],
                    src="controller", dst="router",
                ):
                    changed = True
                if pub.get("reloaded"):
                    # Spill round-trip fix: a reload moved an entry
                    # between that replica's tiers WITHOUT changing its
                    # advertised union, so replacement-expiry reports
                    # "unchanged" — force the long-poll push anyway or
                    # out-of-process routers never reconverge on where
                    # the entry now lives.
                    changed = True
            except FabricUnreachable:
                continue
        if changed:
            self.fabric.cast(
                "controller.push", self.long_poll.notify_changed,
                PREFIX_DIGEST_KEY.format(deployment=state.config.name),
                directory.snapshot(),
                src="controller", dst="router",
            )

    def _publish_quarantine(self, state: "_DeploymentState") -> None:
        """Gossip the deployment's query-of-death fingerprints the same
        way prefix digests travel: durable mirror first (a failover
        successor keeps fencing known poison), then a long-poll push so
        every out-of-process front door merges the set and rejects
        repeats at admission. Fans out only when MEMBERSHIP changed —
        hit counters mutate on every front-door block and must not
        re-trigger pushes. Lost pushes are safe: a missed entry costs
        one more bisection on its next appearance, never correctness."""
        name = state.config.name
        registry = getattr(state.router, "quarantine", None)
        if registry is None:
            return
        snap = registry.snapshot()
        fps = frozenset(snap)
        if fps == self._quarantine_published.get(name, frozenset()):
            return
        with self.store.txn() as txn:
            txn.put_json(STORE_QUARANTINE_KEY.format(deployment=name),
                         snap)
        if not self.fabric.cast(
            "controller.push", self.long_poll.notify_changed,
            QUARANTINE_KEY.format(deployment=name), snap,
            src="controller", dst="router",
        ):
            return  # dropped: republished on the next tick
        self._quarantine_published[name] = fps

    def _renew_leadership(self) -> bool:
        """Heartbeat the store lease. A lapsed-but-UNCLAIMED lease (a
        long reconcile outran the renew cadence, nobody took over) is
        re-acquired by the same owner — same epoch, no fence, the
        control plane must not self-destruct with no successor. Only a
        lease another owner actually TOOK fences this controller
        permanently."""
        if self._fenced:
            return False
        if isinstance(self.store, ReplicatedStore):
            try:
                if not self.store.renew():
                    if self.store.acquire_leadership() is None:
                        self._on_fenced(None)
                        return False
                    logger.warning(
                        "lease lapsed unclaimed; re-acquired at epoch %d",
                        self.store.epoch,
                    )
            except FabricUnreachable as e:
                # Partitioned from the lease or the log: NOT fenced —
                # nobody provably took over. Skip the step and retry
                # next tick; on heal the same owner re-acquires (same
                # epoch) if no standby claimed the lapsed lease, or the
                # acquire returns None and fences us properly.
                logger.warning("leadership heartbeat unreachable "
                               "(%s); skipping control step", e)
                return False
        return True

    def _on_fenced(self, exc: Optional[StaleEpochError]) -> None:
        self._fenced = True
        self._stop.set()
        epoch = getattr(self.store, "epoch", 0)
        fence = getattr(getattr(self.store, "log", None), "fence_epoch",
                        epoch)
        logger.error(
            "controller fenced at epoch %d (log fence %d): a standby took "
            "over; this instance stops leading%s",
            epoch, fence, f" ({exc})" if exc is not None else "",
        )
        self.audit.record(
            "store_fenced",
            observed={"epoch": epoch, "fence": fence},
            note="lease lost or stale-epoch write rejected; control loop "
                 "stopped",
        )

    def _control_step(self) -> None:
        if not self._renew_leadership():
            return
        # Deferred stop/release actions run even if the step is fenced
        # mid-way: their victims are already unpublished and (where a
        # txn committed) out of the durable registry, so skipping them
        # would leak replica threads, HBM, and chip reservations that no
        # successor will ever reclaim.
        deferred: List[Callable[[], None]] = []
        try:
            with self._lock:
                slo_counters: Dict[str, Dict[str, Dict[str, float]]] = {}
                slo_hops: Dict[str, Dict[str, Any]] = {}
                for state in list(self._deployments.values()):
                    self._observe_gray(state)
                    self._observe_admission(state)
                    try:
                        counters, hops = self._observe_slo(state)
                        if counters:
                            slo_counters[state.config.name] = counters
                        slo_hops[state.config.name] = hops
                    except Exception:  # noqa: BLE001 — stats must not
                        pass           # stop control
                    self._publish_prefix_digests(state)
                    self._publish_quarantine(state)
                    # Governor -> budget coupling: while this deployment
                    # is congested (first-attempt attainment under
                    # floor), its retry/hedge budget is held at zero so
                    # recovery is monotone — amplification stops first.
                    budget = getattr(state.router, "retry_budget", None)
                    if budget is not None:
                        budget.set_congested(
                            self.admission.congested(state.config.name)
                        )
                    try:
                        # Prefix push-replication tick: hot entries move
                        # toward least-loaded peers ahead of demand.
                        # Only the directives are enqueued here (cheap);
                        # parcel delivery happens on the engines'
                        # threads at their next service points.
                        self.kv_fabric.push_hot_prefixes(
                            state.config.name, state.replicas,
                            getattr(state.router, "digests", None),
                        )
                    except Exception:  # noqa: BLE001 — pushes are
                        pass           # optimizations, never control-fatal
                    if state.policy is not None:
                        metrics = state.router.demand_metrics()
                        target = state.policy.step(
                            metrics["total_ongoing"], len(state.replicas)
                        )
                        if target is not None \
                                and target != state.config.num_replicas:
                            logger.info(
                                "%s: autoscale %d -> %d (ongoing=%.0f)",
                                state.config.name,
                                state.config.num_replicas,
                                target, metrics["total_ongoing"],
                            )
                            with self.store.txn() as txn:
                                state.config.num_replicas = target
                                self._persist(txn, state)
                    with self.store.txn() as txn:
                        # Durable governor/gray mirrors (elided unless a
                        # state actually changed). The governor mirror is
                        # READ BACK by recover(): a failover successor
                        # keeps enforcing the degraded-mode contract
                        # instead of re-admitting the flood. The gray
                        # mirror is observability — live verdicts ride
                        # the ADOPTED router's monitor object; this is
                        # the durable record of what was declared.
                        txn.put_json(
                            STORE_GOVERNOR_KEY.format(
                                deployment=state.config.name
                            ),
                            {"state": ("degraded" if self.admission.degraded(
                                state.config.name) else "normal"),
                             "congested": self.admission.congested(
                                state.config.name)},
                        )
                        txn.put_json(
                            STORE_GRAY_KEY.format(
                                deployment=state.config.name
                            ),
                            state.router.gray.states(),
                        )
                    try:
                        self._reconcile(state, deferred)
                    except StaleEpochError:
                        # The fence outranks per-deployment isolation: a
                        # deposed leader must stop the WHOLE step, not
                        # shrug one deployment off and mutate the next —
                        # re-raise to the fencing handler below.
                        raise
                    except Exception:  # noqa: BLE001 — one deployment's
                        # failure must not drop other deployments' deferred
                        # actions
                        logger.exception(
                            "%s: reconcile failed", state.config.name
                        )
                try:
                    # One observatory tick per control step — the same
                    # cumulative counters + hop sketches the sim twin
                    # feeds its instance of the SAME classes.
                    self.observatory.tick(slo_counters, self.rates,
                                          slo_hops)
                except Exception:  # noqa: BLE001 — observability must
                    # not stop control
                    logger.exception("observatory tick failed")
                self._checkpoint()
        except StaleEpochError as e:
            self._on_fenced(e)  # falls through: deferred still runs
        except FabricUnreachable as e:
            # A partition opened MID-step (appends unreachable). The
            # store's own bounded-window defense decides demotion; the
            # controller just stops mutating this tick and retries — on
            # a healed partition it resumes, on a lost lease the next
            # _renew_leadership fences it. Deferred stops still run:
            # their victims are already out of the routing set.
            logger.warning("control step partitioned from the store "
                           "(%s); retrying next tick", e)
        for action in deferred:  # blocking stops run outside the lock
            action()

    def _loop(self) -> None:
        while not self._stop.wait(self.control_interval_s):
            try:
                self._control_step()
            except Exception:  # noqa: BLE001
                logger.exception("control step failed")

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="serve-controller", daemon=True
        )
        self._thread.start()

    def crash(self) -> None:
        """Chaos/test harness: kill the control loop WITHOUT draining the
        data plane — the in-process analogue of controller death.
        Replicas, routers and in-flight requests keep running; the lease
        simply stops being renewed, so a standby (sharing the replicated
        store's log + lease) takes over when it lapses."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        with self._lock:
            victims: List[Tuple[_DeploymentState, Replica]] = []
            try:
                with self.store.txn() as txn:
                    for state in self._deployments.values():
                        victims.extend((state, r) for r in state.replicas)
                        state.replicas = []
                        state.router.close()
                        self._persist(txn, state)
            except StaleEpochError:
                # A deposed controller still tears down its local
                # references; the durable mirror belongs to the NEW
                # leader now (its registry is the truth).
                logger.warning(
                    "shutdown on a deposed controller: durable mirror "
                    "left to the current leader"
                )
        for state, r in victims:
            r.stop()
            self._release_chips(state, r)

    # --- checkpoint / recovery (ref controller.py:545, app_state:1096) ----
    def _checkpoint(self) -> None:
        # Snapshot configs under the (reentrant) lock: an API-thread
        # deploy() resizing _deployments mid-walk raises "dictionary
        # changed size during iteration" in this comprehension — the
        # PR-8 registry race on the control plane.
        with self._lock:
            configs = {
                name: state.config.to_json()
                for name, state in self._deployments.items()
            }
        payload = json.dumps(configs, sort_keys=True)
        # Checkpoint-on-change: steady-state control steps must not rewrite
        # the KV file twice a second. (Legacy mirror — the store's
        # per-deployment keys are the authoritative durable state now;
        # this kv blob keeps pre-store restart flows working.)
        if payload != self._last_checkpoint:
            self.kv.put(CHECKPOINT_KEY, payload)
            self._last_checkpoint = payload

    def _adopt(self, name: str, cfg: DeploymentConfig) -> None:
        """Failover adoption: re-bind the live router and the surviving
        replicas recorded in the store instead of cold-starting the
        world. Only replicas recorded but missing (or unhealthy) get
        restarted — by the deploy/reconcile pass that follows."""
        registry = self.store.get_json(
            STORE_REGISTRY_KEY.format(deployment=name)
        ) or {}
        router = self.catalog.router(name) if self.catalog else None
        if router is None:
            return  # nothing live to adopt: deploy() cold-starts
        with self._lock:
            with self.store.txn() as txn:
                state = _DeploymentState(
                    config=cfg, factory=self._factories[name], router=router,
                )
                state.next_replica_ordinal = int(registry.get("ordinal", 0))
                # The health ledger survives the failover: a deployment
                # the old leader declared unhealthy (restart budget
                # spent) must NOT resume crash-looping on the successor
                # — "actors stay DEAD once max_restarts is spent" holds
                # across leaders.
                state.restarts = int(registry.get("restarts", 0))
                state.unhealthy = bool(registry.get("unhealthy", False))
                adopted: List[Replica] = []
                for rid in registry.get("ids", []):
                    r = self.catalog.replica(rid)
                    if r is None:
                        continue  # died with the old leader: reconcile
                        # restarts it from the registry count
                    # Adopt healthy AND unhealthy survivors: the heal
                    # pass retires unhealthy ones through its normal
                    # salvage/stop/release path (dropping them here
                    # would orphan their queues and chip reservations).
                    adopted.append(r)
                    pg = self.catalog.pgroup(rid)
                    if pg is not None:
                        state.pgroups[rid] = pg
                state.replicas = adopted
                state.router.audit = self.audit
                self._deployments[name] = state
                self._persist(txn, state)
        if adopted:
            self.audit.record(
                "failover_adopt",
                key=name,
                observed={"epoch": getattr(self.store, "epoch", 0)},
                diff={"adopted": [r.replica_id for r in adopted]},
                note="live data plane re-bound after controller failover",
            )

    def recover(self) -> List[str]:
        """Restore deployments from the store (factories must already be
        re-registered); falls back to the legacy kv checkpoint when the
        store is empty. With a catalog, live replicas/routers recorded in
        the store are ADOPTED — a controller failover re-binds the
        running data plane instead of restarting it. Returns recovered
        deployment names."""
        if isinstance(self.store, ReplicatedStore):
            self.store.catch_up()
        prefix = "serve:deployments/"
        names = sorted({
            k[len(prefix):].split("/")[0]
            for k in self.store.keys(prefix)
            if k.endswith("/config")
        })
        recovered = []
        if names:
            for name in names:
                if name not in self._factories:
                    logger.warning(
                        "stored deployment %r has no factory; skipping", name
                    )
                    continue
                cfg = DeploymentConfig.from_json(self.store.get_json(
                    STORE_CONFIG_KEY.format(deployment=name)
                ))
                adopted = False
                with self._lock:
                    absent = self.catalog is not None and \
                        name not in self._deployments
                if absent:
                    self._adopt(name, cfg)
                    with self._lock:
                        adopted = name in self._deployments
                self.deploy(cfg, _recovered=adopted)
                governor = self.store.get_json(
                    STORE_GOVERNOR_KEY.format(deployment=name)
                )
                if governor is not None:
                    # Keep enforcing the old leader's degraded-mode
                    # declaration; recovery still exits through the
                    # normal hysteresis once the flood actually ebbs.
                    # `congested` rides the same mirror (absent in
                    # pre-budget mirrors -> None leaves it untouched);
                    # the first control step pushes it back into the
                    # router's retry budget.
                    self.admission.force_state(
                        name, governor.get("state") == "degraded",
                        congested=governor.get("congested"),
                    )
                quarantined = self.store.get_json(
                    STORE_QUARANTINE_KEY.format(deployment=name)
                )
                if quarantined:
                    # Known queries of death stay fenced across the
                    # failover: merge the durable mirror into the adopted
                    # router's registry before traffic resumes.
                    with self._lock:
                        st = self._deployments.get(name)
                    if st is not None and getattr(
                            st.router, "quarantine", None) is not None:
                        st.router.quarantine.merge(quarantined)
                recovered.append(name)
            return recovered
        raw = self.kv.get(CHECKPOINT_KEY)
        if raw is None:
            return []
        for name, cfg_json in json.loads(raw).items():
            if name not in self._factories:
                logger.warning(
                    "checkpointed deployment %r has no factory; skipping", name
                )
                continue
            self.deploy(DeploymentConfig.from_json(cfg_json))
            recovered.append(name)
        return recovered

    def store_status(self) -> Dict[str, Any]:
        """The replicated-store view: version watermark, leadership epoch,
        fencing. Separate from the by-name deployment map in status()
        so dashboard consumers never see a phantom deployment."""
        out: Dict[str, Any] = {
            "kind": type(self.store).__name__,
            "version": self.store.version,
            "fenced": self._fenced,
        }
        if isinstance(self.store, ReplicatedStore):
            out.update(
                epoch=self.store.epoch,
                leader=self.store.is_leader(),
                log_records=len(self.store.log),
                rejected_appends=self.store.log.rejected_appends,
            )
        return out

    def status(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                name: {
                    "target_replicas": state.config.num_replicas,
                    "running_replicas": len(state.replicas),
                    "replicas": {
                        r.replica_id: r.stats() for r in state.replicas
                    },
                    "restarts": state.restarts,
                    "healthy": not state.unhealthy,
                    # Per-replica circuit-breaker state + the failover
                    # layer's retry/shed accounting (serve/failover.py) —
                    # the observable half of request-level fault tolerance.
                    "breakers": state.router.breaker_states(),
                    "failover": state.router.failover.stats(),
                    # Gray-health verdicts + hedge accounting (ISSUE 9):
                    # the straggler-defense half of fault tolerance.
                    "gray": state.router.gray.snapshot(),
                    "hedge": (state.router.hedge.stats()
                              if state.router.hedge is not None else None),
                    # Anti-amplification budget + query-of-death fence
                    # (ISSUE 19): the metastable-failure defense pair.
                    "retry_budget": state.router.retry_budget.stats(),
                    "quarantine": state.router.quarantine.stats(),
                    # Admission governor state (serve/admission.py):
                    # normal vs degraded + whether a policy is installed.
                    "admission": self.admission.snapshot(name),
                    # SLO observatory (serve/observatory.py): burn-rate
                    # alert states/transitions filtered to this
                    # deployment, plus forecast-error and fidelity-drift
                    # instruments (per-model — shared across the app).
                    "observatory": self.observatory.snapshot(key=name),
                    # Per-version replica counts: mid-rollout both the old
                    # and the new version appear here (ref deployment_state
                    # rollout status).
                    "target_version": state.config.version,
                    "versions": dict(collections.Counter(
                        getattr(r, "version", "") for r in state.replicas
                    )),
                    # Recent control-plane decisions about THIS deployment
                    # (deploys, scale moves, heals, rollouts) from the
                    # structured audit ring — filtered BEFORE slicing so a
                    # busy co-deployed app cannot evict this one's view.
                    "audit": self.audit.to_dicts(key=name, last=10),
                }
                for name, state in self._deployments.items()
            }
        return out

    def resources(self) -> Dict[str, Any]:
        """Cluster resource snapshot (separate from the by-name deployment
        map so state/dashboard consumers never see a phantom deployment)."""
        if self.placement is None:
            return {"nodes": {}, "reservations": []}
        return self.placement.resource_view()
