"""Long-poll push channel: controller → routers/handles.

Re-creates Ray Serve's long-poll mechanism
(``python/ray/serve/_private/long_poll.py``): the host keeps a
``(snapshot_id, object)`` per key; ``listen_for_change`` blocks until any
listened key's snapshot advances past the id the client last saw (ref
``:177`` host, ``:242`` blocking wait, ``:64`` client re-arm loop). Config
and replica-set changes reach the data plane through this channel, never via
per-request control traffic (SURVEY.md §3.5 note).

In-process design: a condition variable replaces the RPC long poll; the
client is a daemon thread re-arming the listen, same contract.

Partition seam (ISSUE 12): the client's listen — the router/handle →
controller edge — routes through the control fabric
(``long_poll.listen``). A partitioned listen raises
:class:`~ray_dynamic_batching_tpu.serve.fabric.FabricUnreachable`; the
client treats it exactly like a timed-out poll and re-arms, so a router
cut off from the controller keeps serving its LAST pushed state (stale
but consistent — the reference's long-poll clients behave the same) and
reconverges on heal because snapshot ids are monotone: every re-armed
listen asks for "anything newer than what I have", which makes missed
pushes self-healing and duplicated pushes no-ops.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Tuple

from ray_dynamic_batching_tpu.serve.fabric import (
    ControlFabric,
    FabricUnreachable,
    default_fabric,
)
from ray_dynamic_batching_tpu.utils.concurrency import assert_owner
from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("long_poll")


class LongPollHost:
    """Holds latest (snapshot_id, value) per key; wakes blocked listeners."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._snapshots: Dict[str, Tuple[int, Any]] = {}
        self._next_id = 1

    def notify_changed(self, key: str, value: Any) -> int:
        """Publish a new value for ``key``; returns its snapshot id."""
        with self._cond:
            sid = self._next_id
            self._next_id += 1
            self._snapshots[key] = (sid, value)
            self._cond.notify_all()
            return sid

    def listen_for_change(
        self,
        keys_to_ids: Dict[str, int],
        timeout_s: Optional[float] = None,
    ) -> Dict[str, Tuple[int, Any]]:
        """Block until any listened key's snapshot id exceeds the given id;
        returns {key: (snapshot_id, value)} for every advanced key (empty on
        timeout — the client simply re-arms, ref long_poll.py:242)."""
        with self._cond:
            out = self._updates_locked(keys_to_ids)
            if out:
                return out
            self._cond.wait(timeout_s)
            return self._updates_locked(keys_to_ids)

    def _updates_locked(
        self, keys_to_ids: Dict[str, int]
    ) -> Dict[str, Tuple[int, Any]]:
        assert_owner(self._cond)  # callers hold it (listen_for_change)
        return {
            k: snap
            for k, last_id in keys_to_ids.items()
            if (snap := self._snapshots.get(k)) is not None
            and snap[0] > last_id
        }

    def snapshot_ids(self) -> Dict[str, int]:
        with self._lock:
            return {k: sid for k, (sid, _) in self._snapshots.items()}


class LongPollClient:
    """Daemon thread that re-arms listens and fires callbacks on change
    (ref LongPollClient, long_poll.py:64)."""

    def __init__(
        self,
        host: LongPollHost,
        callbacks: Dict[str, Callable[[Any], None]],
        poll_timeout_s: float = 1.0,
        fabric: Optional[ControlFabric] = None,
        node: str = "router",
    ) -> None:
        self.host = host
        self.callbacks = dict(callbacks)
        self.poll_timeout_s = poll_timeout_s
        self.fabric = fabric if fabric is not None else default_fabric()
        self.node = node
        self.unreachable_polls = 0
        self._ids: Dict[str, int] = {k: -1 for k in callbacks}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="long-poll-client", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                updates = self.fabric.call(
                    "long_poll.listen", self.host.listen_for_change,
                    dict(self._ids), timeout_s=self.poll_timeout_s,
                    src=self.node, dst="controller",
                )
                for key, (sid, value) in updates.items():
                    self._ids[key] = sid
                    try:
                        self.callbacks[key](value)
                    except Exception:  # noqa: BLE001 — bad callback must not kill poller
                        logger.exception("long-poll callback for %r failed", key)
            except FabricUnreachable:
                # Partitioned from the controller: behave like a timeout
                # — keep last-known state, back off one window, re-arm.
                # Snapshot ids are monotone, so the first post-heal
                # listen returns everything missed in one response.
                self.unreachable_polls += 1
                self._stop.wait(self.poll_timeout_s)
            except Exception:  # noqa: BLE001
                logger.exception("long-poll listen failed")
                self._stop.wait(self.poll_timeout_s)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)
