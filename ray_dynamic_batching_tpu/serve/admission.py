"""Token-bucket admission control + the overload governor.

Shepherd's (NSDI '23) serving-layer lesson applied to this stack: overload
protection belongs AHEAD of the queue. Both proxies consult a
per-(deployment, tenant, qos_class) token bucket before any work is routed
or queued; a reject costs the client one round trip and a computed
``Retry-After`` (the bucket's refill time) instead of a queue slot, a
batch slot, and a deadline-doomed wait. The planner then only ever plans
for load the system actually accepted.

Two layers:

- :class:`TokenBucket` — classic refill-on-read bucket, clock-injected so
  the simulator reuses it verbatim at virtual time (deterministic).
- :class:`AdmissionController` — policy table + bucket registry + the
  **overload governor**: a per-deployment ``normal <-> degraded`` state
  machine fed queue-depth / SLO-compliance signals by the control plane
  (``ServeController._control_step`` live, the monitor tick in sim). In
  the degraded state each class's bucket rate is multiplied by its
  ``degraded_class_fractions`` entry — best-effort throttles to a trickle
  while interactive keeps its full rate — so overload lands on the tier
  that contracted for it. Transitions have hysteresis BOTH ways (enter on
  high depth or low compliance, exit only when both recover) and every
  transition is recorded in the scheduler audit ring.

Rejections raise (or return) :class:`AdmissionRejected`, which the shared
error table (``serve/failover.reject_disposition``) maps to HTTP 429 /
gRPC RESOURCE_EXHAUSTED with the computed ``Retry-After``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from ray_dynamic_batching_tpu.engine.request import (
    DEFAULT_QOS_CLASS,
    DEFAULT_TENANT,
)
from ray_dynamic_batching_tpu.utils.logging import get_logger
from ray_dynamic_batching_tpu.utils import metrics as m

logger = get_logger("admission")

ADMISSION_TOTAL = m.Counter(
    "rdb_admission_total",
    "Admission decisions (outcome: admit | reject)",
    tag_keys=("deployment", "tenant", "qos", "outcome"),
    bounded_tags={"tenant": m.DEFAULT_TENANT_TOP_K},
)
GOVERNOR_STATE = m.Gauge(
    "rdb_admission_governor_degraded",
    "1 while the overload governor holds the deployment degraded",
    tag_keys=("deployment",),
)


class AdmissionRejected(Exception):
    """The request was turned away BEFORE any work was queued (bucket
    empty). Carries the computed retry hint; client-visible as
    429 + Retry-After (gRPC RESOURCE_EXHAUSTED) — capacity economics,
    never a server fault."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class TokenBucket:
    """Refill-on-read token bucket. ``clock`` returns monotonic seconds —
    the simulator injects its virtual clock, so admission decisions are
    byte-deterministic under replay. Not thread-safe by itself; the
    controller serializes access."""

    def __init__(self, rate_rps: float, burst: float,
                 clock=time.monotonic) -> None:
        self.rate_rps = float(rate_rps)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._last = clock()

    def _refill(self) -> None:
        now = self._clock()
        self._tokens = min(
            self.burst, self._tokens + (now - self._last) * self.rate_rps
        )
        self._last = now

    def set_rate(self, rate_rps: float) -> None:
        """Governor rate flips refill at the OLD rate first, so tokens
        earned before the transition are kept, not re-priced."""
        self._refill()
        self.rate_rps = float(rate_rps)

    def try_acquire(self, n: float = 1.0) -> Tuple[bool, float]:
        """(admitted, retry_after_s). The retry hint is the exact refill
        time for the missing tokens — a well-behaved client that waits it
        out is admitted on its next attempt (barring new contention)."""
        self._refill()
        if self._tokens >= n:
            self._tokens -= n
            return True, 0.0
        if self.rate_rps <= 0.0:
            return False, 60.0  # administratively closed: poll slowly
        return False, (n - self._tokens) / self.rate_rps


@dataclass
class AdmissionPolicy:
    """Per-deployment admission contract.

    ``rate_rps``/``burst`` size each (tenant, class) bucket in the normal
    state; ``degraded_class_fractions`` multiply the per-class rate while
    the governor holds the deployment degraded. Hysteresis: degrade when
    queue depth fraction >= ``depth_high`` OR SLO compliance <=
    ``compliance_low``; recover only when depth <= ``depth_low`` AND
    compliance >= ``compliance_high``."""

    rate_rps: float
    burst: float = 0.0                      # 0 -> defaults to rate_rps
    # Distinct tenants that get their OWN buckets (first-come); overflow
    # tenants share one ``__other__`` bucket. Tenant is unauthenticated
    # client input: without a cap, rotating the header would both grow
    # the bucket table without bound AND mint a fresh burst of tokens
    # per made-up tenant — an admission bypass. Same top-K discipline as
    # the metrics layer's bounded tenant labels.
    max_tenants: int = 64
    degraded_class_fractions: Dict[str, float] = field(
        default_factory=lambda: {
            "interactive": 1.0, "standard": 0.5, "best_effort": 0.1,
        }
    )
    depth_high: float = 0.5
    depth_low: float = 0.1
    compliance_low: float = 0.80
    compliance_high: float = 0.95
    # --- congested state (metastability defense, serve/retrybudget.py) ---
    # While first-attempt SLO compliance sits at/below this floor the
    # deployment is CONGESTED: the retry/hedge budget is held at zero —
    # every re-dispatch would displace a first attempt that already
    # cannot make its deadline, which is how retries hold a recovered
    # cluster in collapse (metastable failure). 0.0 disables the state.
    congested_floor: float = 0.0
    # Exit bar (hysteresis): compliance must recover to at least this
    # before the budget is restored; 0.0 defaults to compliance_high.
    congested_exit: float = 0.0

    def __post_init__(self) -> None:
        if self.burst <= 0.0:
            self.burst = self.rate_rps
        if self.depth_low > self.depth_high:
            raise ValueError("depth_low must be <= depth_high (hysteresis)")
        if self.compliance_high < self.compliance_low:
            raise ValueError(
                "compliance_high must be >= compliance_low (hysteresis)"
            )
        if self.congested_floor > 0.0:
            if self.congested_exit <= 0.0:
                self.congested_exit = self.compliance_high
            if self.congested_exit < self.congested_floor:
                raise ValueError(
                    "congested_exit must be >= congested_floor (hysteresis)"
                )

    def class_rate(self, qos: str, degraded: bool) -> float:
        if not degraded:
            return self.rate_rps
        return self.rate_rps * self.degraded_class_fractions.get(qos, 1.0)


class AdmissionController:
    """Policy table + bucket registry + overload governor for a serving
    domain. One instance per controller (live) or per simulation run."""

    def __init__(self, clock=time.monotonic) -> None:
        self._clock = clock
        self._lock = threading.Lock()
        self._policies: Dict[str, AdmissionPolicy] = {}
        self._degraded: Dict[str, bool] = {}
        # Congested: first-attempt attainment under floor — the retry
        # budget is zeroed until it recovers (ISSUE 19 metastability
        # defense). Orthogonal to degraded: a deployment can shed
        # best-effort (degraded) without being so far gone that
        # re-dispatches must stop (congested).
        self._congested: Dict[str, bool] = {}
        # (deployment, tenant, qos) -> bucket; tenants over the policy's
        # top-K collapse into one shared overflow bucket (see
        # AdmissionPolicy.max_tenants).
        self._buckets: Dict[Tuple[str, str, str], TokenBucket] = {}
        self._tenants_seen: Dict[str, set] = {}
        # Optional decision ring (scheduler/audit.AuditLog): every governor
        # transition is a control-plane decision and must land next to
        # replans, heals and breaker trips.
        self.audit = None
        self.transitions = 0
        self.admitted = 0
        self.rejected = 0
        # Rejects per deployment since its last observe() tick: while the
        # governor holds a deployment degraded, ongoing rejects mean the
        # flood is still arriving — recovery on depth/compliance alone
        # would flap (degrade sheds the load, the queue looks healthy one
        # tick later, recovery readmits the flood, repeat).
        self._rejects_since_observe: Dict[str, int] = {}

    # --- configuration ----------------------------------------------------
    def configure(self, deployment: str,
                  policy: Optional[AdmissionPolicy]) -> None:
        """Install (or with ``None`` remove) a deployment's policy.
        Unconfigured deployments admit everything."""
        with self._lock:
            if policy is None:
                self._policies.pop(deployment, None)
                self._degraded.pop(deployment, None)
                self._congested.pop(deployment, None)
                self._tenants_seen.pop(deployment, None)
                for key in [k for k in self._buckets if k[0] == deployment]:
                    del self._buckets[key]
                return
            previous = self._policies.get(deployment)
            self._policies[deployment] = policy
            self._degraded.setdefault(deployment, False)
            if previous is not None and previous != policy:
                # A CHANGED contract must bind existing buckets too:
                # admit() lazily re-derives rate_rps, but burst and the
                # tenant top-K are frozen into the bucket/seen state —
                # drop them so the new knobs apply from the next admit
                # (an unchanged redeploy keeps its budgets untouched).
                self._tenants_seen.pop(deployment, None)
                for key in [k for k in self._buckets if k[0] == deployment]:
                    del self._buckets[key]

    def policy(self, deployment: str) -> Optional[AdmissionPolicy]:
        with self._lock:
            return self._policies.get(deployment)

    def degraded(self, deployment: str) -> bool:
        with self._lock:
            return self._degraded.get(deployment, False)

    def congested(self, deployment: str) -> bool:
        with self._lock:
            return self._congested.get(deployment, False)

    def force_state(self, deployment: str, degraded: bool,
                    congested: Optional[bool] = None) -> None:
        """Restore the governor state from a durable mirror (controller
        failover: the successor's fresh controller must keep enforcing
        the degraded-mode contract the old leader declared, not re-admit
        the flood until its own hysteresis re-detects it). Bucket rates
        re-derive lazily at the next admit, as with observe().
        ``congested=None`` leaves the congested verdict untouched (old
        mirrors predate the key)."""
        with self._lock:
            if deployment in self._policies:
                self._degraded[deployment] = bool(degraded)
                if congested is not None:
                    self._congested[deployment] = bool(congested)

    # --- the admission decision -------------------------------------------
    def admit(
        self,
        deployment: str,
        tenant: str = DEFAULT_TENANT,
        qos_class: str = DEFAULT_QOS_CLASS,
    ) -> Tuple[bool, float]:
        """(admitted, retry_after_s) — consulted by the proxies BEFORE any
        routing or queueing."""
        with self._lock:
            policy = self._policies.get(deployment)
            if policy is None:
                return True, 0.0
            # Top-K tenant buckets: a tenant string beyond the cap shares
            # the overflow bucket — rotating the (unauthenticated) tenant
            # header cannot mint fresh burst tokens or unbounded state.
            seen = self._tenants_seen.setdefault(deployment, set())
            if tenant not in seen:
                if len(seen) < policy.max_tenants:
                    seen.add(tenant)
                else:
                    tenant = m.OTHER_LABEL
            degraded = self._degraded.get(deployment, False)
            key = (deployment, tenant, qos_class)
            bucket = self._buckets.get(key)
            rate = policy.class_rate(qos_class, degraded)
            if bucket is None:
                bucket = self._buckets[key] = TokenBucket(
                    rate, policy.burst, clock=self._clock
                )
            elif bucket.rate_rps != rate:
                bucket.set_rate(rate)  # governor flipped since last use
            ok, retry_after_s = bucket.try_acquire()
            if ok:
                self.admitted += 1
            else:
                self.rejected += 1
                self._rejects_since_observe[deployment] = (
                    self._rejects_since_observe.get(deployment, 0) + 1
                )
        ADMISSION_TOTAL.inc(tags={
            "deployment": deployment, "tenant": tenant, "qos": qos_class,
            "outcome": "admit" if ok else "reject",
        })
        if ok:
            return True, 0.0
        return False, retry_after_s

    def admit_or_raise(self, deployment: str, tenant: str = DEFAULT_TENANT,
                       qos_class: str = DEFAULT_QOS_CLASS) -> None:
        ok, retry_after_s = self.admit(deployment, tenant, qos_class)
        if not ok:
            raise AdmissionRejected(  # rdb-lint: disable=shed-accounting (admit() above already counted this reject in ADMISSION_TOTAL and the controller stats)
                f"{deployment}: admission rate exceeded for tenant "
                f"{tenant!r} class {qos_class!r}",
                retry_after_s=retry_after_s,
            )

    # --- overload governor -------------------------------------------------
    def observe(self, deployment: str, depth_frac: float,
                slo_compliance: float) -> Optional[str]:
        """Feed one control-tick's signals; returns the transition name
        (``"degrade"``/``"recover"``) when the state flipped, else None.
        Recovery additionally requires ZERO rejects since the last tick:
        a degraded deployment still turning traffic away is still under
        the flood — readmitting it would flap (degrade sheds the load,
        the queue reads healthy one tick later, recovery readmits,
        repeat). Bucket rates re-derive lazily at the next admit — no
        bucket churn on quiet ticks."""
        with self._lock:
            policy = self._policies.get(deployment)
            if policy is None:
                return None
            recent_rejects = self._rejects_since_observe.pop(deployment, 0)
            degraded = self._degraded.get(deployment, False)
            transition = None
            if not degraded and (
                depth_frac >= policy.depth_high
                or slo_compliance <= policy.compliance_low
            ):
                self._degraded[deployment] = True
                transition = "degrade"
            elif degraded and (
                depth_frac <= policy.depth_low
                and slo_compliance >= policy.compliance_high
                and recent_rejects == 0
            ):
                self._degraded[deployment] = False
                transition = "recover"
            # Congested hysteresis (its OWN axis — a tick may flip both):
            # enter at/below the attainment floor, exit only at/above the
            # exit bar. No zero-rejects gate here: while congested the
            # budget itself sheds re-dispatches, so rejects are the
            # defense WORKING, not evidence the flood persists.
            congest_transition = None
            if policy.congested_floor > 0.0:
                congested = self._congested.get(deployment, False)
                if not congested and \
                        slo_compliance <= policy.congested_floor:
                    self._congested[deployment] = True
                    congest_transition = "congest"
                elif congested and \
                        slo_compliance >= policy.congested_exit:
                    self._congested[deployment] = False
                    congest_transition = "clear_congestion"
            if transition is None and congest_transition is None:
                return None
            self.transitions += (transition is not None) + (
                congest_transition is not None)
            now_degraded = self._degraded[deployment]
            now_congested = self._congested.get(deployment, False)
            fractions = dict(policy.degraded_class_fractions)
        if transition is not None:
            GOVERNOR_STATE.set(
                1.0 if now_degraded else 0.0, tags={"deployment": deployment}
            )
            logger.warning(
                "%s: admission governor %s (depth_frac=%.3f "
                "compliance=%.3f)",
                deployment, transition.upper(), depth_frac, slo_compliance,
            )
            if self.audit is not None:
                self.audit.record(
                    "admission_governor",
                    key=deployment,
                    observed={"depth_frac": round(depth_frac, 4),
                              "slo_compliance": round(slo_compliance, 4)},
                    before={"state": "normal" if now_degraded
                            else "degraded"},
                    after={"state": "degraded" if now_degraded
                           else "normal"},
                    diff={"class_rate_fractions": (
                        fractions if now_degraded else
                        {c: 1.0 for c in fractions}
                    )},
                )
        if congest_transition is not None:
            logger.warning(
                "%s: admission governor %s (compliance=%.3f floor=%.3f)",
                deployment, congest_transition.upper(), slo_compliance,
                policy.congested_floor,
            )
            if self.audit is not None:
                self.audit.record(
                    "admission_governor",
                    key=deployment,
                    observed={"slo_compliance": round(slo_compliance, 4),
                              "congested_floor": policy.congested_floor},
                    before={"congested": not now_congested},
                    after={"congested": now_congested},
                    diff={"retry_budget": ("zeroed" if now_congested
                                           else "restored")},
                )
        return transition or congest_transition

    # --- observability -----------------------------------------------------
    def snapshot(self, deployment: str) -> Dict[str, object]:
        with self._lock:
            policy = self._policies.get(deployment)
            return {
                "configured": policy is not None,
                "state": ("degraded"
                          if self._degraded.get(deployment, False)
                          else "normal"),
                "congested": self._congested.get(deployment, False),
                "rate_rps": policy.rate_rps if policy else None,
                "buckets": sum(
                    1 for k in self._buckets if k[0] == deployment
                ),
            }

    def stats(self) -> Dict[str, float]:
        return {
            "admitted": float(self.admitted),
            "rejected": float(self.rejected),
            "governor_transitions": float(self.transitions),
        }
