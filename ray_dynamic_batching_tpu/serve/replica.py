"""Serve replica — one worker executing a deployment's callable with
opportunistic batching.

Re-creates Ray Serve's ``ReplicaActor``
(``python/ray/serve/_private/replica.py:233``: ``handle_request`` :515-544,
``UserCallableWrapper`` :810, per-replica metrics :92) fused with
``@serve.batch`` (``python/ray/serve/batching.py:530``): the replica's loop
pulls size-or-timeout batches from its own queue and invokes the user
callable once per batch.

TPU-first notes: for model deployments the callable typically closes over a
pre-compiled bucket executor (see ``engine.worker``/``engine.decode``); the
replica layer itself is model-agnostic — it owns queueing, concurrency
control, health, and stats, mirroring how Serve wraps arbitrary callables.
"""

from __future__ import annotations

import inspect
import threading
import time
from typing import Any, Callable, List, Optional, Sequence

from contextlib import ExitStack

from ray_dynamic_batching_tpu.engine.batching import OpportunisticBatch
from ray_dynamic_batching_tpu.engine.queue import RequestQueue
from ray_dynamic_batching_tpu.engine.request import Request, RequestDropped
from ray_dynamic_batching_tpu.serve.failover import PoisonRequest, is_retryable
from ray_dynamic_batching_tpu.serve.quarantine import poison_fingerprint
from ray_dynamic_batching_tpu.utils.chaos import chaos
from ray_dynamic_batching_tpu.utils.logging import get_logger
from ray_dynamic_batching_tpu.utils import metrics as m
from ray_dynamic_batching_tpu.utils.tracing import link_to, tracer

logger = get_logger("replica")

REPLICA_REQUESTS = m.Counter(
    "rdb_replica_requests_total", "Requests processed",
    tag_keys=("deployment", "replica"),
)
REPLICA_BATCHES = m.Counter(
    "rdb_replica_batches_total", "Batches processed",
    tag_keys=("deployment", "replica"),
)
REPLICA_ERRORS = m.Counter(
    "rdb_replica_errors_total", "Callable errors",
    tag_keys=("deployment", "replica"),
)


# Replica executing on the current thread (set around user-callable
# execution): lets in-callable framework code — e.g. the @multiplexed
# loader cache — report ground-truth model residency back to the replica
# the router reads, without threading a handle through the user's code.
_current = threading.local()


def current_replica() -> Optional["Replica"]:
    return getattr(_current, "replica", None)


def record_multiplexed_model_locked(
    models: List[str], model_id: str, cap: int
) -> None:
    """Shared multiplex-LRU update (caller holds its own lock): refresh
    recency, evict the coldest past ``cap`` — the ref replica unloading its
    LRU model. Used by in-process replicas and process nodes alike so the
    policy cannot diverge."""
    if model_id in models:
        models.remove(model_id)
    models.append(model_id)
    while len(models) > cap:
        models.pop(0)


class Replica:
    """One deployment replica: queue + batching loop around a user callable.

    ``fn`` maps a list of payloads to a list of results (the ``@serve.batch``
    contract). ``max_ongoing_requests`` bounds queued+running work — the
    router's pow-2 scheduler reads :meth:`queue_len` and respects this cap
    (ref replica_scheduler/replica_wrapper.py queue-length protocol).
    """

    def __init__(
        self,
        replica_id: str,
        deployment: str,
        fn: Callable[[List[Any]], Sequence[Any]],
        max_batch_size: int = 8,
        batch_wait_timeout_s: float = 0.005,
        max_ongoing_requests: int = 256,
    ) -> None:
        self.replica_id = replica_id
        self.deployment = deployment
        self.fn = fn
        self.max_ongoing_requests = max_ongoing_requests
        self.queue = RequestQueue(deployment, max_len=max_ongoing_requests)
        self.policy = OpportunisticBatch(
            max_batch_size=max_batch_size,
            batch_wait_timeout_s=batch_wait_timeout_s,
        )
        self._ongoing = 0
        self._ongoing_lock = threading.Lock()
        # Multiplexed models resident on this replica, most-recent last
        # (ref replica multiplex LRU surfaced to the pow-2 scheduler).
        self.loaded_models: List[str] = []
        self.max_multiplexed_models = 8
        # Config version this replica was built from (stamped by the
        # controller; rolling updates retire mismatched stamps).
        self.version = ""
        self._stopped = False
        self._run = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Drained after each batch completes: lets in-callable framework
        # code (e.g. the @multiplexed cache) defer resource release until
        # no request in the current batch can still be using it.
        self._post_batch_hooks: List[Callable[[], None]] = []
        self._hooks_lock = threading.Lock()
        self.last_heartbeat = time.monotonic()
        self.started_at = time.monotonic()
        self._batch_started_at: Optional[float] = None
        # Failover sink (serve/failover.FailoverManager), wired by the
        # router on registration: retryable system failures hand their
        # batch here for re-dispatch instead of poisoning the futures.
        # None (bare replicas in tests / engine tier) = reject as before.
        self.failure_sink = None
        # Quarantine registry (serve/quarantine.QuarantineRegistry), wired
        # by the router on registration: a non-retryable BATCH failure on a
        # wired replica triggers query-of-death bisection instead of
        # rejecting every co-batched innocent. None = legacy reject-all.
        self.quarantine = None
        self.bisect_probes = 0
        self.rescue_batches = 0
        self.poison_isolated = 0

    # --- router-facing surface -------------------------------------------
    def queue_len(self) -> int:
        """Queued + in-flight, the pow-2 routing signal."""
        with self._ongoing_lock:
            return len(self.queue) + self._ongoing

    def accepting(self) -> bool:
        """Not-yet-started replicas accept (they drain once started);
        stopped replicas never do."""
        return not self._stopped and self.queue_len() < self.max_ongoing_requests

    def assign(self, request: Request) -> bool:
        """Enqueue, declining when saturated (ref
        ``handle_request_with_rejection``, replica.py:544). A declined
        request stays retryable — the router owns terminal rejection."""
        if not self.accepting():
            return False
        ok = self.queue.add_request(request, reject_on_full=False)
        if ok and request.multiplexed_model_id:
            self.record_multiplexed_model(request.multiplexed_model_id)
        return ok

    def record_multiplexed_model(self, model_id: str) -> None:
        """Mark a multiplexed model resident here. Locked: concurrent
        assigns of the same id race check-then-remove."""
        with self._ongoing_lock:
            record_multiplexed_model_locked(
                self.loaded_models, model_id, self.max_multiplexed_models
            )

    def remove_multiplexed_model(self, model_id: str) -> None:
        """Drop a model from the advertised residency set (the loader cache
        evicted it): the router must stop steering its traffic here."""
        with self._ongoing_lock:
            if model_id in self.loaded_models:
                self.loaded_models.remove(model_id)

    def add_post_batch_hook(self, hook: Callable[[], None]) -> None:
        """Run ``hook`` once the in-flight batch finishes (or immediately
        if called outside batch execution, from the drain in finally)."""
        with self._hooks_lock:
            self._post_batch_hooks.append(hook)

    def _drain_post_batch_hooks(self) -> None:
        with self._hooks_lock:
            hooks, self._post_batch_hooks = self._post_batch_hooks, []
        for hook in hooks:
            try:
                hook()
            except Exception:  # noqa: BLE001 — a hook must not kill the loop
                logger.exception("%s: post-batch hook failed", self.replica_id)

    # --- loop -------------------------------------------------------------
    def _stream_generator_batch(
        self, batch: List[Request], gen: Any, stream: bool = True
    ) -> List[Any]:
        """Generator batching (ref ``serve/batching.py:209-276``): the
        callable yields, per step, a list of one chunk per request; each
        chunk streams to its request immediately, and the per-request chunk
        lists become the final results. A ``StopIteration``-style sentinel
        of ``None`` skips a request for that step (ref's semantics for
        unequal-length generator outputs). ``stream=False`` collects
        without emitting (bisection probes: a probe that later FAILS must
        not have leaked tokens to innocents, or their rescue re-execution
        would double-emit)."""
        collected: List[List[Any]] = [[] for _ in batch]
        for step in gen:
            if len(step) != len(batch):
                raise ValueError(
                    f"generator yielded {len(step)} chunks for "
                    f"{len(batch)} requests"
                )
            for i, (req, chunk) in enumerate(zip(batch, step)):
                if chunk is None:
                    continue
                collected[i].append(chunk)
                if stream:
                    req.stream_put(chunk)
        return collected

    def _execute_batch(
        self, batch: List[Request], defer_stream: bool = False
    ) -> List[Any]:
        """One execution of the user callable over ``batch`` — the unit
        the bisection re-runs. The chaos poison hook fires here so armed
        query-of-death markers fail every probe that contains them (the
        property isolation depends on). ``defer_stream`` holds generator
        chunks until the whole generator completes, then replays them —
        token-exact streams even when earlier probes of the same requests
        failed partway."""
        chaos().maybe_poison(
            "replica.process_batch", [r.payload for r in batch]
        )
        results = self.fn([r.payload for r in batch])
        if inspect.isgenerator(results):
            results = self._stream_generator_batch(
                batch, results, stream=not defer_stream
            )
            if defer_stream:
                for req, chunks in zip(batch, results):
                    for chunk in chunks:
                        req.stream_put(chunk)
        if len(results) != len(batch):
            raise ValueError(
                f"callable returned {len(results)} results for "
                f"{len(batch)} requests"
            )
        return results

    def _bisect_poison(self, batch: List[Request], exc: Exception) -> None:
        """Query-of-death isolation: a non-retryable failure on a batch of
        N is presumed to be ONE request's content. Binary-search it in
        exactly ``ceil(log2 N)`` re-executions — each round probes the
        first half of the suspect set; a raise implicates that half (the
        other half parks as pending innocents), success fulfills it and
        implicates the other half. The survivor is rejected terminally
        (``PoisonRequest``, 4xx, never retried), fingerprinted into the
        quarantine registry so every front door refuses repeats, and the
        parked innocents get one rescue execution (re-bisected if it
        fails again — multi-poison batches resolve recursively).

        Never raises: isolation is the replica's last line before
        reject-all, so its own failures degrade to rejection, not a dead
        loop."""
        suspects = list(batch)
        deferred: List[Request] = []  # parked innocents, rescued at the end
        probes = 0
        while len(suspects) > 1:
            mid = (len(suspects) + 1) // 2
            lo, hi = suspects[:mid], suspects[mid:]
            probes += 1
            self.bisect_probes += 1
            try:
                results = self._execute_batch(lo, defer_stream=True)
            except Exception as probe_exc:  # noqa: BLE001 — verdict, not crash
                exc = probe_exc
                deferred.extend(hi)
                suspects = lo
            else:
                for req, res in zip(lo, results):
                    req.fulfill(res)
                self.queue.record_batch_completion(lo)
                suspects = hi
        poison = suspects[0]
        fp = poison_fingerprint(self.deployment, poison.payload)
        self.poison_isolated += 1
        if self.quarantine is not None:
            self.quarantine.add(fp, self.deployment, stage="isolated")
        poison.reject(PoisonRequest(
            f"{poison.request_id}: query of death isolated by batch "
            f"bisection ({probes} probes over batch of {len(batch)}): "
            f"{exc}",
            cause=exc, fingerprint=fp,
        ))
        logger.warning(
            "%s: quarantined poison request %s (fingerprint %s, %d probes)",
            self.replica_id, poison.request_id, fp, probes,
        )
        if not deferred:
            return
        # Rescue pass: innocents whose half was implicated then cleared by
        # a deeper probe were never executed — run them once, token-exact.
        self.rescue_batches += 1
        try:
            results = self._execute_batch(deferred, defer_stream=True)
        except Exception as rescue_exc:  # noqa: BLE001 — may be 2nd poison
            if is_retryable(rescue_exc) and self.failure_sink is not None:
                # System fault during rescue: these requests are innocent
                # and retryable — failover re-dispatches them.
                self.failure_sink.on_batch_failure(
                    self, deferred, rescue_exc
                )
            else:
                # A SECOND poison in the same batch: recurse (a singleton
                # skips the loop above and is condemned directly).
                self._bisect_poison(deferred, rescue_exc)
        else:
            for req, res in zip(deferred, results):
                req.fulfill(res)
            self.queue.record_batch_completion(deferred)

    def _process_batch(self, batch: List[Request]) -> None:
        with self._ongoing_lock:
            self._ongoing += len(batch)
        self._batch_started_at = time.monotonic()
        _current.replica = self  # visible to in-callable framework hooks
        try:
            chaos().maybe_fail("replica.process_batch")
            # Gray-failure injection (ISSUE 9): a seeded slowdown verdict
            # makes THIS batch degrade — stall before any output, run a
            # latency multiple, or withhold EOS — without erroring, which
            # is exactly the failure class the breaker used to miss.
            slowdown = chaos().slowdown(
                "replica.process_batch", instance=self.replica_id
            )
            if (slowdown is not None
                    and slowdown.mode == "stall_before_first_token"):
                time.sleep(slowdown.ms / 1000.0)  # rdb-lint: disable=event-loop-blocking (chaos-injected stall on the replica's own worker thread; no event loop involved)
            exec_started = time.monotonic()
            with ExitStack() as spans:
                if tracer().enabled:
                    # One span for the BATCH execution, linked to every
                    # member request's span (dynamic batching's fan-in:
                    # parent/child cannot express N callers -> one step),
                    # then one execution span per request joined to its
                    # caller's trace via the propagated context (ref spans
                    # around every actor call, tracing_helper.py:293) and
                    # linked BACK to the batch span.
                    batch_span = spans.enter_context(
                        tracer().span(
                            "replica.batch",
                            links=[link_to(r.trace_ctx) for r in batch],
                            deployment=self.deployment,
                            replica=self.replica_id,
                            lane=self.replica_id,
                            size=len(batch),
                        )
                    )
                    for r in batch:
                        spans.enter_context(
                            tracer().attach_context(
                                r.trace_ctx, "replica.execute",
                                links=[link_to(batch_span)],
                                replica=self.replica_id,
                                lane=self.replica_id,
                            )
                        )
                results = self._execute_batch(batch)
            if slowdown is not None:
                if slowdown.mode == "latency_multiplier":
                    # The batch "runs" factor x as long as it measured —
                    # chunks (if any) already streamed, completion drags.
                    extra_s = max(0.0, (slowdown.factor - 1.0)
                                  * (time.monotonic() - exec_started))
                    time.sleep(extra_s)  # rdb-lint: disable=event-loop-blocking (chaos-injected slowdown on the replica's own worker thread; no event loop involved)
                elif slowdown.mode == "stuck_stream":
                    # Output exists, EOS never arrives: the stream stays
                    # open for ms of dead air before fulfill closes it.
                    time.sleep(slowdown.ms / 1000.0)  # rdb-lint: disable=event-loop-blocking (chaos-injected stuck stream on the replica's own worker thread; no event loop involved)
            for req, res in zip(batch, results):
                req.fulfill(res)
            self.queue.record_batch_completion(batch)
            sink = self.failure_sink
            if sink is not None:
                sink.on_batch_success(self)  # closes a half-open breaker
            REPLICA_BATCHES.inc(
                tags={"deployment": self.deployment, "replica": self.replica_id}
            )
            REPLICA_REQUESTS.inc(
                len(batch),
                tags={"deployment": self.deployment, "replica": self.replica_id},
            )
        except Exception as e:  # noqa: BLE001 — user errors flow to futures
            sink = self.failure_sink
            if sink is not None and is_retryable(e):
                # System failure (chaos, replica death, drain eviction):
                # the failover layer re-dispatches to a different replica
                # under the admission deadline; user errors below stay
                # terminal — retrying a bad payload just fails again.
                sink.on_batch_failure(self, batch, e)
            else:
                if self.quarantine is not None and len(batch) > 1:
                    # Router-wired replica, multi-request batch: presume
                    # query of death and bisect — innocents complete, the
                    # poison alone is condemned + quarantined. Bare
                    # replicas and singleton batches keep the legacy
                    # reject-with-original-exception contract.
                    self._bisect_poison(batch, e)
                else:
                    for req in batch:
                        req.reject(e)
                if sink is not None:
                    # A user error is terminal for the REQUEST but proof
                    # of life for the REPLICA (it executed the callable):
                    # it must close a half-open breaker, not wedge it.
                    sink.on_batch_success(self)
            REPLICA_ERRORS.inc(
                tags={"deployment": self.deployment, "replica": self.replica_id}
            )
            logger.warning("%s: batch failed: %s", self.replica_id, e)
        finally:
            _current.replica = None
            self._batch_started_at = None
            with self._ongoing_lock:
                self._ongoing -= len(batch)
            self._drain_post_batch_hooks()

    def _loop(self) -> None:
        while self._run.is_set():
            self.last_heartbeat = time.monotonic()
            # chaos: an injected loop failure kills this replica's thread,
            # simulating a worker crash the controller must detect + replace
            chaos().maybe_fail("replica.loop")
            batch = self.policy.next_batch(self.queue)
            if batch:
                self._process_batch(batch)

    # --- lifecycle (ref deployment_state replica start/stop) --------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._run.set()
        self._thread = threading.Thread(
            target=self._loop, name=f"replica-{self.replica_id}", daemon=True
        )
        self._thread.start()

    def drain_queue(self) -> List[Request]:
        """Stop accepting and pop everything still queued (the controller's
        heal path salvages these onto a replacement replica instead of
        rejecting work a live replica could serve)."""
        self._stopped = True
        out: List[Request] = []
        while len(self.queue) > 0:
            out.extend(
                self.queue.get_batch(self.max_ongoing_requests,
                                     discard_stale=False)
            )
        return out

    def stop(self, timeout_s: float = 5.0, drain: bool = True) -> None:
        """Graceful: stop accepting, drain the queue, then join."""
        self._stopped = True
        if drain and self._thread is not None:
            deadline = time.monotonic() + timeout_s
            while self.queue_len() > 0 and time.monotonic() < deadline:
                time.sleep(0.01)  # rdb-lint: disable=event-loop-blocking (control-plane stop() drain poll on the controller's thread; no event loop involved)
        self._run.clear()
        self.queue.close()  # releases the loop's condition wait permanently
        if self._thread is not None:
            self._thread.join(timeout_s)
            self._thread = None
        # Reject everything left, however much reconfigure() shrank max_len
        # — counted as drops so shed accounting conserves through teardown.
        for req in self.drain_queue():
            self.queue.count_external_drop(req, reason="closed")
            req.reject(RequestDropped(f"{self.replica_id} stopped"))

    def healthy(self, stall_timeout_s: float = 60.0) -> bool:
        """Liveness check (ref deployment_state health checks): the loop
        thread must be alive, and any in-flight batch must not have been
        running longer than ``stall_timeout_s`` (a wedged user callable —
        e.g. deadlocked on an external resource — is the stall we detect;
        set the timeout above the worst legitimate batch, XLA warmup
        compiles included)."""
        if not self._run.is_set():
            return False
        if self._thread is None or not self._thread.is_alive():
            return False
        started = self._batch_started_at
        return started is None or (time.monotonic() - started) < stall_timeout_s

    def reconfigure(
        self,
        max_batch_size: Optional[int] = None,
        batch_wait_timeout_s: Optional[float] = None,
        max_ongoing_requests: Optional[int] = None,
        user_config: Optional[dict] = None,
    ) -> None:
        """Apply new batching/concurrency knobs to a RUNNING replica (the
        runtime-tunable contract of ``@serve.batch``, batching.py:369-386).
        ``user_config`` flows to the USER callable's own ``reconfigure``
        hook when it has one (ref: replicas call the user class's
        reconfigure on deploy-time user_config updates, replica.py:810
        UserCallableWrapper) — looked up on the callable, then on the
        bound instance behind it."""
        if max_batch_size is not None:
            self.policy.set_max_batch_size(max_batch_size)
        if batch_wait_timeout_s is not None:
            self.policy.set_batch_wait_timeout_s(batch_wait_timeout_s)
        if max_ongoing_requests is not None:
            self.max_ongoing_requests = max_ongoing_requests
            self.queue.max_len = max_ongoing_requests
        if user_config is not None:
            hook = getattr(self.fn, "reconfigure", None)
            if hook is None:
                target = getattr(self.fn, "__self__", None)
                hook = getattr(target, "reconfigure", None)
            if callable(hook):
                hook(user_config)

    def slo_compliance(self) -> float:
        """Recent-completion SLO compliance — the governor's degrade
        signal. Subclasses whose traffic bypasses the base queue
        (LLMReplica's per-bucket queues) override to read the queues
        that actually carry requests."""
        return self.queue.slo_compliance()

    def latency_observation(self) -> tuple:
        """``(p50_ms, p95_ms, n)`` over this replica's recent batch
        completions — the gray detector's per-tick observation and the
        hedge bar's p95 source share this one accessor. Subclasses whose
        traffic bypasses the base queue (LLMReplica) override it, for
        the same reason as :meth:`slo_compliance`: the closed base
        queue's empty sketch would leave the replica permanently
        ungraded and the hedge bar at its floor."""
        win = self.queue.latency_window
        return (win.percentile(0.5), win.percentile(0.95), len(win))

    def stats(self) -> dict:
        s = self.queue.stats()
        s["ongoing"] = float(self.queue_len())
        if self.poison_isolated or self.bisect_probes:
            s["bisect_probes"] = float(self.bisect_probes)
            s["rescue_batches"] = float(self.rescue_batches)
            s["poison_isolated"] = float(self.poison_isolated)
        return s
