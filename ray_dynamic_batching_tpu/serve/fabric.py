"""Control fabric — the injectable message seam under the control plane.

Every control-plane exchange in the shipped tree used to be a direct
in-process call over a perfect network: ``ReplicatedStore`` appends to
``StoreLog``, ``LeaderLease`` renews, ``FrontDoor`` shards absorb each
other's gossip, the controller pushes long-poll digests — and nothing
could be dropped, delayed, duplicated, reordered, or partitioned. The
single biggest untested correlated failure was a network partition of
the control plane itself (ROADMAP item 4's "rates far above live
capacity" demands it; the reference's GCS/raft lineage is DEFINED by how
it behaves under exactly this).

This module is the seam. All cross-component control traffic routes
through a :class:`ControlFabric`:

- :meth:`ControlFabric.call` — request/response edges (log appends and
  reads, lease acquire/renew). A partitioned or chaos-dropped call
  raises :class:`FabricUnreachable`; the caller owns the degraded mode
  (the store self-demotes, the controller skips the step and retries).
- :meth:`ControlFabric.cast` — one-way messages (gossip state exchange,
  long-poll pushes). Drops are silent (counted), delays defer delivery
  through an injectable scheduler (the sim twin passes the virtual
  event loop, so delays are EVENTS and replay byte-identically; live
  mode uses daemon timers), duplicates deliver twice — the consumers
  are delta-state CRDTs / snapshot-id channels precisely so re-delivery
  and reordering are harmless, and the chaos policy is what proves it.

The default fabric is a zero-overhead passthrough: unconfigured, every
message is one attribute check plus the direct call — live canon is
unchanged. Chaos arms it, the same way PR 9's slowdown spec arms gray
failures:

    RDB_TESTING_PARTITION="ctl-A|log@t=10:heal=8"
    RDB_TESTING_PARTITION="ctl-A+fd-0+fd-1|ctl-B+log+lease+fd-2+fd-3@t=5"
    RDB_TESTING_FABRIC="frontdoor.gossip=-1:drop:p0.5,controller.push=3:delay5-20"

A partition splits NODES (or node groups registered via :meth:`assign`)
into two sides from ``t`` seconds after the fabric's epoch, healing
after ``heal`` seconds (omitted = never). Messages whose ``src`` and
``dst`` land on opposite sides are unreachable; same-side and unnamed
endpoints are untouched — which is what makes the ASYMMETRIC cases
expressible (a leader that can renew its lease but not reach the log:
partition ``ctl-A|log``, leave ``lease`` unnamed or on ctl-A's side).

Edge chaos grammar (per edge, seeded like utils/chaos.py so a schedule
replays byte-identically): ``edge=BUDGET:MODE[:pP]`` with modes ``drop``,
``delay<MS>[-<MS>]`` (uniform draw in the range), ``dup``; BUDGET -1 is
unlimited; ``:pP`` makes each opportunity fire with probability P.

Observability: ``rdb_fabric_messages_total{edge,outcome}`` counts every
message through an ACTIVE fabric (delivered | dropped | delayed |
duplicated; both tags bounded) and ``rdb_fabric_partition_active`` holds
1 while any configured partition window is open.
"""

from __future__ import annotations

import os
import random
import threading
import time  # live-mode default clock only; the sim twin injects VirtualClock
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_dynamic_batching_tpu.utils.concurrency import OrderedLock, assert_owner
from ray_dynamic_batching_tpu.utils.logging import get_logger
from ray_dynamic_batching_tpu.utils import metrics as m

logger = get_logger("fabric")

PARTITION_ENV_VAR = "RDB_TESTING_PARTITION"
FABRIC_ENV_VAR = "RDB_TESTING_FABRIC"

# Edge names are code-controlled (the canonical set below), but bounded
# anyway so a typo'd or runaway edge label cannot mint unbounded series.
FABRIC_MESSAGES = m.Counter(
    "rdb_fabric_messages_total",
    "Control-fabric messages by edge and outcome "
    "(delivered | dropped | delayed | duplicated)",
    tag_keys=("edge", "outcome"),
    bounded_tags={"edge": 12},
)
FABRIC_PARTITION = m.Gauge(
    "rdb_fabric_partition_active",
    "1 while a configured fabric partition window is open, else 0",
)

# Canonical edge names (the fabric treats them as opaque; listed here so
# specs, dashboards, and the fabric-discipline lint rule agree):
#   store.append      ReplicatedStore -> StoreLog commit
#   store.read        ReplicatedStore -> StoreLog replay
#   store.fence       ReplicatedStore -> StoreLog fence raise
#   store.snapshot    ReplicatedStore -> StoreLog snapshot install/fetch
#   lease.acquire     ReplicatedStore -> LeaderLease takeover
#   lease.renew       ReplicatedStore -> LeaderLease heartbeat
#   frontdoor.gossip  shard -> shard ledger-state absorb
#   controller.push   controller -> router long-poll notify
#   controller.digest_push  controller -> router digest directory
#   long_poll.listen  router/handle -> controller long-poll listen
#   courier.migrate   KVPageFabric -> replica live-stream parcel delivery
#   courier.push      KVPageFabric -> replica prefix-push parcel delivery


class FabricUnreachable(RuntimeError):
    """A request/response control message could not be delivered: the
    edge crossed an active partition or drew a chaos drop. The caller —
    not the fabric — owns the degraded mode: a leader whose appends are
    unreachable self-demotes; a controller whose lease is unreachable
    skips the step and retries; a long-poll listen re-arms."""

    def __init__(self, message: str, edge: str = "", src: str = "",
                 dst: str = "") -> None:
        super().__init__(message)
        self.edge = edge
        self.src = src
        self.dst = dst


@dataclass(frozen=True)
class Partition:
    """One partition window: sides ``a``/``b`` (node or group names),
    open from ``at_s`` after the fabric epoch, healing after ``heal_s``
    more seconds (``heal_s <= 0`` = never heals)."""

    a: frozenset
    b: frozenset
    at_s: float
    heal_s: float = 0.0

    def open_at(self, t_s: float) -> bool:
        if t_s < self.at_s:
            return False
        return self.heal_s <= 0 or t_s < self.at_s + self.heal_s


def parse_partition_spec(spec: str) -> List[Partition]:
    """Parse ``sideA|sideB@t=N[:heal=M][;...]`` — nodes within a side
    joined by ``+``. Parses fully before returning, so an invalid spec
    configures nothing (the all-or-nothing discipline of
    utils/chaos.py)."""
    out: List[Partition] = []
    for part in filter(None, (p.strip() for p in spec.split(";"))):
        if "@" not in part or "|" not in part:
            raise ValueError(
                f"bad partition spec entry {part!r} "
                "(want sideA|sideB@t=N[:heal=M])"
            )
        sides, when = part.split("@", 1)
        a_raw, b_raw = sides.split("|", 1)
        a = frozenset(filter(None, (n.strip() for n in a_raw.split("+"))))
        b = frozenset(filter(None, (n.strip() for n in b_raw.split("+"))))
        if not a or not b:
            raise ValueError(f"partition entry {part!r} has an empty side")
        if a & b:
            raise ValueError(
                f"partition entry {part!r} puts {sorted(a & b)} on both sides"
            )
        at_s = heal_s = None
        for token in filter(None, (t.strip() for t in when.split(":"))):
            if token.startswith("t="):
                at_s = float(token[2:])
            elif token.startswith("heal="):
                heal_s = float(token[5:])
            else:
                raise ValueError(
                    f"bad partition window token {token!r} in {part!r} "
                    "(want t=N[:heal=M])"
                )
        if at_s is None:
            raise ValueError(f"partition entry {part!r} has no t=N window")
        out.append(Partition(a=a, b=b, at_s=at_s, heal_s=heal_s or 0.0))
    return out


@dataclass(frozen=True)
class EdgeChaos:
    """One edge's chaos verdict kind: HOW messages on it misbehave."""

    mode: str                          # "drop" | "delay" | "dup"
    delay_ms: Tuple[float, float] = (0.0, 0.0)


def _parse_edge_mode(token: str) -> EdgeChaos:
    if token == "drop":
        return EdgeChaos("drop")
    if token == "dup":
        return EdgeChaos("dup")
    if token.startswith("delay"):
        rng = token[5:]
        if "-" in rng:
            lo, hi = rng.split("-", 1)
        else:
            lo = hi = rng
        lo_f, hi_f = float(lo), float(hi)
        if lo_f < 0 or hi_f < lo_f:
            raise ValueError(f"bad delay range {rng!r} (want MS or MS-MS)")
        return EdgeChaos("delay", delay_ms=(lo_f, hi_f))
    raise ValueError(
        f"bad fabric mode {token!r} (want drop|delay<MS>[-<MS>]|dup)"
    )


def parse_fabric_spec(spec: str) -> Dict[str, Tuple[int, float, EdgeChaos]]:
    """Parse ``edge=BUDGET:MODE[:pP],...`` into
    ``{edge: (budget, prob, EdgeChaos)}`` — the utils/chaos.py grammar
    with fabric modes."""
    table: Dict[str, Tuple[int, float, EdgeChaos]] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        if "=" not in part:
            raise ValueError(f"bad fabric spec entry {part!r}")
        edge, rhs = part.split("=", 1)
        tokens = rhs.split(":")
        if len(tokens) < 2:
            raise ValueError(
                f"fabric entry {part!r} needs a mode "
                "(edge=N:drop|delay<MS>[-<MS>]|dup[:pP])"
            )
        prob = 1.0
        if len(tokens) > 2:
            if not tokens[2].startswith("p"):
                raise ValueError(
                    f"bad fabric suffix {tokens[2]!r} (want p<float>)"
                )
            prob = float(tokens[2][1:])
        table[edge.strip()] = (int(tokens[0]), prob,
                               _parse_edge_mode(tokens[1]))
    return table


class ControlFabric:
    """The message seam. One instance per control plane; components hold
    a reference and route every cross-component message through it.

    ``clock`` is THE control-plane clock (shared with ``StoreLog`` and
    ``LeaderLease`` — the PR's clock-unification contract); partition
    windows are measured from the clock value at construction/configure.
    ``scheduler(delay_ms, fn)`` defers delayed cast deliveries — the sim
    twin passes ``EventLoop.schedule_in`` so delays are virtual-time
    events; live mode defaults to daemon timers. A fabric with no
    partitions and no edge chaos is a passthrough: one attribute read
    per message, no accounting, no behavior change."""

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        scheduler: Optional[Callable[[float, Callable[[], None]], None]] = None,
        seed: Optional[int] = None,
        partition_spec: Optional[str] = None,
        edge_spec: Optional[str] = None,
    ) -> None:
        self._clock = clock
        self._scheduler = scheduler
        self._lock = OrderedLock("fabric")
        self._groups: Dict[str, str] = {}
        self._seed = seed if seed is not None else self._config_seed()
        self._rng = random.Random(self._seed)
        self._partitions: List[Partition] = []
        self._edges: Dict[str, Tuple[int, float, EdgeChaos]] = {}
        self._stats: Dict[Tuple[str, str], int] = {}
        self._t0 = clock()
        self._active = False
        self._partition_gauge = 0
        self.configure(
            partition_spec if partition_spec is not None
            else os.environ.get(PARTITION_ENV_VAR, ""),
            edge_spec if edge_spec is not None
            else os.environ.get(FABRIC_ENV_VAR, ""),
        )

    @staticmethod
    def _config_seed() -> int:
        from ray_dynamic_batching_tpu.utils.config import get_config

        return get_config().chaos_seed

    # --- configuration ----------------------------------------------------
    def configure(self, partition_spec: str = "", edge_spec: str = "",
                  seed: Optional[int] = None) -> None:
        """(Re)arm the chaos policy; parses fully before swapping state
        and reseeds the draw RNG, so same spec + same seed replays the
        same schedule. Re-anchors the partition epoch at the current
        clock value."""
        partitions = parse_partition_spec(partition_spec)
        edges = parse_fabric_spec(edge_spec)
        with self._lock:
            self._partitions = partitions
            self._edges = edges
            self._stats = {}
            if seed is not None:
                self._seed = seed
            self._rng = random.Random(self._seed)
            self._t0 = self._clock()
            self._active = bool(partitions or edges)
            self._partition_gauge = 0
        # Reflect the (re)configured state immediately: disarming must
        # clear the exported gauge — a passthrough fabric never touches
        # it again, so a stale 1.0 would stand as a false alarm forever.
        FABRIC_PARTITION.set(0.0)

    def assign(self, node: str, group: str) -> None:
        """Map a node name onto a partition group (so a spec can say
        ``routers`` instead of enumerating every shard)."""
        with self._lock:
            self._groups[node] = group

    @property
    def active(self) -> bool:
        return self._active  # rdb-lint: disable=lock-discipline (arming flag flipped in quiesced configure(); one-op staleness on the passthrough fast path is benign and locking would serialize every edge)

    # --- partition evaluation ---------------------------------------------
    def _side(self, name: str) -> str:
        assert_owner(self._lock)  # callers hold it (_crosses)
        return self._groups.get(name, name)

    def _refresh_gauge_locked(self, open_now: bool) -> None:
        """Edge-triggered gauge refresh; caller holds ``_lock`` so two
        concurrent evaluations cannot interleave the compare and the
        write (a lost update would freeze the exported gauge wrong)."""
        assert_owner(self._lock)
        val = 1 if open_now else 0
        if val != self._partition_gauge:
            self._partition_gauge = val
            FABRIC_PARTITION.set(float(val))

    def partition_active(self, now: Optional[float] = None) -> bool:
        """True while ANY configured partition window is open (whether or
        not a given edge crosses it); refreshes the gauge on edges."""
        with self._lock:
            if not self._partitions:
                return False
            t = (self._clock() if now is None else now) - self._t0
            open_now = any(p.open_at(t) for p in self._partitions)
            self._refresh_gauge_locked(open_now)
            return open_now

    def _crosses(self, src: str, dst: str) -> bool:
        with self._lock:
            if not self._partitions or not src or not dst:
                # Unnamed endpoints cannot be placed on a side: untouched
                # — but still refresh the gauge on this edge visit.
                if self._partitions:
                    t = self._clock() - self._t0
                    self._refresh_gauge_locked(
                        any(p.open_at(t) for p in self._partitions))
                return False
            t = self._clock() - self._t0
            sa, sb = self._side(src), self._side(dst)
            crossing = False
            open_now = False
            for p in self._partitions:
                if not p.open_at(t):
                    continue
                open_now = True
                if (sa in p.a and sb in p.b) or (sa in p.b and sb in p.a):
                    crossing = True
            self._refresh_gauge_locked(open_now)
            return crossing

    def _edge_verdict(self, edge: str) -> Optional[EdgeChaos]:
        """Consume one unit of the edge's chaos budget, or None."""
        with self._lock:
            entry = self._edges.get(edge)
            if entry is None:
                return None
            budget, prob, verdict = entry
            if budget == 0:
                return None
            if prob < 1.0 and self._rng.random() >= prob:
                return None
            if budget > 0:
                self._edges[edge] = (budget - 1, prob, verdict)
            return verdict

    def _draw_delay_ms(self, verdict: EdgeChaos) -> float:
        lo, hi = verdict.delay_ms
        if hi <= lo:
            return lo
        with self._lock:
            return self._rng.uniform(lo, hi)

    def _count(self, edge: str, outcome: str) -> None:
        with self._lock:
            key = (edge, outcome)
            self._stats[key] = self._stats.get(key, 0) + 1
        FABRIC_MESSAGES.inc(tags={"edge": edge, "outcome": outcome})

    # --- the seam ----------------------------------------------------------
    def call(self, edge: str, fn: Callable[..., Any], *args: Any,
             src: str = "", dst: str = "", **kwargs: Any) -> Any:
        """Request/response edge: deliver ``fn(*args, **kwargs)`` and
        return its result, or raise :class:`FabricUnreachable` when the
        edge is partitioned / drew a drop. A delay verdict on a call
        edge is counted (``delayed``) and delivered — synchronous
        transports model latency at the caller, not here; drops and
        partitions are the failure modes that matter for appends and
        renews."""
        if not self._active:  # rdb-lint: disable=lock-discipline (passthrough fast path: arming flips in quiesced configure(); a one-call-stale read only delays chaos onset by one edge)
            return fn(*args, **kwargs)
        if self._crosses(src, dst):
            self._count(edge, "dropped")
            raise FabricUnreachable(
                f"{edge}: {src or '?'} cannot reach {dst or '?'} across an "
                "active partition", edge=edge, src=src, dst=dst,
            )
        verdict = self._edge_verdict(edge)
        if verdict is not None and verdict.mode == "drop":
            self._count(edge, "dropped")
            raise FabricUnreachable(
                f"{edge}: message dropped by chaos policy",
                edge=edge, src=src, dst=dst,
            )
        if verdict is not None and verdict.mode == "delay":
            self._count(edge, "delayed")
        else:
            self._count(edge, "delivered")
        return fn(*args, **kwargs)

    def cast(self, edge: str, deliver: Callable[..., Any], *args: Any,
             src: str = "", dst: str = "") -> bool:
        """One-way edge: deliver (possibly late, possibly twice) or drop
        silently. Returns False when the message was dropped, True when
        it was (or will be) delivered — callers treat False as "the
        network ate it", never an error. Delayed deliveries go through
        the scheduler; with none configured (live default) a daemon
        timer fires them, so a delayed gossip absorb can land out of
        order with a later round — exactly the reordering the
        delta-state CRDT consumers must (and do) tolerate."""
        if not self._active:  # rdb-lint: disable=lock-discipline (passthrough fast path: arming flips in quiesced configure(); a one-call-stale read only delays chaos onset by one edge)
            deliver(*args)
            return True
        if self._crosses(src, dst):
            self._count(edge, "dropped")
            return False
        verdict = self._edge_verdict(edge)
        if verdict is None:
            self._count(edge, "delivered")
            deliver(*args)
            return True
        if verdict.mode == "drop":
            self._count(edge, "dropped")
            return False
        if verdict.mode == "dup":
            self._count(edge, "duplicated")
            self._count(edge, "delivered")
            deliver(*args)
            deliver(*args)
            return True
        # delay
        self._count(edge, "delayed")
        delay_ms = self._draw_delay_ms(verdict)
        self._schedule(delay_ms, lambda: deliver(*args))
        return True

    def _schedule(self, delay_ms: float, fn: Callable[[], None]) -> None:
        if self._scheduler is not None:
            self._scheduler(delay_ms, fn)
            return
        t = threading.Timer(delay_ms / 1000.0, fn)
        t.daemon = True
        t.start()

    # --- observability -----------------------------------------------------
    def stats(self) -> Dict[str, int]:
        """Deterministic per-edge outcome counts (``edge.outcome: n``) —
        the partition sim's report reads this; empty for a passthrough."""
        with self._lock:
            return {f"{edge}.{outcome}": n
                    for (edge, outcome), n in sorted(self._stats.items())}


_DEFAULT: Optional[ControlFabric] = None
_DEFAULT_LOCK = threading.Lock()


def default_fabric() -> ControlFabric:
    """Process-global fabric, configured from the environment on first
    use (mirrors utils/chaos.py). Unconfigured, it is the zero-overhead
    passthrough every component defaults to."""
    global _DEFAULT
    if _DEFAULT is None:
        with _DEFAULT_LOCK:
            if _DEFAULT is None:
                _DEFAULT = ControlFabric()
    return _DEFAULT


def reset_fabric(partition_spec: str = "", edge_spec: str = "",
                 seed: Optional[int] = None) -> ControlFabric:
    """Re-configure (and optionally reseed) the global fabric — test and
    soak harnesses arm/disarm partitions through this, exactly like
    ``utils.chaos.reset_chaos``."""
    fab = default_fabric()
    fab.configure(partition_spec, edge_spec, seed=seed)
    return fab
