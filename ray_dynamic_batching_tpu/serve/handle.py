"""DeploymentHandle — the caller-side API for a deployment.

Re-creates Ray Serve's ``DeploymentHandle``
(``python/ray/serve/handle.py:745``; ``.remote()`` at ``:821`` returns a
response future resolved by the router): ``handle.remote(payload)`` builds a
request, routes it pow-2, and returns a ``concurrent.futures.Future`` the
caller (sync or asyncio via ``wrap_future``) awaits.
"""

from __future__ import annotations

from concurrent.futures import Future
from typing import Any, Optional, Tuple

from ray_dynamic_batching_tpu.engine.request import Request, TokenStream
from ray_dynamic_batching_tpu.serve.router import Router
from ray_dynamic_batching_tpu.utils.tracing import tracer


def _session_affinity(payload: Any) -> Optional[str]:
    """Steer a session's turns to the replica holding its KV row: the
    session cache is per-engine, so without affinity a multi-replica
    deployment misses ~(n-1)/n of continuations. Rides the same
    multiplex-awareness the pow-2 scheduler already ranks by."""
    if isinstance(payload, dict) and payload.get("session_id") is not None:
        return f"session:{payload['session_id']}"
    return None


class DeploymentHandle:
    """Lightweight, shareable; one per (caller, deployment)."""

    def __init__(
        self,
        router: Router,
        default_slo_ms: float = 30_000.0,
    ) -> None:
        self.router = router
        self.default_slo_ms = default_slo_ms

    @property
    def deployment(self) -> str:
        return self.router.deployment

    def remote(
        self,
        payload: Any,
        slo_ms: Optional[float] = None,
        locality_hint: Optional[str] = None,
        multiplexed_model_id: Optional[str] = None,
    ) -> Future:
        """Route one request; the future resolves to the replica's result
        (ref handle.py:821). ``multiplexed_model_id`` steers routing toward
        replicas already holding that model (ref handle
        ``options(multiplexed_model_id=...)``)."""
        multiplexed_model_id = multiplexed_model_id or _session_affinity(
            payload
        )
        # Span around routing; context rides the request so the replica's
        # execution span joins the same trace (ref task-metadata
        # propagation, tracing_helper.py:165,293).
        with tracer().span("handle.remote", deployment=self.deployment):
            request = Request(
                model=self.deployment,
                payload=payload,
                slo_ms=slo_ms if slo_ms is not None else self.default_slo_ms,
                multiplexed_model_id=multiplexed_model_id,
                trace_ctx=tracer().inject_context(),
            )
            self.router.assign_request(request, locality_hint=locality_hint)
        return request.future

    def remote_stream(
        self,
        payload: Any,
        slo_ms: Optional[float] = None,
        locality_hint: Optional[str] = None,
    ) -> Tuple[TokenStream, Future]:
        """Route one streaming request: chunks arrive on the returned
        :class:`TokenStream` as the replica produces them, the future still
        resolves with the final result (ref streaming handle path,
        ``serve/_private/replica.py:515`` ``handle_request_streaming``)."""
        stream = TokenStream()
        with tracer().span("handle.remote_stream", deployment=self.deployment):
            request = Request(
                model=self.deployment,
                payload=payload,
                slo_ms=slo_ms if slo_ms is not None else self.default_slo_ms,
                stream=stream,
                multiplexed_model_id=_session_affinity(payload),
                trace_ctx=tracer().inject_context(),
            )
            self.router.assign_request(request, locality_hint=locality_hint)
        return stream, request.future

    def options(self, slo_ms: Optional[float] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self.router,
            default_slo_ms=slo_ms if slo_ms is not None else self.default_slo_ms,
        )
