"""DeploymentHandle — the caller-side API for a deployment.

Re-creates Ray Serve's ``DeploymentHandle``
(``python/ray/serve/handle.py:745``; ``.remote()`` at ``:821`` returns a
response future resolved by the router): ``handle.remote(payload)`` builds a
request, routes it pow-2, and returns a ``concurrent.futures.Future`` the
caller (sync or asyncio via ``wrap_future``) awaits.
"""

from __future__ import annotations

from concurrent.futures import Future
from typing import Any, Optional, Tuple

from ray_dynamic_batching_tpu.engine.request import (
    DEFAULT_QOS_CLASS,
    DEFAULT_TENANT,
    Request,
    TokenStream,
    normalize_qos,
)
from ray_dynamic_batching_tpu.serve.router import Router
from ray_dynamic_batching_tpu.utils.tracing import tracer


def _session_affinity(payload: Any) -> Optional[str]:
    """Steer a session's turns to the replica holding its KV row: the
    session cache is per-engine, so without affinity a multi-replica
    deployment misses ~(n-1)/n of continuations. Rides the same
    multiplex-awareness the pow-2 scheduler already ranks by."""
    if isinstance(payload, dict) and payload.get("session_id") is not None:
        return f"session:{payload['session_id']}"
    return None


class DeploymentHandle:
    """Lightweight, shareable; one per (caller, deployment)."""

    def __init__(
        self,
        router: Router,
        default_slo_ms: float = 30_000.0,
        default_qos_class: str = DEFAULT_QOS_CLASS,
    ) -> None:
        self.router = router
        self.default_slo_ms = default_slo_ms
        # Per-deployment default tier (DeploymentConfig.default_qos_class):
        # requests that declare nothing serve at the deployment's contract.
        self.default_qos_class = normalize_qos(default_qos_class)

    def _qos_identity(self, payload, tenant, qos_class):
        """Resolve (tenant, qos_class): explicit kwargs (the gRPC/OpenAI
        doors) win, then payload fields (the HTTP door injects here), then
        the deployment default. Unknown classes raise BadRequest so the
        caller answers 4xx."""
        if isinstance(payload, dict):
            tenant = tenant or payload.get("tenant")
            qos_class = qos_class or payload.get("qos_class")
        return (
            tenant or DEFAULT_TENANT,
            normalize_qos(qos_class or self.default_qos_class),
        )

    @property
    def deployment(self) -> str:
        return self.router.deployment

    def remote(
        self,
        payload: Any,
        slo_ms: Optional[float] = None,
        locality_hint: Optional[str] = None,
        multiplexed_model_id: Optional[str] = None,
        tenant: Optional[str] = None,
        qos_class: Optional[str] = None,
    ) -> Future:
        """Route one request; the future resolves to the replica's result
        (ref handle.py:821). ``multiplexed_model_id`` steers routing toward
        replicas already holding that model (ref handle
        ``options(multiplexed_model_id=...)``). ``tenant``/``qos_class``
        ride the request through queueing, spans and failover (explicit
        kwargs > payload fields > deployment default)."""
        multiplexed_model_id = multiplexed_model_id or _session_affinity(
            payload
        )
        tenant, qos_class = self._qos_identity(payload, tenant, qos_class)
        # Span around routing; context rides the request so the replica's
        # execution span joins the same trace (ref task-metadata
        # propagation, tracing_helper.py:165,293).
        with tracer().span(
            "handle.remote", deployment=self.deployment,
            tenant=tenant, qos_class=qos_class,
        ):
            request = Request(
                model=self.deployment,
                payload=payload,
                slo_ms=slo_ms if slo_ms is not None else self.default_slo_ms,
                multiplexed_model_id=multiplexed_model_id,
                trace_ctx=tracer().inject_context(),
                tenant=tenant,
                qos_class=qos_class,
            )
            self.router.assign_request(request, locality_hint=locality_hint)
        return request.future

    def remote_stream(
        self,
        payload: Any,
        slo_ms: Optional[float] = None,
        locality_hint: Optional[str] = None,
        tenant: Optional[str] = None,
        qos_class: Optional[str] = None,
    ) -> Tuple[TokenStream, Future]:
        """Route one streaming request: chunks arrive on the returned
        :class:`TokenStream` as the replica produces them, the future still
        resolves with the final result (ref streaming handle path,
        ``serve/_private/replica.py:515`` ``handle_request_streaming``)."""
        stream = TokenStream()
        tenant, qos_class = self._qos_identity(payload, tenant, qos_class)
        with tracer().span(
            "handle.remote_stream", deployment=self.deployment,
            tenant=tenant, qos_class=qos_class,
        ):
            request = Request(
                model=self.deployment,
                payload=payload,
                slo_ms=slo_ms if slo_ms is not None else self.default_slo_ms,
                stream=stream,
                multiplexed_model_id=_session_affinity(payload),
                trace_ctx=tracer().inject_context(),
                tenant=tenant,
                qos_class=qos_class,
            )
            self.router.assign_request(request, locality_hint=locality_hint)
        return stream, request.future

    def options(self, slo_ms: Optional[float] = None,
                qos_class: Optional[str] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self.router,
            default_slo_ms=slo_ms if slo_ms is not None else self.default_slo_ms,
            default_qos_class=(qos_class if qos_class is not None
                               else self.default_qos_class),
        )
