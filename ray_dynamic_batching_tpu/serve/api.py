"""Developer-facing serve API: ``@deployment`` / ``bind`` / ``run`` /
``@batch`` — the reference's headline surface
(``python/ray/serve/api.py:463`` ``serve.run``, ``@serve.deployment``,
``@serve.batch`` at ``serve/batching.py:530``), re-created over the
TPU-native controller/router/replica stack.

Semantics kept from the reference:

- ``@deployment`` wraps a class or function into a :class:`Deployment`;
  ``.options(**overrides)`` returns a modified copy (ref
  ``Deployment.options``); ``.bind(*args, **kwargs)`` captures init args
  into an :class:`Application` for :func:`run`.
- User callables are PER-REQUEST by default — one payload in, one result
  out. Opting into batch execution is explicit via ``@batch`` (ref: Serve
  replicas call the user method per request unless ``@serve.batch``
  aggregates them), and the batch wrapper may be a generator that yields
  per-wave results for streaming (ref ``batching.py:209-276``).
- ``run`` deploys onto a module-level controller (created on first use —
  the singleton role of Serve's controller actor), returns a
  :class:`DeploymentHandle`, and optionally publishes an HTTP route when
  given a proxy (ref ``serve.run(..., route_prefix=...)``).

Differences, by TPU-first design: deployments are threads + compiled XLA
programs in one process (or process workers via ``runtime.cluster``), not
Ray actors, so ``bind`` does not build a multi-node DAG — it captures
constructor state for replica factories.
"""

from __future__ import annotations

import functools
import inspect
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

from ray_dynamic_batching_tpu.serve.controller import (
    DeploymentConfig,
    ServeController,
)
from ray_dynamic_batching_tpu.serve.handle import DeploymentHandle
from ray_dynamic_batching_tpu.serve.proxy import HTTPProxy
from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("serve.api")

_BATCH_ATTR = "_rdb_batch_options"


def batch(
    _fn: Optional[Callable] = None,
    *,
    max_batch_size: int = 8,
    batch_wait_timeout_s: float = 0.005,
) -> Callable:
    """Mark a callable as batch-executing (ref ``@serve.batch``,
    ``serve/batching.py:530``): the replica hands it the whole collected
    wave as a list and it returns one result per element (or yields lists
    incrementally — generator batching). The size/timeout knobs become the
    deployment's batching config, runtime-tunable exactly like the
    reference's ``set_max_batch_size`` (``batching.py:369-386``) through
    ``Replica.reconfigure``."""

    def wrap(fn: Callable) -> Callable:
        setattr(fn, _BATCH_ATTR, {
            "max_batch_size": int(max_batch_size),
            "batch_wait_timeout_s": float(batch_wait_timeout_s),
        })
        return fn

    return wrap if _fn is None else wrap(_fn)


class _MultiplexedMethod:
    """Descriptor produced by :func:`multiplexed`: a per-instance (= per
    replica, since the factory constructs the user class once per replica)
    LRU of loaded models, keyed by model id (ref
    ``serve/multiplex.py`` ``_ModelMultiplexWrapper``: bounded LRU, evicted
    models get their release hook called before being dropped)."""

    def __init__(self, fn: Callable, max_models: int,
                 unload: Optional[Callable[[Any], None]]):
        self._fn = fn
        self._max_models = max(1, int(max_models))
        self._unload = unload
        functools.update_wrapper(self, fn, updated=())

    def __get__(self, instance: Any, owner: Any = None) -> Callable:
        if instance is None:
            return self
        state_key = f"_rdb_mux_{self._fn.__name__}"
        state = instance.__dict__.get(state_key)
        if state is None:
            # setdefault is atomic under the GIL: concurrent first accesses
            # must converge on ONE state dict or each would load its own
            # duplicate model into an orphaned cache.
            state = instance.__dict__.setdefault(state_key, {
                "cache": {}, "order": [], "lock": threading.Lock(),
                # model_id -> Event; presence = a load is in flight, so
                # concurrent misses wait instead of loading a duplicate
                # (a duplicate is a full model's HBM leaked until GC).
                "inflight": {},
            })

        def get_model(model_id: str) -> Any:
            from ray_dynamic_batching_tpu.serve.replica import current_replica

            while True:
                with state["lock"]:
                    if model_id in state["cache"]:
                        state["order"].remove(model_id)
                        state["order"].append(model_id)
                        return state["cache"][model_id]
                    waiter = state["inflight"].get(model_id)
                    if waiter is None:
                        state["inflight"][model_id] = threading.Event()
                        break  # this thread is the loader
                waiter.wait()  # loader finished (or failed) -> re-check

            # Load OUTSIDE the lock: weight upload + XLA warmup can take
            # tens of seconds and must not block cache hits.
            evicted = None
            try:
                model = self._fn(instance, model_id)
                with state["lock"]:
                    state["cache"][model_id] = model
                    state["order"].append(model_id)
                    if len(state["order"]) > self._max_models:
                        victim = state["order"].pop(0)
                        evicted = (victim, state["cache"].pop(victim))
            finally:
                with state["lock"]:
                    state["inflight"].pop(model_id).set()
            # Ground-truth residency for the pow-2 router: advertise the
            # load and retract the eviction on the replica running this
            # callable (assign-time recording alone would keep steering
            # traffic to replicas that already evicted the model).
            replica = current_replica()
            if replica is not None:
                replica.record_multiplexed_model(model_id)
                if evicted is not None:
                    replica.remove_multiplexed_model(evicted[0])
            if evicted is not None:
                victim = evicted[1]
                if replica is not None:
                    # Replica batches are serialized on one thread, so any
                    # request still USING the victim belongs to the current
                    # batch — release only after it completes, never under
                    # a live forward pass.
                    replica.add_post_batch_hook(
                        lambda v=victim: self._release(v)
                    )
                else:
                    self._release(victim)
            return model

        get_model.loaded_model_ids = lambda: list(state["order"])
        return get_model

    def _release(self, model: Any) -> None:
        try:
            if self._unload is not None:
                self._unload(model)
            elif hasattr(model, "unload"):
                model.unload()
            # else: dropping the last reference frees device buffers on GC
        except Exception:  # noqa: BLE001 — eviction must not kill serving
            logger.exception("multiplexed model release hook failed")


def multiplexed(
    _fn: Optional[Callable] = None,
    *,
    max_num_models_per_replica: int = 4,
    unload: Optional[Callable[[Any], None]] = None,
) -> Callable:
    """``@serve.multiplexed`` equivalent (ref ``serve/multiplex.py``):
    decorate a loader METHOD ``def get_model(self, model_id)`` of a
    deployment class; calls become per-replica LRU-cached loads, bounded at
    ``max_num_models_per_replica``, with evicted models released through
    ``unload`` (or their own ``.unload()``). Pair with
    ``handle.remote(..., multiplexed_model_id=...)`` so the pow-2 router
    steers requests toward replicas already holding the model."""

    def wrap(fn: Callable) -> _MultiplexedMethod:
        return _MultiplexedMethod(fn, max_num_models_per_replica, unload)

    return wrap if _fn is None else wrap(_fn)


class Application:
    """A deployment bound to its constructor arguments (ref
    ``Deployment.bind`` building an app graph node)."""

    def __init__(self, deployment: "Deployment", args: tuple, kwargs: dict):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs

    @property
    def name(self) -> str:
        return self.deployment.name


class Deployment:
    """A user callable plus its deployment options (ref serve.Deployment)."""

    def __init__(self, target: Callable, config: DeploymentConfig,
                 explicit: Optional[frozenset] = None):
        self._target = target
        self._config = config
        # Field names the user set via options(): an explicit override must
        # beat the @batch decorator's defaults in run().
        self._explicit = explicit or frozenset()
        functools.update_wrapper(self, target, updated=())

    @property
    def name(self) -> str:
        return self._config.name

    def options(self, **overrides: Any) -> "Deployment":
        """Copy with config overrides (ref Deployment.options)."""
        cfg_fields = {f for f in DeploymentConfig.__dataclass_fields__}
        bad = set(overrides) - cfg_fields
        if bad:
            raise TypeError(f"unknown deployment options: {sorted(bad)}")
        merged = DeploymentConfig.from_json(
            {**self._config.to_json(), **{
                k: v for k, v in overrides.items() if k != "autoscaling"
            }}
        )
        if "autoscaling" in overrides:
            merged.autoscaling = overrides["autoscaling"]
        return Deployment(
            self._target, merged, self._explicit | frozenset(overrides)
        )

    def bind(self, *args: Any, **kwargs: Any) -> Application:
        return Application(self, args, kwargs)

    def _make_factory(
        self, args: tuple, kwargs: dict
    ) -> Callable[[], Callable[[List[Any]], Sequence[Any]]]:
        """Replica factory: constructs the user callable per replica, then
        adapts per-request callables to the replica's batch contract."""
        target = self._target
        raw = target.__call__ if inspect.isclass(target) else target
        marked = getattr(raw, _BATCH_ATTR, None)
        # unwrap: a logging/timing decorator with functools.wraps hides the
        # generator-ness of the underlying callable.
        if marked is None and inspect.isgeneratorfunction(inspect.unwrap(raw)):
            # The replica's generator contract is batch-shaped (yield one
            # chunk list per wave); silently promoting an unmarked
            # per-request generator would hand it a payload LIST and
            # misread its yields. Fail at deploy time, not mid-request.
            raise TypeError(
                f"{self.name}: generator callables stream whole batches "
                "and must opt in with @serve.batch"
            )

        def factory() -> Callable[[List[Any]], Sequence[Any]]:
            if inspect.isclass(target):
                instance = target(*args, **kwargs)
                call = instance.__call__
            elif args or kwargs:
                call = functools.partial(target, *args, **kwargs)
            else:
                call = target

            if marked is not None:
                return call  # already list -> list (or generator)

            def per_request(payloads: List[Any]) -> List[Any]:
                return [call(p) for p in payloads]

            # The replica's user_config hook looks for `reconfigure` on
            # the callable; surface the instance's through the wrapper.
            instance_hook = getattr(
                getattr(call, "__self__", None), "reconfigure", None
            )
            if callable(instance_hook):
                per_request.reconfigure = instance_hook
            return per_request

        return factory

    def batch_options(self) -> Optional[Dict[str, float]]:
        target = self._target
        if inspect.isclass(target):
            return getattr(target.__call__, _BATCH_ATTR, None)
        return getattr(target, _BATCH_ATTR, None)


def deployment(
    _target: Optional[Callable] = None,
    *,
    name: Optional[str] = None,
    num_replicas: int = 1,
    max_ongoing_requests: int = 256,
    max_restarts: int = 3,
    autoscaling: Any = None,
    user_config: Optional[Dict[str, Any]] = None,
    chips_per_replica: int = 0,
    placement_strategy: str = "PACK",
) -> Callable:
    """``@serve.deployment`` equivalent: turn a class or function into a
    deployable unit. Batching is per-request unless the callable opts in
    with ``@batch``."""

    def wrap(target: Callable) -> Deployment:
        cfg = DeploymentConfig(
            name=name or target.__name__,
            num_replicas=num_replicas,
            max_ongoing_requests=max_ongoing_requests,
            max_restarts=max_restarts,
            autoscaling=autoscaling,
            user_config=dict(user_config or {}),
            chips_per_replica=chips_per_replica,
            placement_strategy=placement_strategy,
        )
        return Deployment(target, cfg)

    return wrap if _target is None else wrap(_target)


# --- module-level controller (the singleton controller-actor role) ---------

_state_lock = threading.Lock()
_controller: Optional[ServeController] = None
_proxy: Optional[HTTPProxy] = None


def _get_controller() -> ServeController:
    global _controller
    with _state_lock:
        if _controller is None:
            _controller = ServeController()
            _controller.start()
        return _controller


def run(
    app: Application,
    *,
    route_prefix: Optional[str] = None,
    controller: Optional[ServeController] = None,
    default_slo_ms: float = 30_000.0,
) -> DeploymentHandle:
    """Deploy an application and return its handle (ref serve.run,
    ``api.py:463``). With ``route_prefix`` the deployment is also published
    on the module HTTP proxy (started on first use)."""
    if not isinstance(app, Application):
        raise TypeError(
            "run() takes Deployment.bind(...); got "
            f"{type(app).__name__} — decorate with @deployment and bind"
        )
    ctl = controller or _get_controller()
    dep = app.deployment
    cfg = dep._config
    # getattr resolves each name through the MRO, so a subclass override
    # shadows its base's descriptor — only ACTIVE loaders count (an
    # inactive base bound would under-advertise the cache size).
    mux_bounds = [
        v._max_models
        for v in (getattr(dep._target, n, None) for n in dir(dep._target))
        if isinstance(v, _MultiplexedMethod)
    ] if inspect.isclass(dep._target) else []
    if mux_bounds and "max_multiplexed_models" not in dep._explicit:
        # Advertised residency must match the tightest real cache bound,
        # or the router steers traffic to replicas that already evicted
        # the model.
        cfg = DeploymentConfig.from_json(cfg.to_json())
        cfg.max_multiplexed_models = min(mux_bounds)
    bopts = dep.batch_options()
    if bopts is not None:
        # @batch values are defaults; options() overrides win (both knobs
        # are plain DeploymentConfig fields the user may have set).
        cfg = DeploymentConfig.from_json(cfg.to_json())
        if "max_batch_size" not in dep._explicit:
            cfg.max_batch_size = int(bopts["max_batch_size"])
        if "batch_wait_timeout_s" not in dep._explicit:
            cfg.batch_wait_timeout_s = float(bopts["batch_wait_timeout_s"])
    router = ctl.deploy(cfg, factory=dep._make_factory(app.args, app.kwargs))
    handle = DeploymentHandle(router, default_slo_ms=default_slo_ms,
                              default_qos_class=cfg.default_qos_class)
    if route_prefix is not None:
        proxy = _get_proxy()
        # The proxy's admission checks must grade against THIS
        # controller's policy table/governor state (one shared instance,
        # so the control loop's degrade decisions bind the front door).
        proxy.admission = ctl.admission
        proxy.router.set_route(route_prefix, handle)
    return handle


def _get_proxy() -> HTTPProxy:
    global _proxy
    with _state_lock:
        if _proxy is None:
            from ray_dynamic_batching_tpu.serve.proxy import ProxyRouter

            _proxy = HTTPProxy(ProxyRouter(), port=0)
            _proxy.start()
        return _proxy


def get_proxy() -> Optional[HTTPProxy]:
    """The module proxy, if any route was published."""
    return _proxy


def get_deployment_handle(
    name: str, default_slo_ms: float = 30_000.0
) -> DeploymentHandle:
    """Handle to an already-running deployment (ref
    ``serve.get_deployment_handle``)."""
    ctl = _get_controller()
    return DeploymentHandle(
        ctl.get_router(name), default_slo_ms=default_slo_ms
    )


def status() -> Dict[str, Any]:
    """Deployment/replica state of the module controller (ref
    ``serve.status`` / the status CLI). Empty when nothing is running —
    asking must not START a controller as a side effect."""
    with _state_lock:
        ctl = _controller
    return ctl.status() if ctl is not None else {}


def delete(name: str) -> None:
    """Tear down one deployment (ref serve.delete)."""
    _get_controller().delete_deployment(name)


def shutdown() -> None:
    """Stop the module controller and proxy (ref serve.shutdown)."""
    global _controller, _proxy
    with _state_lock:
        ctl, proxy = _controller, _proxy
        _controller = None
        _proxy = None
    if proxy is not None:
        proxy.stop()
    if ctl is not None:
        ctl.shutdown()
