"""Dashboard — HTTP state/metrics surface with a minimal HTML front end.

Re-creates the reference's dashboard head (``python/ray/dashboard/head.py:61``
aiohttp server + per-module backends + React client) at the scale this
framework needs: a threaded HTTP server exposing

- ``GET /``            auto-refreshing HTML view (deployments, replicas,
                       queue SLO table)
- ``GET /api/state``   full cluster state JSON (StateAPI.summary)
- ``GET /metrics``     Prometheus text exposition

The heavy lifting (state aggregation) lives in
:class:`ray_dynamic_batching_tpu.state.StateAPI`; this module is transport.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ray_dynamic_batching_tpu.serve.proxy import _to_jsonable
from ray_dynamic_batching_tpu.state import StateAPI
from ray_dynamic_batching_tpu.utils.logging import get_logger

logger = get_logger("dashboard")

_PAGE = """<!doctype html>
<html><head><title>rdb-tpu dashboard</title>
<style>
 body { font-family: monospace; margin: 2em; background: #111; color: #ddd; }
 table { border-collapse: collapse; margin: 1em 0; }
 td, th { border: 1px solid #444; padding: 4px 10px; text-align: left; }
 th { background: #222; }
 .ok { color: #7c4; } .warning { color: #fb3; } .CRITICAL { color: #f55; }
 h2 { color: #8ac; }
 .timeline { position: relative; height: 22px; margin: 0.5em 0;
             background: #1a1a1a; border: 1px solid #444; }
 .tl { position: absolute; top: 2px; font-size: 14px; cursor: default; }
 .tl.rate_change { color: #fb3; } .tl.quarantine, .tl.heal { color: #f55; }
 .tl.manual, .tl.deploy, .tl.scale { color: #7c4; }
 .tl.rolling_update, .tl.health { color: #8ac; }
 td.diff { max-width: 40em; overflow-wrap: anywhere; }
</style></head>
<body>
<h1>ray_dynamic_batching_tpu</h1>
<div id="root">loading...</div>
<script>
function esc(v) {  // names/ids are arbitrary strings: escape before innerHTML
  return String(v).replace(/[&<>"']/g,
    c => ({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]));
}
async function tick() {
  try {
    const s = await (await fetch('/api/state')).json();
    const thr = s.slo_thresholds ?? {good: 0.98, warn: 0.95};
    let html = '';
    if (s.deployments.length) {
      html += '<h2>deployments</h2><table><tr><th>name</th><th>replicas</th>'
            + '<th>target</th><th>healthy</th></tr>';
      for (const d of s.deployments)
        html += `<tr><td>${esc(d.name)}</td><td>${d.running_replicas ?? ''}</td>`
              + `<td>${d.target_replicas ?? d.num_replicas ?? ''}</td>`
              + `<td>${d.healthy ?? true}</td></tr>`;
      html += '</table>';
    }
    if (s.replicas.length) {
      html += '<h2>replicas</h2><table><tr><th>deployment</th><th>id</th>'
            + '<th>healthy</th><th>queue</th><th>accepting</th></tr>';
      for (const r of s.replicas)
        html += `<tr><td>${esc(r.deployment)}</td><td>${esc(r.replica_id)}</td>`
              + `<td>${r.healthy}</td><td>${r.queue_len}</td>`
              + `<td>${r.accepting}</td></tr>`;
      html += '</table>';
    }
    const queues = Object.entries(s.queues ?? {});
    if (queues.length) {
      html += '<h2>queues (SLO)</h2><table><tr><th>model</th><th>p95 ms</th>'
            + '<th>p99 ms</th><th>depth</th><th>SLO %</th><th>status</th></tr>';
      for (const [name, q] of queues) {
        const c = q.slo_compliance ?? 1;
        const st = c >= thr.good ? 'ok' : c >= thr.warn ? 'warning' : 'CRITICAL';
        html += `<tr><td>${esc(name)}</td><td>${(q.latency_p95_ms??0).toFixed(1)}</td>`
              + `<td>${(q.latency_p99_ms??0).toFixed(1)}</td><td>${q.depth??0}</td>`
              + `<td class="${st}">${(c*100).toFixed(1)}%</td>`
              + `<td class="${st}">${st}</td></tr>`;
      }
      html += '</table>';
    }
    const obs = s.observatory ?? {};
    const alerts = Object.entries(obs.alerts?.states ?? {});
    if (alerts.length) {
      // SLO observatory: burn-rate alert machine per (deployment/qos).
      html += '<h2>SLO observatory (burn-rate alerts)</h2>'
            + '<table><tr><th>deployment/qos</th><th>state</th>'
            + '<th>fast burn</th><th>slow burn</th></tr>';
      for (const [key, a] of alerts) {
        const st = a.state === 'ok' ? 'ok'
                 : a.state === 'page' ? 'CRITICAL' : 'warning';
        html += `<tr><td>${esc(key)}</td>`
              + `<td class="${st}">${esc(a.state)}</td>`
              + `<td>${a.fast_burn == null ? '—' : a.fast_burn.toFixed(2)}</td>`
              + `<td>${a.slow_burn == null ? '—' : a.slow_burn.toFixed(2)}</td>`
              + `</tr>`;
      }
      html += '</table>';
    }
    const fc = Object.entries(obs.forecast ?? {});
    if (fc.length) {
      html += '<h2>arrival forecast error</h2><table><tr><th>model</th>'
            + '<th>scored</th><th>refused</th><th>p50 |err| rps</th>'
            + '<th>p95 |err| rps</th></tr>';
      for (const [name, f] of fc)
        html += `<tr><td>${esc(name)}</td><td>${f.scored}</td>`
              + `<td>${f.refused}</td>`
              + `<td>${f.p50_abs_err_rps == null ? '—' : f.p50_abs_err_rps.toFixed(2)}</td>`
              + `<td>${f.p95_abs_err_rps == null ? '—' : f.p95_abs_err_rps.toFixed(2)}</td></tr>`;
      html += '</table>';
    }
    const fid = Object.entries(obs.fidelity?.last?.models ?? {});
    if (fid.length) {
      html += '<h2>sim-fidelity drift</h2><table><tr><th>model</th>'
            + '<th>drifting hops</th><th>ungraded</th></tr>';
      for (const [name, r] of fid) {
        const bad = (r.drifting_hops ?? []).join(', ');
        html += `<tr><td>${esc(name)}</td>`
              + `<td class="${bad ? 'CRITICAL' : 'ok'}">${esc(bad || 'none')}</td>`
              + `<td>${esc(Object.keys(r.ungraded ?? {}).join(', '))}</td></tr>`;
      }
      html += '</table>';
    }
    const audit = s.audit ?? [];
    if (audit.length) {
      // Replan timeline: one marker per decision, positioned by wall time
      // over the window the ring covers, colored by trigger.
      html += '<h2>scheduler audit (replans &amp; control decisions)</h2>';
      const t0 = audit[0].wall_time, t1 = audit[audit.length - 1].wall_time;
      const span = Math.max(1e-9, t1 - t0);
      html += '<div class="timeline">' + audit.map(a => {
        const left = ((a.wall_time - t0) / span * 97).toFixed(2);
        const tip = `${new Date(a.wall_time * 1000).toLocaleTimeString()} `
                  + `${a.domain}/${a.trigger} ${a.key ?? ''}`;
        return `<span class="tl ${esc(a.trigger)}" style="left:${left}%"`
             + ` title="${esc(tip)}">&#9679;</span>`;
      }).join('') + '</div>';
      html += '<table><tr><th>time</th><th>domain</th><th>trigger</th>'
            + '<th>key</th><th>cost</th><th>old &rarr; new</th></tr>';
      for (const a of audit.slice(-12).reverse()) {
        const d = a.diff ?? {};
        let change;
        if (d.engines_changed !== undefined) {
          change = Object.entries(d.engines_changed).map(([e, c]) =>
            `engine${e}: [${(c.old ?? []).join(' ')}] → `
            + `[${(c.new ?? []).join(' ')}]`).join('; ')
            || 'no movement';
        } else {
          change = Object.entries(d).map(([k, v]) =>
            `${k}=${JSON.stringify(v)}`).join(', ') || (a.note ?? '');
        }
        html += `<tr><td>${new Date(a.wall_time * 1000).toLocaleTimeString()}`
              + `</td><td>${esc(a.domain)}</td><td>${esc(a.trigger)}</td>`
              + `<td>${esc(a.key ?? '')}</td>`
              + `<td>${(a.migration_cost ?? 0).toFixed(1)}</td>`
              + `<td class="diff">${esc(change)}</td></tr>`;
      }
      html += '</table>';
    }
    document.getElementById('root').innerHTML = html || 'no state yet';
  } catch (e) {
    document.getElementById('root').innerHTML = 'fetch failed: ' + esc(e);
  }
}
tick(); setInterval(tick, 2000);
</script>
</body></html>
"""


class DashboardServer:
    """Threaded HTTP server over a StateAPI (default bind 127.0.0.1:8265 —
    the reference dashboard's port)."""

    def __init__(self, state: StateAPI, host: str = "127.0.0.1",
                 port: int = 8265) -> None:
        self.state = state
        dashboard = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route to framework logger
                logger.debug("dashboard: " + fmt, *args)

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 - http.server API
                try:
                    if self.path == "/" or self.path == "/index.html":
                        self._send(200, _PAGE.encode(), "text/html")
                    elif self.path == "/api/state":
                        body = json.dumps(
                            _to_jsonable(dashboard.state.summary())
                        ).encode()
                        self._send(200, body, "application/json")
                    elif self.path == "/metrics":
                        # Same negotiation as the proxy: exemplars only on
                        # the OpenMetrics grammar.
                        accept = self.headers.get("Accept", "") or ""
                        if "application/openmetrics-text" in accept:
                            self._send(
                                200,
                                dashboard.state.registry
                                .openmetrics_text().encode(),
                                "application/openmetrics-text; "
                                "version=1.0.0; charset=utf-8",
                            )
                        else:
                            self._send(
                                200, dashboard.state.metrics_text().encode(),
                                "text/plain; version=0.0.4",
                            )
                    elif self.path == "/-/healthz":
                        self._send(200, b"ok", "text/plain")
                    else:
                        self._send(404, b"not found", "text/plain")
                except Exception as e:  # noqa: BLE001 — keep serving
                    logger.warning("dashboard handler error: %s", e)
                    try:
                        self._send(500, str(e).encode(), "text/plain")
                    except Exception:  # noqa: BLE001 — client gone
                        pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "DashboardServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="dashboard", daemon=True
        )
        self._thread.start()
        logger.info("dashboard listening on :%d", self.port)
        return self

    def stop(self) -> None:
        # shutdown() blocks forever unless serve_forever() is running
        if self._thread is not None:
            self._server.shutdown()
            self._thread.join(timeout=5)
            self._thread = None
        self._server.server_close()
