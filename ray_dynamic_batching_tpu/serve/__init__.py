"""Serving substrate: controller, routers, replicas, handles, long poll.

TPU-native re-creation of Ray Serve's architecture (SURVEY.md §2.3): a
controller reconciles deployment state and checkpoints it; routers schedule
requests over replicas with power-of-two-choices; replicas run user
callables with size-or-timeout batching; config changes flow over long poll.
"""

from ray_dynamic_batching_tpu.serve.api import (
    Application,
    Deployment,
    batch,
    delete,
    deployment,
    get_deployment_handle,
    multiplexed,
    run,
    shutdown,
    status,
)
from ray_dynamic_batching_tpu.serve.admission import (
    AdmissionController,
    AdmissionPolicy,
    AdmissionRejected,
    TokenBucket,
)
from ray_dynamic_batching_tpu.serve.autoscaling import (
    AutoscalingConfig,
    AutoscalingPolicy,
)
from ray_dynamic_batching_tpu.serve.controller import (
    DeploymentConfig,
    ServeController,
)
from ray_dynamic_batching_tpu.serve.fabric import (
    ControlFabric,
    FabricUnreachable,
    default_fabric,
    parse_partition_spec,
    reset_fabric,
)
from ray_dynamic_batching_tpu.serve.frontdoor import (
    FrontDoor,
    FrontDoorShard,
    GlobalBudget,
    HashRing,
)
from ray_dynamic_batching_tpu.serve.store import (
    CompactedLogError,
    ControllerStore,
    InMemoryStore,
    LeaderLease,
    ReplicaCatalog,
    ReplicatedStore,
    StaleEpochError,
    StoreLog,
    StoreSnapshot,
)
from ray_dynamic_batching_tpu.serve.failover import (
    DrainEvicted,
    FailoverManager,
    FailoverPolicy,
    HedgeManager,
    HedgePolicy,
    ReplicaDeadError,
    SliceDeadError,
    RetriesExhausted,
    RetryableSystemError,
    is_retryable,
    is_shed,
    reject_disposition,
)
from ray_dynamic_batching_tpu.serve.grayhealth import (
    GrayHealthMonitor,
    GrayHealthPolicy,
)
from ray_dynamic_batching_tpu.serve.handle import DeploymentHandle
from ray_dynamic_batching_tpu.serve.llm import LLMDeployment, LLMReplica
from ray_dynamic_batching_tpu.serve.long_poll import LongPollClient, LongPollHost
from ray_dynamic_batching_tpu.serve.openai_api import CompletionsHandle
from ray_dynamic_batching_tpu.serve.proxy import HTTPProxy, ProxyRouter
from ray_dynamic_batching_tpu.serve.replica import Replica
from ray_dynamic_batching_tpu.serve.router import Router
from ray_dynamic_batching_tpu.serve.schema import (
    ServeConfigSchema,
    apply_config,
    load_config,
    run_config,
)

__all__ = [
    "AdmissionController",
    "AdmissionPolicy",
    "AdmissionRejected",
    "TokenBucket",
    "reject_disposition",
    "Application",
    "Deployment",
    "batch",
    "delete",
    "deployment",
    "get_deployment_handle",
    "multiplexed",
    "run",
    "shutdown",
    "status",
    "AutoscalingConfig",
    "AutoscalingPolicy",
    "CompactedLogError",
    "CompletionsHandle",
    "ControlFabric",
    "ControllerStore",
    "default_fabric",
    "DeploymentConfig",
    "DeploymentHandle",
    "DrainEvicted",
    "FabricUnreachable",
    "FrontDoor",
    "FrontDoorShard",
    "GlobalBudget",
    "HashRing",
    "InMemoryStore",
    "LeaderLease",
    "ReplicaCatalog",
    "ReplicatedStore",
    "StaleEpochError",
    "StoreLog",
    "StoreSnapshot",
    "parse_partition_spec",
    "reset_fabric",
    "FailoverManager",
    "FailoverPolicy",
    "GrayHealthMonitor",
    "GrayHealthPolicy",
    "HedgeManager",
    "HedgePolicy",
    "ReplicaDeadError",
    "SliceDeadError",
    "RetriesExhausted",
    "RetryableSystemError",
    "is_retryable",
    "is_shed",
    "HTTPProxy",
    "LLMDeployment",
    "LLMReplica",
    "LongPollClient",
    "LongPollHost",
    "ProxyRouter",
    "Replica",
    "Router",
    "ServeConfigSchema",
    "ServeController",
    "apply_config",
    "load_config",
    "run_config",
]
