"""OpenAI-shaped completions surface over an LLM deployment.

Clients speaking the de-facto ``/v1/completions`` wire shape can hit the
framework without learning its native payloads: the adapter translates
{model, prompt, max_tokens, temperature, top_k, seed, stop, logit_bias,
user/session} into the decode engine's request fields and wraps
``DecodeResult`` back into the ``{id, object, choices, usage}`` response
envelope, with finish reasons mapped to the API's vocabulary.

Token ids, not text: this image has no tokenizer assets (zero egress), so
``prompt`` is a list of token ids and ``choices[].tokens`` carries ids.
The shape — not the tokenizer — is what client SDKs and gateways key on.
Streaming clients use the native NDJSON route (``{"stream": true}``,
``serve/proxy.py``); SSE framing is not replicated.

The reference's serve stack exposes raw handle routing only (its proxy
maps routes to deployments, ``_private/proxy.py:446``); an API-schema
adapter is a serving-completeness addition.
"""

from __future__ import annotations

import time
import uuid
from concurrent.futures import Future
from typing import Any, Dict, Optional

from ray_dynamic_batching_tpu.engine.request import BadRequest, normalize_qos
from ray_dynamic_batching_tpu.serve.handle import DeploymentHandle

_FINISH_MAP = {
    "eos": "stop",
    "length": "length",
    "capacity": "length",
}


def _bad(msg: str) -> BadRequest:
    return BadRequest(f"invalid completions request: {msg}")


def translate_request(body: Dict[str, Any],
                      default_max_tokens: int = 64) -> Dict[str, Any]:
    """OpenAI-shaped body -> native decode payload (raises ValueError on
    malformed input so the proxy answers 4xx, not a replica error)."""
    if not isinstance(body, dict):
        raise _bad("body must be a JSON object")
    prompt = body.get("prompt")
    if (not isinstance(prompt, (list, tuple)) or not prompt
            or not all(isinstance(t, int) for t in prompt)):
        raise _bad("prompt must be a non-empty list of token ids "
                   "(no tokenizer assets in this environment)")
    if body.get("n", 1) != 1:
        raise _bad("n > 1 is not supported")
    if body.get("stream"):
        # The NATIVE route streams NDJSON; this adapter's response
        # envelope is unary. Reject loudly (a dropped connection would
        # look like a proxy bug to the client).
        raise _bad("stream=true is not supported on /v1/completions; "
                   "use the deployment's native route with "
                   '{"stream": true}')
    payload: Dict[str, Any] = {"tokens": list(prompt)}
    # Coercion failures (int(None), dict([1,2]), float("hot")) are the
    # CLIENT's malformed fields: fold TypeError in too, or they escape
    # the BadRequest->400 path as server errors.
    try:
        payload["max_new_tokens"] = int(
            body.get("max_tokens", default_max_tokens)
        )
        if "temperature" in body:
            payload["temperature"] = float(body["temperature"])
        if "top_k" in body:
            payload["top_k"] = int(body["top_k"])
        if "top_p" in body:
            payload["top_p"] = float(body["top_p"])
        if "seed" in body:
            payload["seed"] = int(body["seed"])
        if "presence_penalty" in body:
            payload["presence_penalty"] = float(body["presence_penalty"])
        if "frequency_penalty" in body:
            payload["frequency_penalty"] = float(body["frequency_penalty"])
        if "stop" in body:  # token ids, per the module contract
            stop = body["stop"]
            if not isinstance(stop, (list, tuple)):
                stop = [stop]
            payload["stop_token_ids"] = [int(t) for t in stop]
        if "logit_bias" in body:
            payload["logit_bias"] = {
                int(t): float(v)
                for t, v in dict(body["logit_bias"]).items()
            }
    except BadRequest:
        raise
    except (TypeError, ValueError) as e:
        raise _bad(f"malformed field: {e}")
    # Session continuation key: prefer the explicit extension field,
    # fall back to OpenAI's standard `user` (stable per end-user, which
    # is exactly what conversation KV affinity wants).
    session = body.get("session_id", body.get("user"))
    if session is not None:
        payload["session_id"] = str(session)
    # QoS extension fields: `tenant` names the paying account, `qos_class`
    # the service tier — both ride the native payload so the handle stamps
    # them onto the Request (admission at the proxy graded them already).
    # An unknown class is a 400, mirror of the native doors.
    if body.get("tenant") is not None:
        payload["tenant"] = str(body["tenant"])
    if body.get("qos_class") is not None:
        payload["qos_class"] = normalize_qos(str(body["qos_class"]))
    return payload


def translate_response(model: str, prompt_len: int, result: Any
                       ) -> Dict[str, Any]:
    """DecodeResult -> completions response envelope."""
    n_out = len(result.tokens)
    return {
        "id": f"cmpl-{uuid.uuid4().hex[:24]}",
        "object": "text_completion",
        "created": int(time.time()),
        "model": model,
        "choices": [{
            "index": 0,
            "tokens": list(result.tokens),
            "finish_reason": _FINISH_MAP.get(result.finish_reason,
                                             result.finish_reason),
        }],
        "usage": {
            "prompt_tokens": prompt_len,
            "completion_tokens": n_out,
            "total_tokens": prompt_len + n_out,
        },
        "ttft_ms": round(result.ttft_ms, 1),
    }


class CompletionsHandle:
    """Drop-in for :class:`DeploymentHandle` on a proxy route: the proxy
    calls ``remote(body)`` and resolves the returned future — this wrapper
    translates on the way in and rewraps the resolved result on the way
    out, so ``ProxyRouter.set_route('/v1/completions', ...)`` is the whole
    integration."""

    def __init__(self, handle: DeploymentHandle, model: str,
                 default_max_tokens: int = 64,
                 default_slo_ms: Optional[float] = None):
        self._handle = handle
        self.model = model
        self.default_max_tokens = default_max_tokens
        self.default_slo_ms = default_slo_ms

    @property
    def deployment(self) -> str:
        return self._handle.deployment

    def remote(self, body: Any, **kwargs):
        out: Future = Future()
        try:
            payload = translate_request(body, self.default_max_tokens)
        except Exception as e:  # noqa: BLE001 — a synchronous raise would
            out.set_exception(e)  # drop the HTTP connection responseless
            return out
        if self.default_slo_ms is not None:
            kwargs.setdefault("slo_ms", self.default_slo_ms)
        inner = self._handle.remote(payload, **kwargs)

        def _done(f):
            if out.done():  # proxy timeout already cancelled the future
                return
            try:
                out.set_result(translate_response(
                    self.model, len(payload["tokens"]), f.result()
                ))
            except Exception as e:  # noqa: BLE001 — surface replica errors
                if not out.done():
                    out.set_exception(e)

        inner.add_done_callback(_done)
        return out
