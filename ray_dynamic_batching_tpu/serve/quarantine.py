"""Query-of-death quarantine — fingerprint and fence poison requests.

A "query of death" (Barroso et al., *The Datacenter as a Computer*) is a
request whose *content* crashes execution: re-dispatching it is not
recovery, it is replication of the fault into every replica that will
take it. Once the replica's batch bisection (serve/replica.py) isolates
one, the request is rejected terminally (``PoisonRequest``, 4xx, never
retried) and its *fingerprint* — a digest of model + payload shape and
content — lands here so every front door can refuse the identical query
at admission, before it reaches a replica.

:class:`QuarantineRegistry` follows the ``PrefixDigestDirectory``
gossip discipline (serve/router.py): bounded, merge-by-union with FIFO
eviction, a ``snapshot()`` the controller pushes to peers over the
ControlFabric + long-poll channel, and a ``changed`` bool so unchanged
ticks cost no fan-out. Entries are *hints with teeth*: a lost entry
only means one more bisection on its next appearance — correctness
never depends on the gossip converging.

Every verdict is priced in the shared planes: ``rdb_poison_total
{model,stage}`` counts isolations/front-door rejects/gossip merges, and
the registry writes ``poison_quarantine`` records into whatever audit
ring the router shares with it.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, Optional

import numpy as np

from ray_dynamic_batching_tpu.utils.concurrency import OrderedLock
from ray_dynamic_batching_tpu.utils import metrics as m

POISON_TOTAL = m.Counter(
    "rdb_poison_total",
    "Query-of-death verdicts by stage (isolated / front_door / merged)",
    tag_keys=("model", "stage"),
)

# Registry bound — same order as the digest directory's per-replica cap:
# a poison *campaign* larger than this rotates through FIFO eviction and
# pays one bisection per reappearance instead of unbounded memory.
DEFAULT_MAX_ENTRIES = 256


def _feed(h: "hashlib._Hash", obj: Any) -> None:
    """Canonical content walk: type-tagged so ``[1]`` and ``(1,)`` and
    ``"1"`` cannot collide, sorted dict order so wire-order noise cannot
    split one poison into many fingerprints."""
    if isinstance(obj, dict):
        h.update(b"d")
        for k in sorted(obj, key=str):
            _feed(h, str(k))
            _feed(h, obj[k])
    elif isinstance(obj, (list, tuple)):
        h.update(b"l%d:" % len(obj))
        for v in obj:
            _feed(h, v)
    elif isinstance(obj, np.ndarray):
        h.update(b"a" + str(obj.dtype).encode()
                 + str(obj.shape).encode() + obj.tobytes())
    elif isinstance(obj, bytes):
        h.update(b"b" + obj)
    elif isinstance(obj, bool):
        h.update(b"t" if obj else b"f")
    elif isinstance(obj, (int, float, str)) or obj is None:
        h.update(repr(obj).encode())
    else:
        # Arbitrary user objects: repr is the best stable proxy we have;
        # an unstable repr only weakens dedup, never correctness.
        h.update(b"o" + repr(obj).encode())


def poison_fingerprint(model: str, payload: Any) -> str:
    """Stable digest of (model, payload shape + content). The model is
    part of the identity: the same prompt may be poison to one decoder
    build and benign to another."""
    h = hashlib.blake2b(digest_size=16)
    _feed(h, model)
    _feed(h, payload)
    return h.hexdigest()


class QuarantineRegistry:
    """Bounded, gossipable set of poison fingerprints.

    Mirrors ``PrefixDigestDirectory``: mutators return ``changed`` so
    the controller's publish tick only fans out real deltas; ``merge``
    is a commutative union (last-writer metadata, FIFO eviction) so
    shards converge regardless of push order.
    """

    def __init__(self, max_entries: int = DEFAULT_MAX_ENTRIES) -> None:
        self.max_entries = max_entries
        # Rank: consulted under router_pool (front-door check) and from
        # replica execution threads; leaf-adjacent like the sketches.
        self._lock = OrderedLock("sketch")
        # fp -> {"model": str, "hits": int} — insertion-ordered for FIFO
        # eviction (Python dicts preserve insertion order).
        self._entries: Dict[str, Dict[str, Any]] = {}
        self.audit = None  # shared ring, wired by the router/controller
        self.evicted = 0

    # --- mutation ----------------------------------------------------------
    def add(self, fingerprint: str, model: str,
            stage: str = "isolated", note: str = "") -> bool:
        """Record a bisection verdict. Returns True when the fingerprint
        is new to this registry (callers gossip only on change)."""
        with self._lock:
            entry = self._entries.get(fingerprint)
            if entry is not None:
                entry["hits"] += 1
                return False
            self._entries[fingerprint] = {"model": model, "hits": 1}
            self._evict_locked()
        POISON_TOTAL.inc(tags={"model": model, "stage": stage})
        if self.audit is not None:
            self.audit.record(
                "poison_quarantine",
                key=model,
                observed={"fingerprint": fingerprint, "stage": stage},
                after={"quarantined": True},
                note=note or "query-of-death isolated by batch bisection",
            )
        return True

    def merge(self, entries: Dict[str, Dict[str, Any]]) -> bool:
        """Gossip union: absorb a peer snapshot. Hit counts take the max
        (summing would double-count a fingerprint gossiped both ways;
        max loses least information without double counting). Returns
        True when anything changed."""
        changed = False
        merged_models = []
        with self._lock:
            for fp, entry in entries.items():
                model = str(entry.get("model", ""))
                hits = int(entry.get("hits", 1))
                mine = self._entries.get(fp)
                if mine is None:
                    self._entries[fp] = {"model": model, "hits": hits}
                    merged_models.append(model)
                    changed = True
                elif hits > mine["hits"]:
                    mine["hits"] = hits
            if changed:
                self._evict_locked()
        for model in merged_models:
            POISON_TOTAL.inc(tags={"model": model, "stage": "merged"})
        return changed

    def _evict_locked(self) -> None:
        while len(self._entries) > self.max_entries:  # rdb-lint: disable=lock-discipline (_locked-suffix contract: both callers, add() and merge(), hold _lock; re-acquiring the non-reentrant lock here would self-deadlock)
            self._entries.pop(next(iter(self._entries)))  # rdb-lint: disable=lock-discipline (insertion-order FIFO eviction under the caller's _lock — see the _locked-suffix contract above)
            self.evicted += 1

    # --- query -------------------------------------------------------------
    def contains(self, fingerprint: str) -> bool:
        with self._lock:
            return fingerprint in self._entries

    def check(self, model: str, payload: Any) -> Optional[str]:
        """Front-door gate: returns the fingerprint when (model, payload)
        is quarantined, else None. Free when the registry is empty — the
        common case pays one len() check, no hashing."""
        with self._lock:
            if not self._entries:
                return None
        fp = poison_fingerprint(model, payload)
        with self._lock:
            entry = self._entries.get(fp)
            if entry is None:
                return None
            entry["hits"] += 1
        POISON_TOTAL.inc(tags={"model": model, "stage": "front_door"})
        return fp

    # --- observability / gossip --------------------------------------------
    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {fp: dict(e) for fp, e in self._entries.items()}

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "max_entries": self.max_entries,
                "evicted": self.evicted,
                "hits": sum(e["hits"] for e in self._entries.values()),
            }

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
