"""Request router — power-of-two-choices replica scheduling.

Re-creates Ray Serve's ``PowerOfTwoChoicesReplicaScheduler``
(``python/ray/serve/_private/replica_scheduler/pow_2_scheduler.py:52``; the
fulfillment loop with backoff is ``:673``): sample two replicas, route to the
one with the shorter queue, retry with exponential backoff while every
candidate is saturated. Queue lengths come from a short-TTL cache refreshed
on use (ref queue-len cache in the same file), and routing prefers
``locality_hint`` replicas when available (locality/multiplex awareness).

The router also aggregates per-deployment demand metrics for the autoscaler
(ref ``RouterMetricsManager``, ``serve/_private/router.py:43``).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, List, Optional, Sequence

from ray_dynamic_batching_tpu.engine.request import Request, RequestDropped
from ray_dynamic_batching_tpu.serve.replica import Replica
from ray_dynamic_batching_tpu.utils.chaos import chaos
from ray_dynamic_batching_tpu.utils.logging import get_logger
from ray_dynamic_batching_tpu.utils import metrics as m
from ray_dynamic_batching_tpu.utils.tracing import tracer

logger = get_logger("router")

ROUTED_TOTAL = m.Counter(
    "rdb_router_routed_total", "Requests routed", tag_keys=("deployment",)
)
ROUTER_REJECTED = m.Counter(
    "rdb_router_rejected_total", "Requests rejected after backoff",
    tag_keys=("deployment",),
)

QUEUE_LEN_CACHE_TTL_S = 0.1          # ref pow_2_scheduler queue-len cache
BACKOFF_INITIAL_S = 0.002
BACKOFF_MAX_S = 0.1


class _CachedLen:
    __slots__ = ("value", "at")

    def __init__(self, value: int, at: float) -> None:
        self.value = value
        self.at = at


class Router:
    """Routes requests for one deployment over its live replica set."""

    def __init__(
        self,
        deployment: str,
        replicas: Optional[Sequence[Replica]] = None,
        max_assign_timeout_s: float = 1.0,
    ) -> None:
        self.deployment = deployment
        self.max_assign_timeout_s = max_assign_timeout_s
        self._replicas: List[Replica] = list(replicas or [])
        self._lock = threading.Lock()
        self._len_cache: Dict[str, _CachedLen] = {}
        self.total_routed = 0

    # --- replica-set updates (pushed via long poll) -----------------------
    def update_replicas(self, replicas: Sequence[Replica]) -> None:
        with self._lock:
            self._replicas = list(replicas)
            self._len_cache.clear()
        logger.info(
            "%s: replica set -> %s",
            self.deployment, [r.replica_id for r in replicas],
        )

    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas)

    # --- pow-2 choice -----------------------------------------------------
    def _queue_len(self, replica: Replica, now: float) -> int:
        cached = self._len_cache.get(replica.replica_id)
        if cached is not None and now - cached.at < QUEUE_LEN_CACHE_TTL_S:
            return cached.value
        val = replica.queue_len()
        self._len_cache[replica.replica_id] = _CachedLen(val, now)
        return val

    def _choose(
        self,
        candidates: List[Replica],
        locality_hint: Optional[str],
        multiplexed_model_id: Optional[str] = None,
    ) -> Optional[Replica]:
        if not candidates:
            return None
        # Multiplexing first (ref pow_2_scheduler.py:52 candidate ranking):
        # replicas already holding the model avoid a load/compile stall.
        if multiplexed_model_id:
            warm = [
                r for r in candidates
                if multiplexed_model_id in getattr(r, "loaded_models", ())
            ]
            if warm:
                candidates = warm
        # Locality next: same-hint replicas tried as their own pool.
        if locality_hint:
            local = [
                r for r in candidates
                if getattr(r, "locality", None) == locality_hint
            ]
            if local:
                candidates = local
        now = time.monotonic()
        if len(candidates) == 1:
            chosen = candidates[0]
        else:
            a, b = random.sample(candidates, 2)
            chosen = a if self._queue_len(a, now) <= self._queue_len(b, now) else b
        return chosen

    def assign_request(
        self, request: Request, locality_hint: Optional[str] = None
    ) -> bool:
        """Route with pow-2 + backoff; reject after the assign timeout
        (ref fulfillment loop, pow_2_scheduler.py:673)."""
        # Assignment is its own traced hop: attempts > 1 means the request
        # burned wall-clock in backoff against saturated replicas — the
        # flight record shows that as router.assign duration, distinct
        # from queue wait on the chosen replica.
        with tracer().span(
            "router.assign", deployment=self.deployment, lane=self.deployment
        ) as sp:
            attempts = 0
            deadline = time.monotonic() + self.max_assign_timeout_s
            backoff = BACKOFF_INITIAL_S
            while True:
                attempts += 1
                candidates = [r for r in self.replicas() if r.accepting()]
                chosen = self._choose(
                    candidates, locality_hint, request.multiplexed_model_id
                )
                # chaos: a dropped assignment RPC — falls into the normal
                # backoff/retry path, like a lost PushActorTask in the
                # reference (only burns budget when there was a real
                # assignment to drop)
                if chosen is not None and chaos().should_fail("router.assign"):
                    chosen = None
                if chosen is not None and chosen.assign(request):
                    # Invalidate the cache entry so bursts spread out.
                    self._len_cache.pop(chosen.replica_id, None)
                    self.total_routed += 1
                    ROUTED_TOTAL.inc(tags={"deployment": self.deployment})
                    if sp is not None:
                        sp.attributes.update(
                            attempts=attempts, replica=chosen.replica_id
                        )
                    return True
                if time.monotonic() >= deadline:
                    ROUTER_REJECTED.inc(tags={"deployment": self.deployment})
                    request.reject(
                        RequestDropped(
                            f"{self.deployment}: no replica accepted within "
                            f"{self.max_assign_timeout_s}s"
                        )
                    )
                    if sp is not None:
                        sp.attributes.update(attempts=attempts, rejected=True)
                    return False
                time.sleep(backoff)  # rdb-lint: disable=event-loop-blocking (caller-thread backoff by contract: the asyncio proxy offloads handle.remote to its routing pool, so this never runs on the event loop)
                backoff = min(backoff * 2, BACKOFF_MAX_S)

    # --- autoscaler metrics (ref RouterMetricsManager) --------------------
    def demand_metrics(self) -> Dict[str, float]:
        reps = self.replicas()
        total = sum(r.queue_len() for r in reps)
        return {
            "total_ongoing": float(total),
            "num_replicas": float(len(reps)),
        }
