"""Request router — power-of-two-choices replica scheduling.

Re-creates Ray Serve's ``PowerOfTwoChoicesReplicaScheduler``
(``python/ray/serve/_private/replica_scheduler/pow_2_scheduler.py:52``; the
fulfillment loop with backoff is ``:673``): sample two replicas, route to the
one with the shorter queue, retry with exponential backoff while every
candidate is saturated. Queue lengths come from a short-TTL cache refreshed
on use (ref queue-len cache in the same file), and routing prefers
``locality_hint`` replicas when available (locality/multiplex awareness).

Fault tolerance rides on two layers here:

- a per-replica **circuit breaker**: N consecutive system failures trip
  the breaker and the replica leaves the pow-2 candidate pool; after a
  cooldown one half-open probe request tests it, success closes the
  breaker (ref: Serve routers deprioritizing replicas with failed health
  probes). Trip/recover events land in the controller's audit ring.
- a per-deployment :class:`~ray_dynamic_batching_tpu.serve.failover.
  FailoverManager` re-dispatching retryable batch failures and drained
  queues to a different replica under the request's admission deadline.

The router also aggregates per-deployment demand metrics for the autoscaler
(ref ``RouterMetricsManager``, ``serve/_private/router.py:43``).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ray_dynamic_batching_tpu.engine.request import Request, RequestDropped
from ray_dynamic_batching_tpu.serve.failover import (
    FailoverManager,
    FailoverPolicy,
    HedgeManager,
    HedgePolicy,
    PoisonRequest,
)
from ray_dynamic_batching_tpu.serve.quarantine import QuarantineRegistry
from ray_dynamic_batching_tpu.serve.retrybudget import (
    RetryBudget,
    RetryBudgetPolicy,
)
from ray_dynamic_batching_tpu.serve.grayhealth import (
    GrayHealthMonitor,
    GrayHealthPolicy,
)
from ray_dynamic_batching_tpu.serve.replica import Replica
from ray_dynamic_batching_tpu.utils.chaos import chaos
from ray_dynamic_batching_tpu.utils.concurrency import OrderedLock, assert_owner
from ray_dynamic_batching_tpu.utils.logging import get_logger
from ray_dynamic_batching_tpu.utils import metrics as m
from ray_dynamic_batching_tpu.utils.tracing import tracer

logger = get_logger("router")

ROUTED_TOTAL = m.Counter(
    "rdb_router_routed_total", "Requests routed",
    tag_keys=("deployment", "shard"),
    bounded_tags={"shard": m.DEFAULT_SHARD_TOP_K},
)
ROUTER_REJECTED = m.Counter(
    "rdb_router_rejected_total",
    "Requests rejected (reason: backoff_exhausted | breaker_open | "
    "quarantined)",
    tag_keys=("deployment", "reason", "shard"),
    bounded_tags={"shard": m.DEFAULT_SHARD_TOP_K},
)

QUEUE_LEN_CACHE_TTL_S = 0.1          # ref pow_2_scheduler queue-len cache
BACKOFF_INITIAL_S = 0.002
BACKOFF_MAX_S = 0.1

BREAKER_FAILURE_THRESHOLD = 3        # consecutive system failures to trip
BREAKER_COOLDOWN_S = 1.0             # open -> half-open probe delay
# Slow strikes (deadline-exceeded / hedge-lost dispatches) needed to trip a
# breaker on a replica that is slow-but-SUCCEEDING. Deliberately above the
# failure threshold (slowness is softer evidence than an error), and NOT
# reset by ordinary successes — that reset is exactly how a persistent
# straggler used to hold its breaker closed forever.
BREAKER_SLOW_THRESHOLD = 5


class CircuitBreaker:
    """Per-replica trip state: closed -> open -> half-open -> closed.

    Counts CONSECUTIVE system failures (the failover taxonomy's
    retryables — user errors never feed it); at ``threshold`` the
    replica leaves the candidate pool. After ``cooldown_s`` exactly one
    probe request is admitted (half-open); its outcome closes or
    re-opens the breaker. Thread-safe; reads are one lock acquire.
    """

    def __init__(self, threshold: int = BREAKER_FAILURE_THRESHOLD,
                 cooldown_s: float = BREAKER_COOLDOWN_S,
                 clock=time.monotonic,
                 slow_threshold: int = BREAKER_SLOW_THRESHOLD) -> None:
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self.slow_threshold = slow_threshold
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive_failures = 0
        self._slow_strikes = 0
        self._opened_at = 0.0
        self._half_open_at = 0.0
        self.trip_count = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _probe_expired_locked(self) -> bool:
        """A probe whose verdict never arrived (the probed request was
        stale-discarded in the queue, or the replica stopped before the
        batch ran) must not wedge the breaker half-open forever: after a
        cooldown's worth of silence the slot is forfeit and the next
        request may probe."""
        assert_owner(self._lock)  # _locked suffix: callers hold it
        return (
            self._state == "half_open"
            and self._clock() - self._half_open_at >= self.cooldown_s
        )

    def eligible(self) -> bool:
        """Read-only: may this replica be a routing CANDIDATE right now?
        (closed, or open with the cooldown elapsed — probe-eligible).
        Candidacy must not consume the probe slot: pow-2 may still route
        the request elsewhere."""
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "open":
                return self._clock() - self._opened_at >= self.cooldown_s
            return self._probe_expired_locked()

    def acquire(self) -> bool:
        """Claim the right to dispatch to this replica. In the open
        state past cooldown this admits exactly ONE half-open probe;
        further dispatches wait for the probe's verdict (or its expiry)."""
        with self._lock:
            if self._state == "closed":
                return True
            if (
                self._state == "open"
                and self._clock() - self._opened_at >= self.cooldown_s
            ) or self._probe_expired_locked():
                self._state = "half_open"
                self._half_open_at = self._clock()
                return True
            return False

    def release(self) -> None:
        """The acquired probe was never dispatched (the replica declined
        the assign): hand the slot back so the next request can probe."""
        with self._lock:
            if self._state == "half_open":
                self._state = "open"  # _opened_at unchanged: still eligible

    def record_failure(self) -> Optional[int]:
        """Count one system failure. On the trip edge (this failure
        OPENED the breaker) returns the actual consecutive-failure count
        — a failed half-open probe re-trips at 1, not at ``threshold`` —
        else None."""
        with self._lock:
            self._consecutive_failures += 1
            if self._state == "half_open" or (
                self._state == "closed"
                and self._consecutive_failures >= self.threshold
            ):
                self._state = "open"
                self._opened_at = self._clock()
                self.trip_count += 1
                return self._consecutive_failures
            return None

    def record_slow(self) -> Optional[int]:
        """Count one slow strike (a deadline-exceeded or hedge-lost
        dispatch — the request SUCCEEDED, too late). Strikes accumulate
        across ordinary successes (a straggler's slow successes must not
        keep resetting the evidence) and are CAPPED two ways: only a
        closed breaker accrues them (no stacking while open/half-open),
        and a half-open probe's success clears them (genuine recovery
        starts clean). Returns the strike count on the trip edge, else
        None."""
        with self._lock:
            if self._state != "closed":
                return None
            self._slow_strikes += 1
            if self._slow_strikes < self.slow_threshold:
                return None
            self._state = "open"
            self._opened_at = self._clock()
            self.trip_count += 1
            tripped_at = self._slow_strikes
            self._slow_strikes = 0
            return tripped_at

    def record_success(self) -> bool:
        """Count one success; True when it CLOSED an open/half-open
        breaker (recovery edge — which also clears slow strikes: the
        probe proved the replica healthy again)."""
        with self._lock:
            self._consecutive_failures = 0
            if self._state != "closed":
                self._state = "closed"
                self._slow_strikes = 0
                return True
            return False

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "slow_strikes": self._slow_strikes,
                "trips": self.trip_count,
            }


class _CachedLen:
    __slots__ = ("value", "at")

    def __init__(self, value: int, at: float) -> None:
        self.value = value
        self.at = at


class PrefixDigestDirectory:
    """Cluster-wide prefix index: replica_id -> published digest chains.

    Each replica publishes the digest keys of the prompt-page prefixes
    its paged pool (HBM prefix cache + host spill tier) can serve —
    16-byte hashes, never token bytes, bounded per replica. The router
    scores candidates by the LONGEST chain matching an incoming prompt
    and prefers the holders before the pow-2 pick, turning the
    per-replica CoW prefix cache into a cluster-wide tier: prompts
    sharing a system prefix converge on the replicas whose pools
    already hold it.

    Expiry is by replacement: every publish supersedes the replica's
    previous set wholesale (an entry the replica no longer advertises —
    evicted and not spilled — stops matching immediately), and
    :meth:`prune` drops departed replicas with the replica set. Thread-
    safe; reads are lock + dict probes.

    Partition semantics (ISSUE 12): the controller feeds this directory
    over the fabric's ``controller.digest_push`` edge, so publishes can
    be dropped, duplicated, or arrive late. Replacement makes all three
    harmless: a duplicated publish of the same set is detected unchanged
    (returns False, no long-poll notify), a dropped one leaves the LAST
    advertised set steering (stale hints degrade hit-rate, never
    correctness — the replica-level cache still validates), and the
    next reachable control tick republishes the truth.
    """

    def __init__(self, max_digests_per_replica: int = 256) -> None:
        self.max_digests_per_replica = int(max_digests_per_replica)
        self._lock = threading.Lock()
        self._page_size: Optional[int] = None
        # replica_id -> {digest_hex: chain_len}
        self._by_replica: Dict[str, Dict[str, int]] = {}
        self.publishes = 0

    def publish(self, replica_id: str, page_size: int,
                digests: Dict[str, int]) -> bool:
        """Replace ``replica_id``'s advertised set; returns True when the
        directory changed (the controller forwards changes — and only
        changes — over the long-poll channel)."""
        bounded = dict(list(digests.items())
                       [: self.max_digests_per_replica])
        with self._lock:
            if (self._by_replica
                    and self._page_size is not None
                    and page_size != self._page_size):
                # Mixed page sizes cannot share one digest space: chains
                # would never match across them. The CURRENT publishers'
                # size wins; a disagreeing publisher is dropped (it
                # still serves, just un-steered). Once every publisher
                # at the old size has left (rolling update to a new
                # page size), the first new publisher re-anchors it.
                self._by_replica.pop(replica_id, None)
                return False
            self._page_size = int(page_size)
            if self._by_replica.get(replica_id) == bounded:
                return False
            self._by_replica[replica_id] = bounded
            self.publishes += 1
            return True

    def prune(self, live: set) -> None:
        with self._lock:
            for rid in [r for r in self._by_replica if r not in live]:
                del self._by_replica[rid]

    def chain_for(self, payload: Any) -> List[str]:
        """The request's digest chain (hex level keys, deepest last) —
        empty when the directory is idle or the payload has no tokens
        spanning a full page. Hashing costs one O(L) pass; skipped
        entirely while nothing is published."""
        with self._lock:
            ps = self._page_size
            empty = not self._by_replica
        if empty or ps is None or not isinstance(payload, dict):
            return []
        tokens = payload.get("tokens")
        if not isinstance(tokens, (list, tuple)) or len(tokens) <= ps:
            return []
        from ray_dynamic_batching_tpu.engine.paging import digest_chain

        try:
            arr = np.asarray(tokens, np.int32)
        except (TypeError, ValueError, OverflowError):
            # Malformed client tokens must not crash the ROUTING layer —
            # un-steered routing proceeds and the replica-level
            # validation rejects the payload the same way it would have
            # before any digest was ever published.
            return []
        if arr.ndim != 1:
            return []  # nested lists convert, but are not a token row
        max_n = (arr.size - 1) // ps  # >=1 tail token stays prefillable
        return [k.hex() for k in digest_chain(arr, ps, max_n)]

    def best(self, chain: List[str],
             candidate_ids: List[str]) -> Tuple[int, Set[str]]:
        """(depth, holders): the longest chain level any candidate
        advertises, and every candidate advertising it. (0, {}) when
        nothing matches — the caller falls straight through to pow-2."""
        with self._lock:
            for depth in range(len(chain), 0, -1):
                key = chain[depth - 1]
                holders = {
                    rid for rid in candidate_ids
                    if key in self._by_replica.get(rid, ())
                }
                if holders:
                    return depth, holders
        return 0, set()

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "page_size": self._page_size,
                "replicas": {rid: dict(d)
                             for rid, d in self._by_replica.items()},
                "publishes": self.publishes,
            }


class Router:
    """Routes requests for one deployment over its live replica set."""

    def __init__(
        self,
        deployment: str,
        replicas: Optional[Sequence[Replica]] = None,
        max_assign_timeout_s: float = 1.0,
        failover_policy: Optional[FailoverPolicy] = None,
        breaker_threshold: int = BREAKER_FAILURE_THRESHOLD,
        breaker_cooldown_s: float = BREAKER_COOLDOWN_S,
        breaker_slow_threshold: int = BREAKER_SLOW_THRESHOLD,
        gray_policy: Optional[GrayHealthPolicy] = None,
        hedge_policy: Optional[HedgePolicy] = None,
        retry_budget_policy: Optional[RetryBudgetPolicy] = None,
    ) -> None:
        self.deployment = deployment
        self.max_assign_timeout_s = max_assign_timeout_s
        # Front-door shard identity for metric families. "0" is the
        # unsharded default; an embedder running a per-shard router tier
        # (N routers behind N front-door shards) stamps each router with
        # its shard id so the routed/rejected series split per shard.
        # The single-router-per-deployment topology this controller
        # builds keeps the default.
        self.shard = "0"
        # Cluster-wide prefix routing (ISSUE 11): per-replica digest
        # publications, matched against request prompts — longest
        # matching chain narrows the pow-2 pool to the replicas whose
        # page pools already hold the prefix.
        self.digests = PrefixDigestDirectory()
        self._replicas: List[Replica] = list(replicas or [])
        self._lock = OrderedLock("router_pool")
        self._len_cache: Dict[str, _CachedLen] = {}
        self.total_routed = 0
        # Per-replica breakers persist across replica-set updates: a
        # half-open replica keeps its probe state through an unrelated
        # scale event (entries for retired replicas are pruned).
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._breaker_threshold = breaker_threshold
        self._breaker_cooldown_s = breaker_cooldown_s
        self._breaker_slow_threshold = breaker_slow_threshold
        self.failover = FailoverManager(self, policy=failover_policy)
        # Gray-failure detection (serve/grayhealth.py): the controller
        # ticks it with per-replica latency sketches; routing consults it
        # — probationed replicas leave the pow-2 pool except for probes.
        self.gray = GrayHealthMonitor(deployment, policy=gray_policy)
        # Hedged dispatch (serve/failover.HedgeManager), per-deployment
        # opt-in: None = never hedge.
        self.hedge = (HedgeManager(self, hedge_policy)
                      if hedge_policy is not None else None)
        # Anti-amplification ledger (ISSUE 19): failover retries and hedge
        # fires draw from one per-deployment budget funded by first-attempt
        # volume. Permissive (track-only) unless a policy sets a fraction;
        # the governor's `congested` verdict zeroes it in either mode.
        self.retry_budget = RetryBudget(deployment, retry_budget_policy)
        # Query-of-death fence: fingerprints isolated by replica-side batch
        # bisection; checked at assign so a quarantined payload never
        # reaches a replica again. Gossiped cluster-wide by the controller
        # alongside the prefix digests.
        self.quarantine = QuarantineRegistry()
        # Optional decision ring (the controller shares its own): breaker
        # trip/recover events are control-plane decisions and belong next
        # to heals and scale moves.
        self._audit = None
        for r in self._replicas:
            self._wire(r)

    @property
    def audit(self):
        return self._audit

    @audit.setter
    def audit(self, ring) -> None:
        # One ring for every routing-layer decision family: breaker
        # trips, gray transitions, and (via _wire) queue displacement
        # sheds all land in the controller's shared timeline.
        self._audit = ring
        self.gray.audit = ring
        self.quarantine.audit = ring

    def _wire(self, replica: Replica) -> None:
        if hasattr(replica, "failure_sink"):
            replica.failure_sink = self.failover
        # Arms query-of-death bisection: a wired replica isolates poison
        # requests instead of rejecting every co-batched innocent, and its
        # verdicts land in the shared (gossiped) registry.
        if hasattr(replica, "quarantine"):
            replica.quarantine = self.quarantine
        # Class-aware displacement sheds are control-plane decisions: the
        # replica's queue records them into the same ring as heals,
        # breaker trips and governor transitions.
        queue = getattr(replica, "queue", None)
        if queue is not None and self.audit is not None:
            queue.audit = self.audit

    # --- replica-set updates (pushed via long poll) -----------------------
    def update_replicas(self, replicas: Sequence[Replica]) -> None:
        with self._lock:
            self._replicas = list(replicas)
            self._len_cache.clear()
            live = {r.replica_id for r in replicas}
            for rid in [b for b in self._breakers if b not in live]:
                del self._breakers[rid]
        self.gray.prune(live)
        self.digests.prune(live)
        for r in replicas:
            self._wire(r)
        logger.info(
            "%s: replica set -> %s",
            self.deployment, [r.replica_id for r in replicas],
        )

    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas)

    # --- circuit breaker (fed by the failover taxonomy) -------------------
    def _breaker(self, replica_id: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(replica_id)
            if br is None:
                br = self._breakers[replica_id] = CircuitBreaker(
                    self._breaker_threshold, self._breaker_cooldown_s,
                    slow_threshold=self._breaker_slow_threshold,
                )
            return br

    def record_replica_failure(self, replica_id: str) -> None:
        br = self._breaker(replica_id)
        tripped_at = br.record_failure()
        if tripped_at is not None:
            logger.warning(
                "%s: circuit breaker OPEN for %s after %d consecutive "
                "system failures", self.deployment, replica_id, tripped_at,
            )
            if self.audit is not None:
                self.audit.record(
                    "breaker_trip",
                    key=self.deployment,
                    observed={"replica": replica_id,
                              "consecutive_failures": tripped_at},
                    after={"state": "open"},
                    diff={"excluded": replica_id},
                )

    def record_replica_slow(self, replica_id: str) -> None:
        """One slow strike (deadline-exceeded / hedge-lost dispatch)
        against this replica's breaker. Soft evidence with its own
        higher threshold — but it accumulates across successes, so a
        slow-but-succeeding straggler eventually trips (PR-4 bugfix)."""
        br = self._breaker(replica_id)
        tripped_at = br.record_slow()
        if tripped_at is not None:
            logger.warning(
                "%s: circuit breaker OPEN for %s after %d slow strikes "
                "(deadline-exceeded/hedge-lost dispatches)",
                self.deployment, replica_id, tripped_at,
            )
            if self.audit is not None:
                self.audit.record(
                    "breaker_trip",
                    key=self.deployment,
                    observed={"replica": replica_id,
                              "slow_strikes": tripped_at},
                    after={"state": "open"},
                    diff={"excluded": replica_id},
                    note="slow-but-succeeding straggler (hedge/deadline "
                         "strikes)",
                )

    def record_replica_success(self, replica_id: str) -> None:
        br = self._breaker(replica_id)
        if br.record_success():
            logger.info(
                "%s: circuit breaker closed for %s (probe succeeded)",
                self.deployment, replica_id,
            )
            if self.audit is not None:
                self.audit.record(
                    "breaker_recover",
                    key=self.deployment,
                    observed={"replica": replica_id},
                    after={"state": "closed"},
                    diff={"readmitted": replica_id},
                )

    def breaker_states(self) -> Dict[str, Dict[str, object]]:
        with self._lock:
            return {rid: br.snapshot() for rid, br in self._breakers.items()}

    def requeue_drained(self, requests: List[Request], victim_id: str,
                        dead: bool = False) -> None:
        """Re-route a retired/unhealthy replica's drained queue through
        the failover path (deadline-budgeted, different replica) instead
        of erroring it back to callers. ``dead`` distinguishes a crashed
        replica (heal) from a planned retirement (rollout)."""
        self.failover.requeue(requests, victim_id, dead=dead)  # rdb-lint: disable=retry-amplification (drain salvage relocates admitted work; FailoverManager.requeue routes it budget-exempt by design)

    # --- pow-2 choice -----------------------------------------------------
    def _queue_len(self, replica: Replica, now: float) -> int:
        cached = self._len_cache.get(replica.replica_id)
        if cached is not None and now - cached.at < QUEUE_LEN_CACHE_TTL_S:
            return cached.value
        val = replica.queue_len()
        self._len_cache[replica.replica_id] = _CachedLen(val, now)
        return val

    def _choose(
        self,
        candidates: List[Replica],
        locality_hint: Optional[str],
        multiplexed_model_id: Optional[str] = None,
        digest_chain: Optional[List[str]] = None,
    ) -> Optional[Replica]:
        if not candidates:
            return None
        # Multiplexing first (ref pow_2_scheduler.py:52 candidate ranking):
        # replicas already holding the model avoid a load/compile stall.
        if multiplexed_model_id:
            warm = [
                r for r in candidates
                if multiplexed_model_id in getattr(r, "loaded_models", ())
            ]
            if warm:
                candidates = warm
        # Locality next: same-hint replicas tried as their own pool.
        if locality_hint:
            local = [
                r for r in candidates
                if getattr(r, "locality", None) == locality_hint
            ]
            if local:
                candidates = local
        # Cluster-wide prefix routing: narrow to the replicas advertising
        # the LONGEST digest chain matching this prompt — their page
        # pools already hold the prefix, so admission borrows pages
        # instead of recomputing them. Ties (several replicas at the
        # same depth) and no-match both fall through to the pow-2 pick
        # below; a preference must sharpen routing, never starve it.
        if digest_chain:
            depth, holders = self.digests.best(
                digest_chain, [r.replica_id for r in candidates]
            )
            if depth > 0:
                held = [r for r in candidates if r.replica_id in holders]
                if held:
                    candidates = held
        now = time.monotonic()
        if len(candidates) == 1:
            chosen = candidates[0]
        else:
            a, b = random.sample(candidates, 2)
            chosen = a if self._queue_len(a, now) <= self._queue_len(b, now) else b
        return chosen

    def assign_request(
        self,
        request: Request,
        locality_hint: Optional[str] = None,
        exclude: Optional[Set[str]] = None,
        timeout_s: Optional[float] = None,
    ) -> bool:
        """Route with pow-2 + backoff; reject after the assign timeout
        (ref fulfillment loop, pow_2_scheduler.py:673).

        ``exclude`` removes replicas by id from the candidate pool (the
        failover path re-dispatching away from the replica that just
        failed); ``timeout_s`` caps this call's backoff window below the
        router default (retries budget against the request's remaining
        admission deadline)."""
        # Quarantine fence FIRST — a known query of death must never
        # reach a replica again (a repeat would re-pay the bisection it
        # already lost). Free while the registry is empty; re-dispatches
        # hit it too, so an isolation elsewhere mid-flight still fences.
        fp = self.quarantine.check(self.deployment, request.payload)
        if fp is not None:
            ROUTER_REJECTED.inc(
                tags={"deployment": self.deployment,
                      "reason": "quarantined", "shard": self.shard}
            )
            request.reject(PoisonRequest(
                f"{self.deployment}: payload quarantined as query of "
                f"death (fingerprint {fp})",
                fingerprint=fp,
            ))
            return False
        # Assignment is its own traced hop: attempts > 1 means the request
        # burned wall-clock in backoff against saturated replicas — the
        # flight record shows that as router.assign duration, distinct
        # from queue wait on the chosen replica.
        with tracer().span(
            "router.assign", deployment=self.deployment, lane=self.deployment
        ) as sp:
            # Computed ONCE per assignment (one O(L) hash pass), empty
            # while no replica has published digests — the non-LLM hot
            # path pays two dict probes.
            digest_chain = self.digests.chain_for(request.payload)
            attempts = 0
            window_s = min(
                timeout_s if timeout_s is not None else
                self.max_assign_timeout_s,
                self.max_assign_timeout_s,
            )
            deadline = time.monotonic() + window_s
            backoff = BACKOFF_INITIAL_S
            breaker_excluded_last = False
            while True:
                attempts += 1
                accepting = [r for r in self.replicas() if r.accepting()]
                if exclude:
                    preferred = [
                        r for r in accepting if r.replica_id not in exclude
                    ]
                    # Soft exclusion: on a sole-replica deployment, a
                    # failover retry back to the same (possibly transiently
                    # failed) replica beats dropping the request.
                    if preferred:
                        accepting = preferred
                # Breaker gate: open-breaker replicas leave the pow-2 pool
                # (read-only eligibility — the probe slot is claimed only
                # at dispatch, below, so an unchosen candidate never
                # wedges the breaker in half-open).
                graded = [
                    r for r in accepting
                    if self._breaker(r.replica_id).eligible()
                ]
                breaker_excluded_last = bool(accepting) and not graded
                # Gray gate: probationed replicas are DRAINED from the
                # pow-2 pool except when their probe window is due (the
                # half-open arm, generalized to slowness); ejected ones
                # never serve. A verdict that would empty the pool falls
                # back to the non-ejected set — a wrong gray call must
                # degrade latency, never blackhole the deployment.
                candidates = [
                    r for r in graded
                    if self.gray.is_candidate(r.replica_id)
                ]
                if not candidates:
                    candidates = [
                        r for r in graded
                        if self.gray.state(r.replica_id) != "ejected"
                    ] or graded
                chosen = self._choose(
                    candidates, locality_hint, request.multiplexed_model_id,
                    digest_chain=digest_chain,
                )
                # chaos: a dropped assignment RPC — falls into the normal
                # backoff/retry path, like a lost PushActorTask in the
                # reference (only burns budget when there was a real
                # assignment to drop)
                if chosen is not None and chaos().should_fail("router.assign"):
                    chosen = None
                if chosen is not None:
                    breaker = self._breaker(chosen.replica_id)
                    if not breaker.acquire():
                        chosen = None  # lost the half-open probe race
                if chosen is not None:
                    if chosen.assign(request):
                        # Invalidate the cache entry so bursts spread out.
                        self._len_cache.pop(chosen.replica_id, None)
                        request.attempts += 1
                        if request.attempts == 1:
                            # First dispatch funds the retry budget;
                            # re-dispatches drew from it before reaching
                            # this path (failover.submit / hedge._fire).
                            self.retry_budget.record_first_attempt()
                        # The hedge fire path reads this: a failover
                        # re-dispatch moves the request, and the timer
                        # armed at first assign must follow it.
                        request._assigned_replica = chosen.replica_id
                        self.total_routed += 1
                        ROUTED_TOTAL.inc(tags={"deployment": self.deployment,
                                               "shard": self.shard})
                        # A dispatch onto a probationed replica IS its
                        # probe: start the next probe window.
                        self.gray.mark_probe(chosen.replica_id)
                        if self.hedge is not None:
                            self.hedge.arm(request, chosen.replica_id)
                        if sp is not None:
                            sp.attributes.update(
                                attempts=attempts, replica=chosen.replica_id
                            )
                        return True
                    breaker.release()  # declined assign frees the probe slot
                if time.monotonic() >= deadline:
                    # The metric distinguishes "every live replica was
                    # breaker-excluded" from plain saturation backoff.
                    reason = (
                        "breaker_open" if breaker_excluded_last
                        else "backoff_exhausted"
                    )
                    ROUTER_REJECTED.inc(
                        tags={"deployment": self.deployment,
                              "reason": reason, "shard": self.shard}
                    )
                    exc = RequestDropped(
                        f"{self.deployment}: no replica accepted within "
                        f"{window_s:.3f}s ({reason})"
                    )
                    # The client surface keys on this: saturation backoff
                    # is a capacity shed (429), but every-replica-breaker-
                    # open is a SYSTEM condition (503/UNAVAILABLE) — see
                    # failover.reject_disposition.
                    exc.reason = reason
                    request.reject(exc)
                    if sp is not None:
                        sp.attributes.update(
                            attempts=attempts, rejected=True, reason=reason
                        )
                    return False
                time.sleep(backoff)  # rdb-lint: disable=event-loop-blocking (caller-thread backoff by contract: the asyncio proxy offloads handle.remote to its routing pool, so this never runs on the event loop)
                backoff = min(backoff * 2, BACKOFF_MAX_S)

    def close(self) -> None:
        """Stop the failover and hedge workers (terminal rejection of
        anything still pending belongs to the failover layer)."""
        self.failover.close()
        if self.hedge is not None:
            self.hedge.close()

    # --- autoscaler metrics (ref RouterMetricsManager) --------------------
    def demand_metrics(self) -> Dict[str, float]:
        reps = self.replicas()
        total = sum(r.queue_len() for r in reps)
        return {
            "total_ongoing": float(total),
            "num_replicas": float(len(reps)),
        }
