"""Request failover — one failure taxonomy, deadline-budgeted re-dispatch.

The reference substrate makes actor death survivable: tasks are retried
from ownership metadata (Ray, OSDI '18 lineage) and the Nexus-style SLO
planner assumes an admitted request either completes within deadline or
is counted SHED — never a spurious client-visible 500. This module is
the recovery half of that contract for the serve tier, shared by every
consumer of the taxonomy (replica, router, controller drain path, proxy
error mapping, sim re-enactment, chaos soak):

- **Taxonomy**: :func:`is_retryable` classifies a rejection into
  retryable *system* failures (chaos injection, replica death, drain
  evictions) vs. non-retryable *user* errors (``BadRequest``, callable
  bugs) and terminal *shed* outcomes (``RequestStale``,
  ``RequestDropped`` — deadline economics, not faults).
- **Deadline-budgeted retries**: :class:`FailoverManager` re-dispatches
  a retryable failure to a DIFFERENT replica with capped exponential
  backoff + seeded jitter, but only while the attempt budget holds and
  ``remaining_deadline >= profiled batch latency`` — otherwise the
  request is counted shed, exactly like the queue's stale discard.
- **At-most-once after first token**: a streaming request that already
  emitted a chunk is never retried (the client saw partial output);
  the failure surfaces as-is.

The circuit breaker lives in ``serve/router.py`` (it is a routing
concern); the manager feeds it per-replica failure/success signals so
the breaker, the retry decision, and the audit trail agree on one
taxonomy.
"""

from __future__ import annotations

import heapq
import itertools
import random
import threading
from dataclasses import dataclass
from typing import Any, List, Optional, Tuple

from ray_dynamic_batching_tpu.engine.request import (
    Request,
    RequestDropped,
    RequestStale,
    now_ms,
)
from ray_dynamic_batching_tpu.utils.chaos import ChaosInjected
from ray_dynamic_batching_tpu.utils.logging import get_logger
from ray_dynamic_batching_tpu.utils import metrics as m
from ray_dynamic_batching_tpu.utils.tracing import tracer

logger = get_logger("failover")

FAILOVER_RETRIES = m.Counter(
    "rdb_failover_retries_total", "Requests re-dispatched after a "
    "retryable system failure", tag_keys=("deployment",),
)
FAILOVER_SHED = m.Counter(
    "rdb_failover_shed_total", "Requests shed by the failover layer",
    tag_keys=("deployment", "reason"),
)


class RetryableSystemError(RuntimeError):
    """Base for failures the framework caused and may transparently
    retry on another replica — never the client's fault."""


class ReplicaDeadError(RetryableSystemError):
    """The serving replica died (loop crash, wedged callable) with this
    request in flight or queued."""


class DrainEvicted(RetryableSystemError):
    """The request was evicted from a draining replica's queue (heal /
    rolling update / plan migration) and must be re-routed."""


class RetriesExhausted(Exception):
    """Terminal: a retryable system failure burned its attempt budget.
    Maps to 503 + Retry-After (gRPC UNAVAILABLE) — the client may retry;
    the payload was never the problem."""

    def __init__(self, message: str, cause: Optional[Exception] = None):
        super().__init__(message)
        self.cause = cause


def is_retryable(exc: BaseException) -> bool:
    """True for system failures the failover layer may re-dispatch.

    ``ChaosInjected`` is the test-harness stand-in for every injected
    fault (dropped RPC, killed batch) and classifies retryable;
    ``RequestStale``/``RequestDropped`` are shed outcomes (terminal by
    design); everything else — ``BadRequest``, user-callable exceptions,
    contract violations — is a non-retryable user/server error whose
    retry would just fail again."""
    return isinstance(exc, (RetryableSystemError, ChaosInjected))


def is_shed(exc: BaseException) -> bool:
    """True for deadline-economics outcomes the SLO accounting counts as
    shed rather than errors (the planner's admitted-or-shed contract)."""
    return isinstance(exc, (RequestStale, RequestDropped))


@dataclass(frozen=True)
class RejectDisposition:
    """How one rejection surfaces to a client — ONE table shared by the
    HTTP proxy and ``grpc_proxy._error_status`` so the two front doors
    can never disagree on what a shed is."""

    kind: str                      # "user" | "capacity" | "system" | "internal"
    http_status: int
    grpc_code: str                 # grpc.StatusCode attribute name
    retry_after_s: Optional[float] = None


def reject_disposition(exc: BaseException) -> RejectDisposition:
    """Classify a request failure for the client surface.

    - **capacity** (429 / RESOURCE_EXHAUSTED + computed ``Retry-After``):
      admission rejects and queue sheds (full-queue drops, displacement,
      stale discards) — the system is healthy and saying "not now"; the
      retry hint comes from the rejecting layer (bucket refill time /
      queue drain estimate) with a 1 s floor-less fallback.
    - **system** (503 / UNAVAILABLE + ``Retry-After``): retryable system
      failures and exhausted failover budgets — the payload was never the
      problem; a different moment (heal, breaker close) may serve it.
    - **user** (400 / INVALID_ARGUMENT): the payload itself.
    - **internal** (500 / INTERNAL): genuine bugs — must alarm, never
      invite a retry."""
    from ray_dynamic_batching_tpu.engine.request import BadRequest
    from ray_dynamic_batching_tpu.serve.admission import AdmissionRejected

    if isinstance(exc, BadRequest):
        return RejectDisposition("user", 400, "INVALID_ARGUMENT")
    if getattr(exc, "reason", "") == "breaker_open":
        # Router terminal reject because EVERY live replica's breaker was
        # open: the system is failing, not merely full — 503, not 429.
        return RejectDisposition("system", 503, "UNAVAILABLE",
                                 retry_after_s=1.0)
    if isinstance(exc, AdmissionRejected) or is_shed(exc):
        return RejectDisposition(
            "capacity", 429, "RESOURCE_EXHAUSTED",
            retry_after_s=float(getattr(exc, "retry_after_s", 0.0) or 1.0),
        )
    if isinstance(exc, RetriesExhausted) or is_retryable(exc):
        return RejectDisposition("system", 503, "UNAVAILABLE",
                                 retry_after_s=1.0)
    return RejectDisposition("internal", 500, "INTERNAL")


def retry_after_header(disposition: RejectDisposition) -> Optional[str]:
    """HTTP ``Retry-After`` value (integer seconds, ceil'd — the header
    grammar takes no fractions; sub-second hints round up to 1)."""
    if disposition.retry_after_s is None:
        return None
    import math

    return str(max(1, math.ceil(disposition.retry_after_s)))


@dataclass
class FailoverPolicy:
    """Retry knobs — deadline is the real bound, attempts the backstop."""

    # Total dispatches (first send included). Sized above any plausible
    # consecutive-failure streak a bounded chaos budget can aim at one
    # request; the deadline budget is what actually stops hopeless work.
    max_attempts: int = 5
    backoff_initial_s: float = 0.002
    backoff_max_s: float = 0.05
    jitter: float = 0.5            # fraction of the backoff randomized
    seed: int = 0                  # jitter RNG seed (deterministic tests)


class FailoverManager:
    """Deadline-budgeted re-dispatch for one deployment's router.

    Replicas hand failed batches here (``on_batch_failure``); drained
    queues arrive via ``requeue``; both paths re-route each request to a
    different replica through ``router.assign_request(exclude=...)`` on
    a dedicated worker thread (a replica's hot loop must never block in
    another replica's backoff). Shed decisions reject with
    :class:`RequestStale` so every accounting surface — queue stats,
    soak, sim — reads them identically to a stale discard.
    """

    def __init__(self, router: Any,
                 policy: Optional[FailoverPolicy] = None) -> None:
        self.router = router
        self.policy = policy or FailoverPolicy()
        self._rng = random.Random(self.policy.seed)
        self._seq = itertools.count()
        # (due_monotonic_ms, seq, request, excluded_replica_id,
        #  submitted_ms — the failover hop span's start)
        self._heap: List[Tuple[float, int, Request, str, float]] = []
        self._cond = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        # --- accounting (surfaced via stats() -> router -> status()) ---
        self.retries = 0
        self.shed_deadline = 0
        self.shed_attempts = 0
        self.stream_aborted = 0

    # --- replica-facing sink ---------------------------------------------
    def on_batch_failure(self, replica: Any, batch: List[Request],
                         exc: Exception) -> None:
        """A replica's batch died on a retryable system failure: feed the
        breaker, then re-dispatch every request that may still be retried."""
        self.router.record_replica_failure(replica.replica_id)
        for req in batch:
            if req.stream is not None and req.stream.emitted > 0:
                # At-most-once after first token: the client consumed
                # partial output; a transparent replay would duplicate it.
                self.stream_aborted += 1
                req.reject(exc)
                continue
            self.submit(req, exc, exclude_replica=replica.replica_id)

    def on_batch_success(self, replica: Any) -> None:
        self.router.record_replica_success(replica.replica_id)

    # --- retry scheduling --------------------------------------------------
    def submit(self, request: Request, exc: Exception,
               exclude_replica: str = "", immediate: bool = False) -> bool:
        """Queue one re-dispatch (True) or reject terminally (False).

        ``immediate`` skips the backoff delay — drain evictions are not
        replica faults, so they re-route without penalty (still deadline-
        and attempt-budgeted)."""
        deployment = self.router.deployment
        if request.attempts >= self.policy.max_attempts:
            self.shed_attempts += 1
            FAILOVER_SHED.inc(
                tags={"deployment": deployment, "reason": "attempts"}
            )
            request.reject(RetriesExhausted(
                f"{request.request_id}: {request.attempts} attempts "
                f"exhausted (last failure: {exc})", cause=exc,
            ))
            return False
        delay_ms = 0.0 if immediate else self._backoff_ms(request.attempts)
        # Retry only if the request can still plausibly complete: the
        # queue's stale-discard rule (deadline < now + expected latency)
        # applied BEFORE burning a backoff + batch on a lost cause.
        if request.remaining_ms() < self._expected_latency_ms() + delay_ms:
            self.shed_deadline += 1
            FAILOVER_SHED.inc(
                tags={"deployment": deployment, "reason": "deadline"}
            )
            request.reject(RequestStale(
                f"{request.request_id}: deadline unreachable after system "
                f"failure ({exc})"
            ))
            return False
        with self._cond:
            # _stopped is authoritative only under the lock: a submit
            # racing close() past an unlocked check would push AFTER the
            # heap drain and leave a client future that never resolves.
            if not self._stopped:
                submitted_ms = m.now_ms()
                heapq.heappush(
                    self._heap,
                    (submitted_ms + delay_ms, next(self._seq), request,
                     exclude_replica, submitted_ms),
                )
                self._ensure_worker()
                self._cond.notify()
                scheduled = True
            else:
                scheduled = False
        if not scheduled:
            # Teardown: no worker to run the retry and no replica set to
            # land it on — terminal, not a silently resurrected thread.
            request.reject(RequestDropped(
                f"{deployment}: shutting down ({exc})"
            ))
            return False
        self.retries += 1
        FAILOVER_RETRIES.inc(tags={"deployment": deployment})
        return True

    def requeue(self, requests: List[Request], victim_id: str,
                dead: bool = False) -> None:
        """Drain-and-requeue: a retired/unhealthy replica's queued work
        re-enters routing through the failover path (no backoff — the
        victim failed, not the request). ``dead=True`` marks the heal
        path (the replica crashed/wedged: :class:`ReplicaDeadError`);
        planned retirements (rolling update, scale-down salvage) stay
        :class:`DrainEvicted`."""
        for req in requests:
            exc: RetryableSystemError = (
                ReplicaDeadError(f"{victim_id} died with request queued")
                if dead else DrainEvicted(f"drained from {victim_id}")
            )
            self.submit(req, exc, exclude_replica=victim_id, immediate=True)

    # --- internals ----------------------------------------------------------
    def _backoff_ms(self, attempts: int) -> float:
        base = min(
            self.policy.backoff_initial_s * (2 ** max(attempts - 1, 0)),
            self.policy.backoff_max_s,
        )
        return base * (1.0 + self.policy.jitter * self._rng.random()) * 1000.0

    def _expected_latency_ms(self) -> float:
        """Profiled cost of one more attempt: the worst recent p50 across
        the replica set (total request latency, so queue wait is priced
        in). 0.0 before any completion — never block the first retries."""
        worst = 0.0
        for r in self.router.replicas():
            queue = getattr(r, "queue", None)
            if queue is None:
                continue
            try:
                worst = max(worst, queue.latency_window.percentile(0.5))
            except Exception:  # noqa: BLE001 — stats must not break retries
                continue
        return worst

    def _ensure_worker(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._worker,
                name=f"failover-{self.router.deployment}", daemon=True,
            )
            self._thread.start()

    def _worker(self) -> None:
        while True:
            with self._cond:
                while not self._stopped and (
                    not self._heap or self._heap[0][0] > m.now_ms()
                ):
                    timeout = None
                    if self._heap:
                        timeout = max(
                            (self._heap[0][0] - m.now_ms()) / 1000.0, 0.0
                        )
                    self._cond.wait(timeout)
                if self._stopped:
                    return
                (_due, _seq, request, excluded,
                 submitted_ms) = heapq.heappop(self._heap)
            try:
                # assign_request owns terminal rejection (RequestDropped
                # after its capped backoff window) — capped further by the
                # request's remaining deadline so a retry can never sleep
                # past the budget it was admitted under.
                self.router.assign_request(
                    request,
                    exclude={excluded} if excluded else None,
                    timeout_s=max(request.remaining_ms() / 1000.0, 0.001),
                )
                if tracer().enabled:
                    # The ledger's `failover` hop: submit -> re-dispatch,
                    # backoff included, joined to the request's trace. It
                    # OUTRANKS router.assign in the hop taxonomy, so the
                    # retry's inner assign attributes here — a regression
                    # in failover latency names failover, not the router.
                    tracer().record_span(
                        "failover.redispatch",
                        ctx=request.trace_ctx,
                        start_ms=submitted_ms,
                        end_ms=m.now_ms(),
                        deployment=self.router.deployment,
                        lane=self.router.deployment,
                        attempts=request.attempts,
                        excluded=excluded,
                    )
            except Exception:  # noqa: BLE001 — one bad dispatch must not
                # kill the worker; the request's future still resolves
                # through assign_request's own rejection path.
                logger.exception(
                    "%s: failover dispatch failed", self.router.deployment
                )

    def close(self) -> None:
        """Stop the worker and terminally reject every retry still
        waiting out its backoff — an abandoned heap entry would be a
        client future that never resolves."""
        with self._cond:
            self._stopped = True
            pending, self._heap = list(self._heap), []
            self._cond.notify_all()
        for _due, _seq, request, _excluded, _submitted in pending:
            FAILOVER_SHED.inc(tags={"deployment": self.router.deployment,
                                    "reason": "shutdown"})
            request.reject(RequestDropped(
                f"{self.router.deployment}: shutting down with retry pending"
            ))

    def stats(self) -> dict:
        return {
            "retries": float(self.retries),
            "shed_deadline": float(self.shed_deadline),
            "shed_attempts": float(self.shed_attempts),
            "stream_aborted": float(self.stream_aborted),
            "pending": float(len(self._heap)),
        }
