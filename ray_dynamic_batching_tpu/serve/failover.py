"""Request failover — one failure taxonomy, deadline-budgeted re-dispatch.

The reference substrate makes actor death survivable: tasks are retried
from ownership metadata (Ray, OSDI '18 lineage) and the Nexus-style SLO
planner assumes an admitted request either completes within deadline or
is counted SHED — never a spurious client-visible 500. This module is
the recovery half of that contract for the serve tier, shared by every
consumer of the taxonomy (replica, router, controller drain path, proxy
error mapping, sim re-enactment, chaos soak):

- **Taxonomy**: :func:`is_retryable` classifies a rejection into
  retryable *system* failures (chaos injection, replica death, drain
  evictions) vs. non-retryable *user* errors (``BadRequest``, callable
  bugs) and terminal *shed* outcomes (``RequestStale``,
  ``RequestDropped`` — deadline economics, not faults).
- **Deadline-budgeted retries**: :class:`FailoverManager` re-dispatches
  a retryable failure to a DIFFERENT replica with capped exponential
  backoff + seeded jitter, but only while the attempt budget holds and
  ``remaining_deadline >= profiled batch latency`` — otherwise the
  request is counted shed, exactly like the queue's stale discard.
- **At-most-once after first token**: a streaming request that already
  emitted a chunk is never retried (the client saw partial output);
  the failure surfaces as-is.

The circuit breaker lives in ``serve/router.py`` (it is a routing
concern); the manager feeds it per-replica failure/success signals so
the breaker, the retry decision, and the audit trail agree on one
taxonomy.
"""

from __future__ import annotations

import heapq
import itertools
import random
import threading
from dataclasses import dataclass
from typing import Any, List, Optional, Tuple

from ray_dynamic_batching_tpu.engine.request import (
    Request,
    RequestDropped,
    RequestStale,
    now_ms,
)
from ray_dynamic_batching_tpu.serve.fabric import FabricUnreachable
from ray_dynamic_batching_tpu.utils.concurrency import OrderedLock
from ray_dynamic_batching_tpu.serve.grayhealth import median_or_zero
from ray_dynamic_batching_tpu.utils.chaos import ChaosInjected
from ray_dynamic_batching_tpu.utils.logging import get_logger
from ray_dynamic_batching_tpu.utils import metrics as m
from ray_dynamic_batching_tpu.utils.tracing import tracer

logger = get_logger("failover")

FAILOVER_RETRIES = m.Counter(
    "rdb_failover_retries_total", "Requests re-dispatched after a "
    "retryable system failure", tag_keys=("deployment",),
)
FAILOVER_SHED = m.Counter(
    "rdb_failover_shed_total", "Requests shed by the failover layer",
    tag_keys=("deployment", "reason"),
)
HEDGE_TOTAL = m.Counter(
    "rdb_hedge_total",
    "Hedge timer outcomes (won: the hedge dispatch delivered the result; "
    "lost: the primary beat a dispatched hedge or the hedge arm failed; "
    "late: the timer fired but no hedge was dispatched — request already "
    "done, first token emitted, deadline too tight, or no second replica)",
    tag_keys=("deployment", "outcome"),
)


class RetryableSystemError(RuntimeError):
    """Base for failures the framework caused and may transparently
    retry on another replica — never the client's fault."""


class ReplicaDeadError(RetryableSystemError):
    """The serving replica died (loop crash, wedged callable) with this
    request in flight or queued."""


class SliceDeadError(ReplicaDeadError):
    """A chip in the replica's MESH SLICE died, taking the whole slice:
    a TP replica's programs gang-schedule every chip in the gang, so
    losing one loses the collective — there is no partial survival at
    the replica level (ROADMAP item 2). Retryable like any replica
    death (the request re-dispatches elsewhere); the RECOVERY half is
    the scheduler's: the heal replan runs over the surviving geometry,
    re-forms the good chips into narrower slices, and degrades the
    model to the mesh-shape profile row that still fits
    (``scheduler/replan.degrade_sessions``). ``chip_index`` names the
    chip that took the slice down, for the audit trail."""

    def __init__(self, message: str, chip_index: Optional[int] = None):
        super().__init__(message)
        self.chip_index = chip_index


class DrainEvicted(RetryableSystemError):
    """The request was evicted from a draining replica's queue (heal /
    rolling update / plan migration) and must be re-routed."""


class RetriesExhausted(Exception):
    """Terminal: a retryable system failure burned its attempt budget.
    Maps to 503 + Retry-After (gRPC UNAVAILABLE) — the client may retry;
    the payload was never the problem."""

    def __init__(self, message: str, cause: Optional[Exception] = None):
        super().__init__(message)
        self.cause = cause


class RetryBudgetExhausted(Exception):
    """Terminal: the deployment's amplification budget
    (serve/retrybudget.py) refused this re-dispatch — retries+hedges
    already consumed their configured fraction of recent first-attempt
    volume, or the overload governor declared the deployment congested.
    Maps to 429 + Retry-After (RESOURCE_EXHAUSTED): the system is
    shedding load deliberately, exactly like an admission reject — the
    client backs off; the payload was never the problem."""

    def __init__(self, message: str, cause: Optional[Exception] = None,
                 retry_after_s: float = 1.0):
        super().__init__(message)
        self.cause = cause
        self.retry_after_s = retry_after_s


class PoisonRequest(Exception):
    """Terminal: batch bisection (serve/replica.py) isolated this
    request as a query of death — its CONTENT crashes execution, so a
    retry replicates the fault instead of recovering from it. Maps to
    400 (gRPC INVALID_ARGUMENT), is never retried or hedged, and its
    fingerprint lands in the QuarantineRegistry so front doors refuse
    the identical query at admission."""

    def __init__(self, message: str, cause: Optional[Exception] = None,
                 fingerprint: str = ""):
        super().__init__(message)
        self.cause = cause
        self.fingerprint = fingerprint


def is_retryable(exc: BaseException) -> bool:
    """True for system failures the failover layer may re-dispatch.

    ``ChaosInjected`` is the test-harness stand-in for every injected
    fault (dropped RPC, killed batch) and classifies retryable;
    ``FabricUnreachable`` (a control-plane message eaten by a partition
    or the fabric chaos policy) likewise — the payload was never the
    problem, a healed edge or a different replica may serve it;
    ``RequestStale``/``RequestDropped`` are shed outcomes (terminal by
    design); everything else — ``BadRequest``, user-callable exceptions,
    contract violations — is a non-retryable user/server error whose
    retry would just fail again."""
    return isinstance(exc, (RetryableSystemError, ChaosInjected,
                            FabricUnreachable))


def is_shed(exc: BaseException) -> bool:
    """True for deadline-economics outcomes the SLO accounting counts as
    shed rather than errors (the planner's admitted-or-shed contract)."""
    return isinstance(exc, (RequestStale, RequestDropped))


@dataclass(frozen=True)
class RejectDisposition:
    """How one rejection surfaces to a client — ONE table shared by the
    HTTP proxy and ``grpc_proxy._error_status`` so the two front doors
    can never disagree on what a shed is."""

    kind: str                      # "user" | "capacity" | "system" | "internal"
    http_status: int
    grpc_code: str                 # grpc.StatusCode attribute name
    retry_after_s: Optional[float] = None


def reject_disposition(exc: BaseException) -> RejectDisposition:
    """Classify a request failure for the client surface.

    - **capacity** (429 / RESOURCE_EXHAUSTED + computed ``Retry-After``):
      admission rejects and queue sheds (full-queue drops, displacement,
      stale discards) — the system is healthy and saying "not now"; the
      retry hint comes from the rejecting layer (bucket refill time /
      queue drain estimate) with a 1 s floor-less fallback.
    - **system** (503 / UNAVAILABLE + ``Retry-After``): retryable system
      failures and exhausted failover budgets — the payload was never the
      problem; a different moment (heal, breaker close) may serve it.
    - **user** (400 / INVALID_ARGUMENT): the payload itself.
    - **internal** (500 / INTERNAL): genuine bugs — must alarm, never
      invite a retry."""
    from ray_dynamic_batching_tpu.engine.request import BadRequest
    from ray_dynamic_batching_tpu.serve.admission import AdmissionRejected

    if isinstance(exc, (BadRequest, PoisonRequest)):
        # A bisection-isolated poison is the payload's fault by proof of
        # execution: same user-class surface as a validation failure.
        return RejectDisposition("user", 400, "INVALID_ARGUMENT")
    if isinstance(exc, RetryBudgetExhausted):
        return RejectDisposition(
            "capacity", 429, "RESOURCE_EXHAUSTED",
            retry_after_s=float(getattr(exc, "retry_after_s", 1.0) or 1.0),
        )
    if getattr(exc, "reason", "") == "breaker_open":
        # Router terminal reject because EVERY live replica's breaker was
        # open: the system is failing, not merely full — 503, not 429.
        return RejectDisposition("system", 503, "UNAVAILABLE",
                                 retry_after_s=1.0)
    if isinstance(exc, AdmissionRejected) or is_shed(exc):
        return RejectDisposition(
            "capacity", 429, "RESOURCE_EXHAUSTED",
            retry_after_s=float(getattr(exc, "retry_after_s", 0.0) or 1.0),
        )
    if isinstance(exc, RetriesExhausted) or is_retryable(exc):
        return RejectDisposition("system", 503, "UNAVAILABLE",
                                 retry_after_s=1.0)
    return RejectDisposition("internal", 500, "INTERNAL")


def retry_after_header(disposition: RejectDisposition) -> Optional[str]:
    """HTTP ``Retry-After`` value (integer seconds, ceil'd — the header
    grammar takes no fractions; sub-second hints round up to 1)."""
    if disposition.retry_after_s is None:
        return None
    import math

    return str(max(1, math.ceil(disposition.retry_after_s)))


@dataclass
class FailoverPolicy:
    """Retry knobs — deadline is the real bound, attempts the backstop."""

    # Total dispatches (first send included). Sized above any plausible
    # consecutive-failure streak a bounded chaos budget can aim at one
    # request; the deadline budget is what actually stops hopeless work.
    max_attempts: int = 5
    backoff_initial_s: float = 0.002
    backoff_max_s: float = 0.05
    jitter: float = 0.5            # fraction of the backoff randomized
    seed: int = 0                  # jitter RNG seed (deterministic tests)


class FailoverManager:
    """Deadline-budgeted re-dispatch for one deployment's router.

    Replicas hand failed batches here (``on_batch_failure``); drained
    queues arrive via ``requeue``; both paths re-route each request to a
    different replica through ``router.assign_request(exclude=...)`` on
    a dedicated worker thread (a replica's hot loop must never block in
    another replica's backoff). Shed decisions reject with
    :class:`RequestStale` so every accounting surface — queue stats,
    soak, sim — reads them identically to a stale discard.
    """

    def __init__(self, router: Any,
                 policy: Optional[FailoverPolicy] = None) -> None:
        self.router = router
        self.policy = policy or FailoverPolicy()
        self._rng = random.Random(self.policy.seed)
        self._seq = itertools.count()
        # (due_monotonic_ms, seq, request, excluded_replica_id,
        #  submitted_ms — the failover hop span's start)
        self._heap: List[Tuple[float, int, Request, str, float]] = []
        self._cond = threading.Condition(OrderedLock("failover"))
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        # --- accounting (surfaced via stats() -> router -> status()) ---
        self.retries = 0
        self.shed_deadline = 0
        self.shed_attempts = 0
        self.shed_budget = 0
        self.stream_aborted = 0

    # --- replica-facing sink ---------------------------------------------
    def on_batch_failure(self, replica: Any, batch: List[Request],
                         exc: Exception) -> None:
        """A replica's batch died on a retryable system failure: feed the
        breaker, then re-dispatch every request that may still be retried."""
        self.router.record_replica_failure(replica.replica_id)
        for req in batch:
            if req.stream is not None and req.stream.emitted > 0:
                # At-most-once after first token: the client consumed
                # partial output; a transparent replay would duplicate it.
                self.stream_aborted += 1
                req.reject(exc)
                continue
            self.submit(req, exc, exclude_replica=replica.replica_id)  # rdb-lint: disable=retry-amplification (submit() prices the budget itself — consulting here too would double-charge each re-dispatch)

    def on_batch_success(self, replica: Any) -> None:
        self.router.record_replica_success(replica.replica_id)

    # --- retry scheduling --------------------------------------------------
    def submit(self, request: Request, exc: Exception,
               exclude_replica: str = "", immediate: bool = False) -> bool:
        """Queue one re-dispatch (True) or reject terminally (False).

        ``immediate`` skips the backoff delay — drain evictions are not
        replica faults, so they re-route without penalty (still deadline-
        and attempt-budgeted)."""
        deployment = self.router.deployment
        if request.attempts >= self.policy.max_attempts:
            self.shed_attempts += 1
            FAILOVER_SHED.inc(
                tags={"deployment": deployment, "reason": "attempts"}
            )
            request.reject(RetriesExhausted(
                f"{request.request_id}: {request.attempts} attempts "
                f"exhausted (last failure: {exc})", cause=exc,
            ))
            return False
        delay_ms = 0.0 if immediate else self._backoff_ms(request.attempts)
        # Retry only if the request can still plausibly complete: the
        # queue's stale-discard rule (deadline < now + expected latency)
        # applied BEFORE burning a backoff + batch on a lost cause.
        if request.remaining_ms() < self._expected_latency_ms() + delay_ms:
            self.shed_deadline += 1
            FAILOVER_SHED.inc(
                tags={"deployment": deployment, "reason": "deadline"}
            )
            request.reject(RequestStale(
                f"{request.request_id}: deadline unreachable after system "
                f"failure ({exc})"
            ))
            return False
        # Amplification budget (serve/retrybudget.py): a backoff retry is
        # a re-dispatch drawing from the deployment's retry/hedge pool.
        # Drain requeues (``immediate=True``) are exempt by design — a
        # planned drain MOVES admitted work instead of amplifying it, and
        # charging it would turn every rolling update into a shed storm.
        budget = getattr(self.router, "retry_budget", None)
        if not immediate and budget is not None \
                and not budget.try_spend("retry"):
            self.shed_budget += 1
            FAILOVER_SHED.inc(
                tags={"deployment": deployment, "reason": "retry_budget"}
            )
            request.reject(RetryBudgetExhausted(
                f"{request.request_id}: retry budget exhausted "
                f"(last failure: {exc})", cause=exc,
            ))
            return False
        with self._cond:
            # _stopped is authoritative only under the lock: a submit
            # racing close() past an unlocked check would push AFTER the
            # heap drain and leave a client future that never resolves.
            if not self._stopped:
                submitted_ms = m.now_ms()
                heapq.heappush(
                    self._heap,
                    (submitted_ms + delay_ms, next(self._seq), request,
                     exclude_replica, submitted_ms),
                )
                self._ensure_worker()
                self._cond.notify()
                scheduled = True
            else:
                scheduled = False
        if not scheduled:
            # Teardown: no worker to run the retry and no replica set to
            # land it on — terminal, not a silently resurrected thread.
            request.reject(RequestDropped(
                f"{deployment}: shutting down ({exc})"
            ))
            return False
        self.retries += 1
        FAILOVER_RETRIES.inc(tags={"deployment": deployment})
        return True

    def requeue(self, requests: List[Request], victim_id: str,
                dead: bool = False) -> None:
        """Drain-and-requeue: a retired/unhealthy replica's queued work
        re-enters routing through the failover path (no backoff — the
        victim failed, not the request). ``dead=True`` marks the heal
        path (the replica crashed/wedged: :class:`ReplicaDeadError`);
        planned retirements (rolling update, scale-down salvage) stay
        :class:`DrainEvicted`."""
        for req in requests:
            exc: RetryableSystemError = (
                ReplicaDeadError(f"{victim_id} died with request queued")
                if dead else DrainEvicted(f"drained from {victim_id}")
            )
            self.submit(req, exc, exclude_replica=victim_id, immediate=True)  # rdb-lint: disable=retry-amplification (drain requeues MOVE admitted work off a retiring replica; immediate=True is the budget-exempt path submit() documents)

    # --- internals ----------------------------------------------------------
    def _backoff_ms(self, attempts: int) -> float:
        base = min(
            self.policy.backoff_initial_s * (2 ** max(attempts - 1, 0)),
            self.policy.backoff_max_s,
        )
        return base * (1.0 + self.policy.jitter * self._rng.random()) * 1000.0

    def _expected_latency_ms(self) -> float:
        """Profiled cost of one more attempt: the worst recent p50 across
        the replica set (total request latency, so queue wait is priced
        in). 0.0 before any completion — never block the first retries."""
        worst = 0.0
        for r in self.router.replicas():
            queue = getattr(r, "queue", None)
            if queue is None:
                continue
            try:
                worst = max(worst, queue.latency_window.percentile(0.5))
            except Exception:  # noqa: BLE001 — stats must not break retries
                continue
        return worst

    def _ensure_worker(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._worker,
                name=f"failover-{self.router.deployment}", daemon=True,
            )
            self._thread.start()

    def _worker(self) -> None:
        while True:
            with self._cond:
                while not self._stopped and (
                    not self._heap or self._heap[0][0] > m.now_ms()
                ):
                    timeout = None
                    if self._heap:
                        timeout = max(
                            (self._heap[0][0] - m.now_ms()) / 1000.0, 0.0
                        )
                    self._cond.wait(timeout)
                if self._stopped:
                    return
                (_due, _seq, request, excluded,
                 submitted_ms) = heapq.heappop(self._heap)
            # Deadline recheck at POP time: submit() priced the backoff
            # into its pre-sleep check, but the cond wait is not exact
            # (scheduler wakeup slop, notify storms) and the profiled
            # attempt cost may have moved while we slept — a retry must
            # never dispatch past the deadline it was admitted under.
            if request.remaining_ms() < self._expected_latency_ms():
                self.shed_deadline += 1
                FAILOVER_SHED.inc(tags={
                    "deployment": self.router.deployment,
                    "reason": "deadline",
                })
                request.reject(RequestStale(
                    f"{request.request_id}: backoff outlived the "
                    f"admission deadline"
                ))
                continue
            try:
                # assign_request owns terminal rejection (RequestDropped
                # after its capped backoff window) — capped further by the
                # request's remaining deadline so a retry can never sleep
                # past the budget it was admitted under.
                self.router.assign_request(
                    request,
                    exclude={excluded} if excluded else None,
                    timeout_s=max(request.remaining_ms() / 1000.0, 0.001),
                )
                if tracer().enabled:
                    # The ledger's `failover` hop: submit -> re-dispatch,
                    # backoff included, joined to the request's trace. It
                    # OUTRANKS router.assign in the hop taxonomy, so the
                    # retry's inner assign attributes here — a regression
                    # in failover latency names failover, not the router.
                    tracer().record_span(
                        "failover.redispatch",
                        ctx=request.trace_ctx,
                        start_ms=submitted_ms,
                        end_ms=m.now_ms(),
                        deployment=self.router.deployment,
                        lane=self.router.deployment,
                        attempts=request.attempts,
                        excluded=excluded,
                    )
            except Exception:  # noqa: BLE001 — one bad dispatch must not
                # kill the worker; the request's future still resolves
                # through assign_request's own rejection path.
                logger.exception(
                    "%s: failover dispatch failed", self.router.deployment
                )

    def close(self) -> None:
        """Stop the worker and terminally reject every retry still
        waiting out its backoff — an abandoned heap entry would be a
        client future that never resolves."""
        with self._cond:
            self._stopped = True
            pending, self._heap = list(self._heap), []
            self._cond.notify_all()
        for _due, _seq, request, _excluded, _submitted in pending:
            FAILOVER_SHED.inc(tags={"deployment": self.router.deployment,
                                    "reason": "shutdown"})
            request.reject(RequestDropped(
                f"{self.router.deployment}: shutting down with retry pending"
            ))

    def stats(self) -> dict:
        with self._cond:
            pending = float(len(self._heap))
        return {
            "retries": float(self.retries),
            "shed_deadline": float(self.shed_deadline),
            "shed_attempts": float(self.shed_attempts),
            "shed_budget": float(self.shed_budget),
            "stream_aborted": float(self.stream_aborted),
            "pending": pending,
        }


# --- hedged dispatch (gray-failure mitigation, ISSUE 9) ---------------------
#
# Detection (serve/grayhealth.py) converges over monitor ticks; the requests
# dispatched onto a straggler in the meantime still miss their deadlines.
# Hedging mitigates per-request ("The Tail at Scale": defer the hedge until
# the p95, cap it at one extra dispatch — 5% added load for most of the tail
# win): when a hedge-eligible request has produced NOTHING by the
# deployment's profiled p95, re-dispatch it to a DIFFERENT replica and let
# the first winner cancel the loser. The PR-4 at-most-once-after-first-token
# rule is the hard boundary: a request whose stream emitted a chunk is never
# hedged, and the race claim fires on the first-token edge itself
# (TokenStream.on_first_emit), so the client can never observe two sources.


@dataclass
class HedgePolicy:
    """Hedge knobs. Hedging is per-deployment OPT-IN (a Router built
    without a policy never hedges): the extra dispatches are the wrong
    trade under overload, where the queue — not a straggler — is the
    bottleneck."""

    # Service tiers eligible for hedging (interactive is the contract
    # whose tail the hedge exists to protect).
    qos_classes: tuple = ("interactive",)
    # Hedge delay = factor x the deployment's profiled p95 (peer-median
    # across replicas so a straggler cannot inflate its own hedge bar).
    threshold_factor: float = 1.0
    # Floor under the computed delay: below this, the hedge would race
    # healthy jitter instead of stragglers.
    min_threshold_ms: float = 10.0
    # How long a computed threshold stays cached. The peer-median p95
    # moves on monitor-tick timescales; recomputing it (a locked sketch
    # walk per replica) on EVERY interactive dispatch is hot-path waste.
    threshold_refresh_ms: float = 100.0




class _HedgeRace:
    """First-winner resolution between a primary dispatch and its hedge.

    Exactly one of ``primary`` / ``hedge`` claims; the loser is
    cancelled. The outcome settles exactly once (``won``/``lost``) no
    matter how many callbacks observe the finish."""

    __slots__ = ("primary", "shadow", "primary_replica", "_lock",
                 "winner", "settled", "dispatched")

    def __init__(self, primary: Request, shadow: Request,
                 primary_replica: str) -> None:
        self.primary = primary
        self.shadow = shadow
        self.primary_replica = primary_replica
        self._lock = threading.Lock()
        self.winner: Optional[str] = None
        self.settled = False
        self.dispatched = False

    def claim(self, who: str) -> bool:
        with self._lock:
            if self.winner is None:
                self.winner = who
                # The loser's cancellation is visible BEFORE claim
                # returns: its next stream_put / fulfill / reject — on
                # any thread — already sees it, closing the window where
                # a loser's in-flight chunk lands after the claim.
                loser = self.shadow if who == "primary" else self.primary
                loser.cancelled = True
                return True
            return False

    def try_dispatch(self) -> bool:
        """Atomically decide the shadow may go out: False when either
        arm already claimed (the fire-time checks raced a finish). The
        shared lock with :meth:`claim` closes the window where a
        primary finish lands between the check and the dispatch —
        whichever acquires first, exactly one side owns the outcome."""
        with self._lock:
            if self.winner is not None:
                return False
            self.dispatched = True
            return True

    def was_dispatched(self) -> bool:
        with self._lock:
            return self.dispatched

    def settle(self) -> bool:
        """True exactly once — the caller owns recording the outcome."""
        with self._lock:
            if self.settled:
                return False
            self.settled = True
            return True


class HedgeManager:
    """Deadline-budgeted hedged dispatch for one deployment's router.

    ``arm()`` is called by the router after every successful PRIMARY
    assign of an eligible request; a worker thread fires each timer at
    ``now + profiled p95`` and — if the request has produced nothing —
    dispatches a shadow copy to a different replica through the same
    ``assign_request`` machinery failover uses. Outcome accounting
    conserves: ``fired == dispatched + late`` and, once races settle,
    ``dispatched == won + lost`` (asserted by the straggler soak)."""

    def __init__(self, router: Any, policy: HedgePolicy) -> None:
        self.router = router
        self.policy = policy
        self._seq = itertools.count()
        # (due_monotonic_ms, seq, request, primary_replica_id)
        self._heap: List[Tuple[float, int, Request, str]] = []
        self._cond = threading.Condition(OrderedLock("failover"))
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        self._threshold_cache: Tuple[float, float] = (0.0, float("-inf"))
        self._stats_lock = threading.Lock()
        self.armed = 0
        self.fired = 0
        self.dispatched = 0
        self.won = 0
        self.lost = 0
        self.late = 0
        self.budget_denied = 0

    # --- arming (router hot path: one eligibility check + heap push) ------
    def eligible(self, request: Request) -> bool:
        return (
            not request.is_hedge
            and not getattr(request, "_hedge_armed", False)
            and request.qos_class in self.policy.qos_classes
        )

    def threshold_ms(self) -> float:
        """The deployment's profiled p95: peer-MEDIAN across replicas
        (a straggler's own inflated tail must not raise its hedge bar),
        floored so healthy jitter never races itself. Cached for
        ``threshold_refresh_ms`` — the sweep walks a locked sketch per
        replica, too heavy for the per-dispatch arm path."""
        now = m.now_ms()
        cached_val, cached_at = self._threshold_cache
        if now - cached_at < self.policy.threshold_refresh_ms:
            return cached_val
        p95s = []
        for r in self.router.replicas():
            try:
                v = r.latency_observation()[1]
            except Exception:  # noqa: BLE001 — stats must not break routing
                continue
            if v > 0:
                p95s.append(v)
        value = max(
            self.policy.min_threshold_ms,
            self.policy.threshold_factor * median_or_zero(p95s),
        )
        self._threshold_cache = (value, now)  # atomic tuple swap
        return value

    def arm(self, request: Request, replica_id: str) -> bool:
        if not self.eligible(request):
            return False
        if len(self.router.replicas()) < 2:
            return False  # nobody to hedge onto
        request._hedge_armed = True  # one hedge per request, ever
        due = m.now_ms() + self.threshold_ms()
        with self._cond:
            if self._stopped:
                return False
            heapq.heappush(
                self._heap, (due, next(self._seq), request, replica_id)
            )
            self._ensure_worker()
            self._cond.notify()
        with self._stats_lock:
            self.armed += 1
        return True

    # --- firing -----------------------------------------------------------
    def _ensure_worker(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._worker,
                name=f"hedge-{self.router.deployment}", daemon=True,
            )
            self._thread.start()

    def _worker(self) -> None:
        while True:
            with self._cond:
                while not self._stopped and (
                    not self._heap or self._heap[0][0] > m.now_ms()
                ):
                    timeout = None
                    if self._heap:
                        timeout = max(
                            (self._heap[0][0] - m.now_ms()) / 1000.0, 0.0
                        )
                    self._cond.wait(timeout)
                if self._stopped:
                    return
                _due, _seq, request, primary_replica = heapq.heappop(
                    self._heap
                )
            try:
                self._fire(request, primary_replica)  # rdb-lint: disable=retry-amplification (_fire consults the hedge budget at fire time, after the delay — charging at pop would price hedges the race already settled)
            except Exception:  # noqa: BLE001 — one bad hedge must not kill
                # the worker; the primary dispatch is unaffected either way.
                logger.exception(
                    "%s: hedge dispatch failed", self.router.deployment
                )

    def _outcome(self, outcome: str) -> None:
        with self._stats_lock:
            setattr(self, outcome, getattr(self, outcome) + 1)
        HEDGE_TOTAL.inc(tags={"deployment": self.router.deployment,
                              "outcome": outcome})

    def _fire(self, request: Request, primary_replica: str) -> None:
        with self._stats_lock:
            self.fired += 1
        # A failover re-dispatch may have moved the request since arm
        # time: strike and exclude the replica CURRENTLY holding it, not
        # the one captured at arm — else the shadow can land on the very
        # replica the primary is stuck on (racing itself) while a dead
        # peer's breaker takes the slow strike.
        primary_replica = getattr(
            request, "_assigned_replica", primary_replica
        )
        # The at-most-once-after-first-token pin, checked at the source:
        # a request that completed, failed, was cancelled, or emitted a
        # chunk is never hedged.
        if (
            request.cancelled
            or request.future.done()
            or (request.stream is not None and request.stream.emitted > 0)
        ):
            self._outcome("late")
            return
        others = [r for r in self.router.replicas()
                  if r.replica_id != primary_replica]
        remaining = request.remaining_ms()
        if not others or remaining < self.router.failover._expected_latency_ms():
            # No second replica / no deadline budget for a second
            # dispatch: the hedge would only add load, never save the
            # request.
            self._outcome("late")
            return
        # Amplification budget (serve/retrybudget.py): a hedge is a
        # second dispatch of already-admitted work — it draws from the
        # same pool as a failover retry. A denied hedge is "late" in the
        # conservation identity (timer fired, nothing dispatched) plus
        # its own counter so operators can tell budget pressure from
        # ordinary late fires.
        budget = getattr(self.router, "retry_budget", None)
        if budget is not None and not budget.try_spend("hedge"):
            with self._stats_lock:
                self.budget_denied += 1
            self._outcome("late")
            return
        # The primary exceeded the deployment's profiled p95 with nothing
        # to show — a deadline-exceeded dispatch. Strike its breaker
        # (capped + audited there) so a persistent straggler cannot hold
        # a breaker closed with slow successes.
        self.router.record_replica_slow(primary_replica)
        shadow = Request(
            model=request.model,
            payload=request.payload,
            slo_ms=request.slo_ms,
            request_id=f"{request.request_id}#hedge",
            seq_len=request.seq_len,
            trace_ctx=dict(request.trace_ctx),
            multiplexed_model_id=request.multiplexed_model_id,
            tenant=request.tenant,
            qos_class=request.qos_class,
            is_hedge=True,
        )
        # The shadow races the PRIMARY's admission deadline — a hedge
        # never buys a fresh SLO clock.
        shadow.admission_deadline_ms = request.admission_deadline_ms
        race = _HedgeRace(request, shadow, primary_replica)
        if request.stream is not None:
            from ray_dynamic_batching_tpu.engine.request import TokenStream

            shadow.stream = TokenStream()
            shadow.stream.on_first_emit = (
                lambda: self._shadow_first_token(race)
            )
            request.stream.on_first_emit = (
                lambda: self._primary_first_token(race)
            )
            if request.stream.emitted > 0:
                # Token raced the hook installation: the pin wins.
                self._primary_finished(race)
        request.future.add_done_callback(
            lambda _f: self._primary_finished(race)
        )
        shadow.future.add_done_callback(
            lambda f: self._shadow_done(race, f)
        )
        if not race.try_dispatch():
            # The primary finished between the eligibility checks and
            # here — no shadow went out, the timer just fired late.
            self._outcome("late")
            return
        with self._stats_lock:
            self.dispatched += 1
        # assign_request owns terminal rejection: even a refused hedge
        # resolves the shadow future, so won + lost always reconciles
        # against dispatched.
        self.router.assign_request(
            shadow,
            exclude={primary_replica},
            timeout_s=max(remaining / 1000.0, 0.001),
        )
        if tracer().enabled:
            tracer().record_span(
                "hedge.dispatch",
                ctx=request.trace_ctx,
                start_ms=m.now_ms(),
                end_ms=m.now_ms(),
                deployment=self.router.deployment,
                lane=self.router.deployment,
                primary_replica=primary_replica,
            )

    # --- race callbacks ---------------------------------------------------
    def _primary_finished(self, race: _HedgeRace) -> None:
        """The primary produced something (first token, result, or a
        terminal rejection): cancel the hedge arm. A primary future
        resolved BY the hedge arrives here too — the claim check keeps
        that from cancelling the winner."""
        if race.claim("primary"):
            race.shadow.cancel()
            # Only a DISPATCHED shadow settles here ("lost"): if the
            # claim beat try_dispatch, _fire records "late" instead. A
            # cancelled-in-queue shadow is discarded without resolving
            # its future, so this is the loser's one accounting site.
            if race.was_dispatched() and race.settle():
                self._outcome("lost")

    def _primary_first_token(self, race: _HedgeRace) -> Optional[bool]:
        """First-emit hook on the PRIMARY's stream: resolve the race,
        then tell the stream whether the triggering chunk may deliver —
        ``False`` (veto) when the shadow claimed while this chunk was in
        flight; the grafted winner owns the client stream."""
        self._primary_finished(race)
        return race.winner != "hedge"

    def _shadow_first_token(self, race: _HedgeRace) -> None:
        """The hedge produced the FIRST token of the whole request:
        claim, cancel the primary, and graft the shadow's stream onto
        the client's (buffered chunks replay in order, then inline)."""
        if not race.claim("hedge"):
            return  # primary won: shadow chunks drop into the void
        race.primary.cancel()
        primary_stream = race.primary.stream
        # The race is resolved: detach the primary's first-emit hook so
        # the WINNER's grafted chunks (which also ride this stream) are
        # not vetoed by it.
        primary_stream.on_first_emit = None
        race.shadow.stream.subscribe(
            primary_stream.put,
            lambda err: (primary_stream.abort(err) if err is not None
                         else primary_stream.close()),
        )

    def _shadow_done(self, race: _HedgeRace, fut) -> None:
        exc = fut.exception()
        if exc is not None:
            # The hedge arm failed (shed, retries exhausted, refused):
            # the primary keeps racing its own deadline untouched —
            # UNLESS the shadow already claimed on its first token and
            # cancelled the primary. A cancelled primary is discarded at
            # queue pop without resolving its future, so the claimed-
            # then-failed shadow is the client's last chance at an
            # answer: reject (aborts the grafted stream too, idempotent
            # if the straggler's own late completion raced us).
            if race.winner == "hedge":
                race.primary.reject(exc, force=True)
            if race.settle():
                self._outcome("lost")
            return
        won = race.claim("hedge") or race.winner == "hedge"
        if won:
            race.primary.cancel()
            race.primary.fulfill(fut.result(), force=True)
            if race.settle():
                self._outcome("won")
        else:
            if race.settle():
                self._outcome("lost")

    # --- lifecycle / stats ------------------------------------------------
    def close(self) -> None:
        with self._cond:
            self._stopped = True
            self._heap = []
            self._cond.notify_all()

    def stats(self) -> dict:
        # _heap is the cond's domain, the counters are _stats_lock's;
        # take them sequentially (never nested) so neither orders
        # against the other.
        with self._cond:
            pending = float(len(self._heap))
        with self._stats_lock:
            return {
                "armed": float(self.armed),
                "fired": float(self.fired),
                "dispatched": float(self.dispatched),
                "won": float(self.won),
                "lost": float(self.lost),
                "late": float(self.late),
                "budget_denied": float(self.budget_denied),
                "pending": pending,
            }
