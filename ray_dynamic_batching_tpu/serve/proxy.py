"""HTTP ingress proxy — the framework's front door.

Re-creates Ray Serve's per-node proxy
(``python/ray/serve/_private/proxy.py:136`` ``GenericProxy``, ``:779``
``HTTPProxy``, actor wrapper ``:1153``) and its prefix router
(``_private/proxy_router.py``): requests are matched by route prefix to a
deployment handle, awaited, and returned as JSON. Implemented on asyncio
streams with a minimal HTTP/1.1 parser — the framework owns both sides of
the socket, so a full ASGI stack buys nothing on the hot path.

Routes:
- ``POST /api/{deployment}``  body = JSON payload → handle result; a payload
  with ``"stream": true`` gets a chunked NDJSON response — one line per
  chunk as the replica produces it, then a final ``{"result": ...}`` line
  (ref streaming proxy path ``_private/proxy.py:959``)
- ``GET  /-/healthz``         liveness (ref proxy health checks)
- ``GET  /-/status``          controller status snapshot
- ``GET  /metrics``           Prometheus text exposition
  (ref ``_private/metrics_agent.py:483,595`` Prometheus surfacing)
"""

from __future__ import annotations

import asyncio
import contextvars
import functools
import json
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from ray_dynamic_batching_tpu.engine.request import (
    BadRequest,
    DEFAULT_TENANT,
    normalize_qos,
)
from ray_dynamic_batching_tpu.serve.failover import (
    RejectDisposition,
    reject_disposition,
    retry_after_header,
)
from ray_dynamic_batching_tpu.serve.handle import DeploymentHandle
from ray_dynamic_batching_tpu.utils.logging import get_logger
from ray_dynamic_batching_tpu.utils import metrics as m
from ray_dynamic_batching_tpu.utils.tracing import parse_traceparent, tracer

logger = get_logger("proxy")

PROXY_REQUESTS = m.Counter(
    "rdb_proxy_requests_total", "HTTP requests",
    tag_keys=("route", "code", "shard"),
    bounded_tags={"shard": m.DEFAULT_SHARD_TOP_K},
)
PROXY_LATENCY_MS = m.Histogram(
    "rdb_proxy_request_latency_ms", "End-to-end HTTP request latency",
    tag_keys=("route", "shard"),
    bounded_tags={"shard": m.DEFAULT_SHARD_TOP_K},
)

MAX_BODY_BYTES = 64 * 1024 * 1024


def _to_jsonable(obj: Any) -> Any:
    """Results may be np arrays / DecodeResults; make them JSON-safe."""
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (np.integer, np.floating)):
        return obj.item()
    if hasattr(obj, "__dict__") and not isinstance(obj, type):
        return {k: _to_jsonable(v) for k, v in vars(obj).items()}
    if isinstance(obj, (list, tuple)):
        return [_to_jsonable(x) for x in obj]
    if isinstance(obj, dict):
        return {k: _to_jsonable(v) for k, v in obj.items()}
    return obj


class ProxyRouter:
    """Longest-prefix route table (ref _private/proxy_router.py)."""

    def __init__(self) -> None:
        self._handles: Dict[str, DeploymentHandle] = {}
        self._sorted: Tuple[str, ...] = ()  # longest-first; rebuilt on mutation
        self._lock = threading.Lock()

    def set_route(self, route: str, handle: DeploymentHandle) -> None:
        with self._lock:
            self._handles[route.rstrip("/")] = handle
            self._sorted = tuple(sorted(self._handles, key=len, reverse=True))

    def remove_route(self, route: str) -> None:
        with self._lock:
            self._handles.pop(route.rstrip("/"), None)
            self._sorted = tuple(sorted(self._handles, key=len, reverse=True))

    def match(self, path: str) -> Optional[Tuple[str, DeploymentHandle]]:
        with self._lock:
            for route in self._sorted:
                if path == route or path.startswith(route + "/"):
                    return route, self._handles[route]
        return None


class HTTPProxy:
    """Asyncio HTTP server bridging sockets to deployment handles."""

    def __init__(
        self,
        router: ProxyRouter,
        host: str = "127.0.0.1",
        port: int = 8265,
        status_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        request_timeout_s: float = 60.0,
        admission: Optional[Any] = None,
        shard_id: str = "0",
    ) -> None:
        self.router = router
        self.host = host
        self.port = port
        # Front-door shard identity (serve/frontdoor.py): tags every
        # proxy metric family so per-shard load skew is observable; "0"
        # is the unsharded default.
        self.shard_id = str(shard_id)
        self.status_fn = status_fn
        self.request_timeout_s = request_timeout_s
        # Optional serve.admission.AdmissionController: consulted BEFORE
        # any routing or queueing (the whole point of admission control —
        # a reject costs one HTTP round trip, not a queue slot). Wired by
        # serve.api when the module controller publishes a route.
        self.admission = admission
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._route_pool: Optional[ThreadPoolExecutor] = None

    async def _offload_routing(self, fn: Callable, *args: Any) -> Any:
        """Run a synchronous routing call off the event loop.

        handle.remote/remote_stream run assign_request's pow-2 + backoff
        loop, which sleeps up to the assign timeout when every replica is
        saturated — on the loop thread that would stall every live
        connection. The proxy's OWN pool (not the loop's default
        executor) absorbs those sleeps: parking up-to-1s backoffs on the
        shared default pool would head-of-line-block unrelated work
        (other deployments' routing, library callbacks) behind one
        saturated deployment. contextvars copy keeps the routing span
        inside this request's trace."""
        loop = asyncio.get_running_loop()
        ctx = contextvars.copy_context()
        return await loop.run_in_executor(
            self._route_pool, lambda: ctx.run(fn, *args)
        )

    # --- HTTP plumbing ----------------------------------------------------
    async def _read_request(
        self, reader: asyncio.StreamReader
    ) -> Optional[Tuple[str, str, Dict[str, str], Optional[bytes]]]:
        line = await reader.readline()
        if not line:
            return None
        try:
            method, target, _version = line.decode("latin1").strip().split(" ", 2)
        except ValueError:
            return None
        headers: Dict[str, str] = {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            if b":" in h:
                k, v = h.decode("latin1").split(":", 1)
                headers[k.strip().lower()] = v.strip()
        length = int(headers.get("content-length", "0") or "0")
        if length > MAX_BODY_BYTES:
            # body=None marks an oversized request: the caller answers 413
            # and closes the connection (the unread bytes would desync any
            # further pipelined parsing on this stream).
            return method, target, headers, None
        body = await reader.readexactly(length) if length else b""
        return method, target, headers, body

    @staticmethod
    def _response(code: int, payload: Any, reason: str = "",
                  headers: Optional[Dict[str, str]] = None) -> bytes:
        body = json.dumps(_to_jsonable(payload)).encode()
        status = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  429: "Too Many Requests",
                  500: "Internal Server Error", 503: "Service Unavailable",
                  504: "Gateway Timeout"}.get(code, reason or "Error")
        extra = "".join(
            f"{k}: {v}\r\n" for k, v in (headers or {}).items()
        )
        head = (
            f"HTTP/1.1 {code} {status}\r\n"
            f"Content-Type: application/json\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"{extra}"
            f"Connection: keep-alive\r\n\r\n"
        )
        return head.encode() + body

    @staticmethod
    def _text_response(code: int, text: str, ctype: str) -> bytes:
        body = text.encode()
        head = (
            f"HTTP/1.1 {code} OK\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: keep-alive\r\n\r\n"
        )
        return head.encode() + body

    # --- streaming (ref HTTPProxy.send_request_to_replica, proxy.py:959) --
    async def _stream_response(
        self,
        writer: asyncio.StreamWriter,
        handle: DeploymentHandle,
        payload: Any,
    ) -> str:
        """Chunked NDJSON: one line per streamed chunk, then a final
        ``{"result": ...}`` (or ``{"error": ...}``) line. Returns the HTTP
        code for metrics.

        Delivery is push-based: the TokenStream's producer thread hands
        chunks to this event loop via ``call_soon_threadsafe`` — no blocked
        reader thread per connection, so concurrent streams scale with the
        event loop, not with an executor pool.
        """
        stream, future = await self._offload_routing(
            handle.remote_stream, payload
        )
        writer.write(
            b"HTTP/1.1 200 OK\r\n"
            b"Content-Type: application/x-ndjson\r\n"
            b"Transfer-Encoding: chunked\r\n"
            b"Connection: keep-alive\r\n\r\n"
        )
        loop = asyncio.get_running_loop()
        aq: asyncio.Queue = asyncio.Queue()
        _CLOSE = object()

        def _push(item: Any) -> None:
            try:
                loop.call_soon_threadsafe(aq.put_nowait, item)
            except RuntimeError:
                pass  # loop shut down mid-stream; connection is dying anyway

        stream.subscribe(
            lambda chunk: _push(("chunk", chunk)),
            lambda err: _push((_CLOSE, err)),
        )

        async def _write_line(obj: Any) -> None:
            data = (json.dumps(_to_jsonable(obj)) + "\n").encode()
            writer.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            await writer.drain()

        code = "200"
        try:
            while True:
                kind, val = await asyncio.wait_for(
                    aq.get(), timeout=self.request_timeout_s
                )
                if kind is _CLOSE:
                    break
                await _write_line({"chunk": val})
            result = await asyncio.wait_for(
                asyncio.wrap_future(future), timeout=self.request_timeout_s
            )
            await _write_line({"result": result})
        except asyncio.TimeoutError:
            # The chunked header is already out — the error must arrive as a
            # body line + clean terminator, never a truncated socket.
            code = "504"
            await _write_line({"error": "stream timed out"})
        except Exception as e:  # noqa: BLE001 — surface on the trailer line
            # Same shared table as the unary path (the 200 header is
            # already out, so `code` is the metrics classification):
            # capacity sheds read as 429, system failures as 503 — never
            # server errors.
            code = str(reject_disposition(e).http_status)
            await _write_line({"error": str(e)})
        writer.write(b"0\r\n\r\n")
        await writer.drain()
        return code

    # --- request handling (ref GenericProxy.proxy_request, proxy.py:446) --
    async def _handle_one(
        self, method: str, path: str, body: bytes,
        writer: Optional[asyncio.StreamWriter] = None,
        headers: Optional[Dict[str, str]] = None,
    ) -> Tuple[Optional[bytes], str]:
        if method == "GET" and path == "/-/healthz":
            return self._response(200, {"status": "ok"}), "healthz"
        if method == "GET" and path == "/-/status":
            status = self.status_fn() if self.status_fn else {}
            return self._response(200, status), "status"
        if method == "GET" and path == "/metrics":
            # Content negotiation: exemplars are OpenMetrics-only syntax —
            # a classic Prometheus scraper gets the clean 0.0.4 text, a
            # client Accept-ing application/openmetrics-text gets
            # exemplars + `# EOF`.
            accept = (headers or {}).get("accept", "")
            if "application/openmetrics-text" in accept:
                return (
                    self._text_response(
                        200, m.default_registry().openmetrics_text(),
                        "application/openmetrics-text; version=1.0.0; "
                        "charset=utf-8",
                    ),
                    "metrics",
                )
            return (
                self._text_response(
                    200, m.default_registry().prometheus_text(),
                    "text/plain; version=0.0.4",
                ),
                "metrics",
            )
        matched = self.router.match(path)
        if matched is None:
            # Fixed sentinel tag: tagging with the raw path would let any
            # client mint unbounded metric label cardinality.
            return self._response(404, {"error": f"no route for {path}"}), "unmatched"
        route, handle = matched
        if method != "POST":
            return self._response(400, {"error": "use POST"}), route
        try:
            payload = json.loads(body) if body else None
        except json.JSONDecodeError as e:
            return self._response(400, {"error": f"bad JSON: {e}"}), route

        # --- QoS identity + admission (BEFORE any routing/queueing) ------
        # Headers win over payload fields (a gateway stamping classes must
        # override whatever the client self-declared); unknown classes are
        # the client's fault (400), never a silent default. Undeclared
        # identity grades at the HANDLE's per-deployment default — the
        # admitter and the queue must see the same class.
        hdrs = headers or {}
        body_dict = payload if isinstance(payload, dict) else {}
        tenant = (hdrs.get("x-rdb-tenant") or body_dict.get("tenant")
                  or DEFAULT_TENANT)
        declared_qos = hdrs.get("x-rdb-qos") or body_dict.get("qos_class")
        try:
            qos = (normalize_qos(declared_qos) if declared_qos
                   else getattr(handle, "default_qos_class", None)
                   or normalize_qos(None))
        except BadRequest as e:
            return self._response(400, {"error": str(e)}), route
        identity_kwargs: Dict[str, Any] = {}
        if isinstance(payload, dict):
            # The handle builds the Request from the payload: HEADER-
            # declared identity must ride it so spans/queues/audit see
            # the same class the admitter graded. Only explicitly-sent
            # values are written — injecting defaults would mutate every
            # user payload visibly (an echo deployment would reflect
            # keys the client never sent).
            if hdrs.get("x-rdb-tenant"):
                payload["tenant"] = tenant
            if hdrs.get("x-rdb-qos"):
                payload["qos_class"] = qos
        elif isinstance(handle, DeploymentHandle) and (
            hdrs.get("x-rdb-tenant") or hdrs.get("x-rdb-qos")
        ):
            # Non-dict payload: identity can't ride the payload, so pass
            # it as kwargs (only to the native handle, whose signature
            # takes them — adapter handles get dict payloads anyway).
            identity_kwargs = {"tenant": tenant, "qos_class": qos}
        if self.admission is not None:
            # Its own ledger hop (admission.check): bucket math is
            # microseconds, but a contended admission lock or governor
            # flap shows up here — and an invisible hop can never be
            # named guilty by the budget gate.
            with tracer().span("admission.check", lane="http",
                               tenant=tenant, qos_class=qos):
                ok, retry_after_s = self.admission.admit(
                    getattr(handle, "deployment", route), tenant, qos
                )
            if not ok:
                # Same header grammar as every other capacity reject
                # (failover.retry_after_header), just pre-dispatch.
                ra = retry_after_header(RejectDisposition(
                    "capacity", 429, "RESOURCE_EXHAUSTED",
                    retry_after_s=retry_after_s,
                ))
                return self._response(
                    429,
                    {"error": f"admission rate exceeded (tenant "
                              f"{tenant!r}, class {qos!r})"},
                    headers={"Retry-After": ra},
                ), route

        if (
            writer is not None
            and isinstance(payload, dict)
            and payload.get("stream")
            # Adapter handles without a streaming surface fall through to
            # the unary path, whose validation can answer 400 — a missing
            # attribute here would drop the connection with no response.
            and hasattr(handle, "remote_stream")
        ):
            code = await self._stream_response(writer, handle, payload)
            # None marks "already written"; tag carries the code for metrics.
            return None, f"{route}|{code}"

        future = await self._offload_routing(
            functools.partial(handle.remote, payload, **identity_kwargs)
        )
        try:
            result = await asyncio.wait_for(
                asyncio.wrap_future(future), timeout=self.request_timeout_s
            )
        except asyncio.TimeoutError:
            return self._response(504, {"error": "request timed out"}), route
        except Exception as e:  # noqa: BLE001 — replica-side errors surface as 500
            # One shared table (serve/failover.reject_disposition) decides
            # how a failure surfaces: capacity sheds are 429 + a COMPUTED
            # Retry-After (bucket refill / queue drain estimate), retryable
            # system failures and exhausted failover budgets are 503 +
            # Retry-After, user payloads 400, genuine bugs 500. The gRPC
            # front door maps the same table so the two can never disagree.
            disp = reject_disposition(e)
            if disp.kind == "internal" and "no replica" in str(e):
                # Untyped routing-layer saturation message: transient, not
                # a bug — keep the historical 503 classification.
                return self._response(
                    503, {"error": str(e)}, headers={"Retry-After": "1"}
                ), route
            ra = retry_after_header(disp)
            return self._response(
                disp.http_status, {"error": str(e)},
                headers={"Retry-After": ra} if ra is not None else None,
            ), route
        return self._response(200, {"result": result}), route

    async def _serve_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        try:
            while True:
                req = await self._read_request(reader)
                if req is None:
                    break
                method, path, headers, body = req
                if body is None:  # oversized: answer and drop the connection
                    resp = self._response(413, {"error": "body too large"},
                                          reason="Payload Too Large")
                    PROXY_REQUESTS.inc(tags={"route": "oversized",
                                             "code": "413",
                                             "shard": self.shard_id})
                    writer.write(resp)
                    await writer.drain()
                    break
                # The ingest span covers the whole hop (parse -> route ->
                # await replica result). An inbound W3C ``traceparent``
                # header joins the caller's trace; absent one, this span
                # starts the trace every downstream hop inherits.
                t_req = m.now_ms()
                with tracer().attach_context(
                    parse_traceparent(headers.get("traceparent")),
                    "proxy.request",
                    lane="http", method=method, path=path,
                ) as psp:
                    resp, route = await self._handle_one(
                        method, path, body, writer, headers
                    )
                if resp is None:  # streamed: already written, tag holds code
                    route, _, code = route.rpartition("|")
                else:
                    code = resp.split(b" ", 2)[1].decode()
                if psp is not None:
                    psp.attributes.update(route=route, code=code)
                PROXY_REQUESTS.inc(tags={"route": route, "code": code,
                                         "shard": self.shard_id})
                PROXY_LATENCY_MS.observe(
                    m.now_ms() - t_req,
                    tags={"route": route, "shard": self.shard_id},
                    trace_id=psp.trace_id if psp is not None else None,
                )
                if resp is None:
                    continue
                writer.write(resp)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        except Exception:  # noqa: BLE001
            logger.exception("connection handler failed")
        finally:
            writer.close()

    # --- lifecycle --------------------------------------------------------
    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def _start():
            try:
                self._server = await asyncio.start_server(
                    self._serve_conn, self.host, self.port
                )
            except OSError as e:  # bind failure — surface it to start()
                self._start_error = e
                self._started.set()
                return
            if self.port == 0:
                self.port = self._server.sockets[0].getsockname()[1]
            self._started.set()
            async with self._server:
                await self._server.serve_forever()

        try:
            self._loop.run_until_complete(_start())
        except asyncio.CancelledError:
            pass
        finally:
            self._loop.close()

    def start(self) -> "HTTPProxy":
        if self._thread is not None:
            return self
        # Fresh state per start: a previous run's event/error must not make a
        # restart report success before (or regardless of whether) we bind.
        self._started = threading.Event()
        self._start_error: Optional[BaseException] = None
        # Sized for saturation, not throughput: routing threads spend
        # their time in backoff sleeps, so 64 mostly-idle threads cover
        # 64 concurrently-backing-off requests before anyone queues.
        self._route_pool = ThreadPoolExecutor(
            max_workers=64, thread_name_prefix="proxy-route"
        )
        self._thread = threading.Thread(
            target=self._run, name="http-proxy", daemon=True
        )
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("proxy failed to start")
        if self._start_error is not None:
            self._thread.join(timeout=5)
            self._thread = None
            raise RuntimeError(
                f"proxy failed to bind {self.host}:{self.port}"
            ) from self._start_error
        logger.info("http proxy listening on %s:%d", self.host, self.port)
        return self

    def stop(self) -> None:
        loop, server = self._loop, self._server
        if loop is not None and server is not None:
            # One threadsafe callback doing close + cancel atomically in the
            # loop thread: scheduling a second call after server.close()
            # races loop shutdown (Server.close() ends serve_forever, which
            # lets _run's finally close the loop).
            def _close() -> None:
                server.close()
                for task in asyncio.all_tasks(loop):
                    if task is not asyncio.current_task(loop):
                        task.cancel()

            try:
                loop.call_soon_threadsafe(_close)
            except RuntimeError:
                pass  # loop already closed — nothing left to stop
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._route_pool is not None:
            # Don't wait: a routing call mid-backoff can hold its thread
            # for up to the assign timeout; its request future resolves
            # (rejected) independently of pool teardown.
            self._route_pool.shutdown(wait=False)
            self._route_pool = None
