"""Work-conserving retry/hedge budgets — the anti-amplification governor.

Retries, hedges, and requeues are *load amplifiers*: each one re-enters
the dispatch path carrying work the cluster already accepted once. Under
a transient fault that is exactly right (the re-dispatch lands on a
healthy replica and the request survives); under sustained overload it
is exactly wrong — the amplified load holds queues saturated after the
original trigger heals, the signature of a metastable failure (Bronson
et al., "Metastable Failures in Distributed Systems", HotOS '21).

:class:`RetryBudget` bounds the amplification: re-dispatches may consume
at most ``fraction`` of the deployment's recent *first-attempt* volume.
Volume is tracked with the same two-epoch rotation discipline as
``utils.sketch.RollingSketch`` — a current and a previous epoch of
counters, rotated every ``window`` first attempts, so "recent" is
count-bounded (between ``window`` and ``2*window`` first attempts),
deterministic, and clock-free (the sim twin shares the class verbatim).

Two modes:

- **permissive** (``fraction is None``, the default) — every spend is
  granted but still *accounted*, so ``status()`` dashboards show what a
  budget WOULD have charged before an operator turns one on.
- **enforcing** (``fraction`` set) — over-budget spends are denied; the
  caller sheds the re-dispatch as ``RetryBudgetExhausted`` (429 +
  Retry-After via the shared ``reject_disposition`` table). First
  attempts are never charged — admission already priced them.

The overload governor's ``congested`` verdict (serve/admission.py)
zeroes the budget outright in either mode: while first-attempt
attainment is below floor, every re-dispatch is one more first attempt
that won't fit — recovery must be monotone, so amplification stops
first. ``min_first_attempts`` keeps enforcement off until there is
enough recent volume for the fraction to mean anything (cold starts and
single-request failovers are not amplification).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from ray_dynamic_batching_tpu.utils.concurrency import OrderedLock
from ray_dynamic_batching_tpu.utils import metrics as m

RETRY_BUDGET_TOTAL = m.Counter(
    "rdb_retry_budget_total",
    "Re-dispatch budget decisions (granted/denied) by kind",
    tag_keys=("deployment", "kind", "outcome"),
)
RETRY_BUDGET_CONGESTED = m.Gauge(
    "rdb_retry_budget_congested",
    "1 while the overload governor holds this deployment's retry "
    "budget at zero",
    tag_keys=("deployment",),
)


@dataclass
class RetryBudgetPolicy:
    """Knobs for one deployment's amplification budget.

    ``fraction`` — re-dispatches (retries + hedges) allowed per recent
    first attempt; ``None`` tracks without enforcing. ``window`` — first
    attempts per accounting epoch (recent = current + previous epoch).
    ``min_first_attempts`` — enforcement floor: below this much recent
    first-attempt volume every spend is granted (a fraction of nothing
    is noise, and low-volume failovers are recovery, not amplification).
    """

    fraction: Optional[float] = None
    window: int = 512
    min_first_attempts: int = 16

    def __post_init__(self) -> None:
        if self.fraction is not None and not 0.0 <= self.fraction <= 1.0:
            raise ValueError(
                f"retry budget fraction must be in [0, 1], got "
                f"{self.fraction}"
            )
        if self.window <= 0:
            raise ValueError("retry budget window must be positive")
        if self.min_first_attempts < 0:
            raise ValueError("min_first_attempts must be >= 0")


class RetryBudget:
    """Per-deployment amplification ledger; thread-safe; clock-free.

    Shared by FailoverManager (backoff retries), HedgeManager (hedge
    fires), and the sim twin's client-retry model — one ledger per
    deployment so every amplifier draws from the same pool.
    """

    def __init__(self, deployment: str,
                 policy: Optional[RetryBudgetPolicy] = None) -> None:
        self.deployment = deployment
        self.policy = policy or RetryBudgetPolicy()
        # Same rank as the RollingSketch epoch state it mirrors: consulted
        # under router_pool/failover locks, bumps metrics inside.
        self._lock = OrderedLock("sketch")
        self._congested = False
        # Two-epoch rotation (utils.sketch.RollingSketch discipline):
        # "recent" = previous epoch + current epoch, rotated every
        # `window` first attempts.
        self._cur_first = 0
        self._prev_first = 0
        self._cur_spent = 0
        self._prev_spent = 0
        # Cumulative observability (never rotated).
        self._granted: Dict[str, int] = {}
        self._denied: Dict[str, int] = {}
        self._first_total = 0

    # --- accounting --------------------------------------------------------
    def record_first_attempt(self, n: int = 1) -> None:
        """A first dispatch happened: it funds the budget, never draws
        from it."""
        with self._lock:
            self._cur_first += n
            self._first_total += n
            if self._cur_first >= self.policy.window:
                self._prev_first = self._cur_first
                self._prev_spent = self._cur_spent
                self._cur_first = 0
                self._cur_spent = 0

    def try_spend(self, kind: str = "retry") -> bool:
        """Check-and-consume one re-dispatch. ``kind`` is observability
        only ("retry" | "hedge" | "requeue"); all kinds draw from the
        one pool — a hedge and a retry amplify identically."""
        with self._lock:
            if self._congested:
                # Governor verdict outranks the fraction in BOTH modes:
                # while first-attempt attainment is under floor, zero
                # re-dispatches is the only monotone-recovery answer.
                self._denied[kind] = self._denied.get(kind, 0) + 1
                RETRY_BUDGET_TOTAL.inc(tags={
                    "deployment": self.deployment, "kind": kind,
                    "outcome": "denied_congested",
                })
                return False
            frac = self.policy.fraction
            recent_first = self._prev_first + self._cur_first
            recent_spent = self._prev_spent + self._cur_spent
            if (
                frac is not None
                and recent_first >= self.policy.min_first_attempts
                and recent_spent + 1 > frac * recent_first
            ):
                self._denied[kind] = self._denied.get(kind, 0) + 1
                RETRY_BUDGET_TOTAL.inc(tags={
                    "deployment": self.deployment, "kind": kind,
                    "outcome": "denied",
                })
                return False
            self._cur_spent += 1
            self._granted[kind] = self._granted.get(kind, 0) + 1
            RETRY_BUDGET_TOTAL.inc(tags={
                "deployment": self.deployment, "kind": kind,
                "outcome": "granted",
            })
            return True

    # --- governor coupling -------------------------------------------------
    def set_congested(self, congested: bool) -> None:
        """Driven by the overload governor's `congested` hysteresis
        (serve/admission.py): True zeroes the budget, False restores the
        configured fraction. Idempotent."""
        with self._lock:
            if congested == self._congested:
                return
            self._congested = congested
        RETRY_BUDGET_CONGESTED.set(
            1.0 if congested else 0.0,
            tags={"deployment": self.deployment},
        )

    @property
    def congested(self) -> bool:
        with self._lock:
            return self._congested

    # --- config / observability --------------------------------------------
    def reconfigure(self, policy: RetryBudgetPolicy) -> None:
        """Redeploy repricing (controller._apply_router_policies): swap
        the knobs, keep the ledger — history stays honest across a knob
        change."""
        with self._lock:
            self.policy = policy

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {
                "enforcing": self.policy.fraction is not None,
                "fraction": self.policy.fraction,
                "congested": self._congested,
                "recent_first_attempts":
                    self._prev_first + self._cur_first,
                "recent_redispatches":
                    self._prev_spent + self._cur_spent,
                "first_attempts_total": self._first_total,
                "granted": dict(self._granted),
                "denied": dict(self._denied),
            }
