"""Replica autoscaling policy — queue-length proportional control.

Re-creates Ray Serve's default policy
(``python/ray/serve/autoscaling_policy.py:12-85``
``replica_queue_length_autoscaling_policy``): desired replicas =
``ceil(current * smoothed(total_ongoing / target_ongoing))`` with separate
up/down smoothing factors, bounded by [min, max], and up/down-scale delay
windows implemented as consecutive-decision counters (ref
``_private/autoscaling_state.py`` delay accounting).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional


@dataclass
class AutoscalingConfig:
    """Knobs mirroring serve's AutoscalingConfig (serve/config.py)."""

    min_replicas: int = 1
    max_replicas: int = 8
    target_ongoing_requests: float = 4.0
    upscale_smoothing_factor: float = 1.0
    downscale_smoothing_factor: float = 0.5
    upscale_delay_s: float = 0.0
    downscale_delay_s: float = 2.0


class AutoscalingPolicy:
    """Stateful wrapper adding delay windows around the pure policy."""

    def __init__(self, config: AutoscalingConfig, interval_s: float = 1.0):
        self.config = config
        self.interval_s = interval_s
        self._consecutive_up = 0
        self._consecutive_down = 0

    def desired_replicas(
        self, total_ongoing: float, current_replicas: int
    ) -> int:
        """Pure proportional decision (ref autoscaling_policy.py:42-85)."""
        cfg = self.config
        if current_replicas == 0:
            return cfg.min_replicas if total_ongoing == 0 else max(
                cfg.min_replicas, 1
            )
        error_ratio = total_ongoing / (
            cfg.target_ongoing_requests * current_replicas
        )
        if error_ratio >= 1:
            smoothed = 1 + (error_ratio - 1) * cfg.upscale_smoothing_factor
        else:
            smoothed = 1 - (1 - error_ratio) * cfg.downscale_smoothing_factor
        desired = math.ceil(current_replicas * smoothed)
        return max(cfg.min_replicas, min(cfg.max_replicas, desired))

    def step(
        self, total_ongoing: float, current_replicas: int
    ) -> Optional[int]:
        """Delay-gated decision; returns a new target or None (hold).

        Scale-ups apply after ``upscale_delay_s`` of consistent pressure,
        scale-downs after ``downscale_delay_s`` (ref delay semantics in
        autoscaling_state.py)."""
        desired = self.desired_replicas(total_ongoing, current_replicas)
        if desired > current_replicas:
            self._consecutive_up += 1
            self._consecutive_down = 0
            need = math.ceil(self.config.upscale_delay_s / self.interval_s)
            if self._consecutive_up > need:
                self._consecutive_up = 0
                return desired
        elif desired < current_replicas:
            self._consecutive_down += 1
            self._consecutive_up = 0
            need = math.ceil(self.config.downscale_delay_s / self.interval_s)
            if self._consecutive_down > need:
                self._consecutive_down = 0
                return desired
        else:
            self._consecutive_up = 0
            self._consecutive_down = 0
        return None
