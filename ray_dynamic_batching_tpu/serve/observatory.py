"""SLO observatory — burn-rate alerting, forecast scoring, fidelity drift.

Every prior control-plane defense here trusts a model of the system:
the planner trusts the profile tables, the rate-change trigger trusts
the sliding-window estimate, CI trusts that the sim's hop pricing still
matches live. This module is the layer that MEASURES that trust,
continuously, from inside the control loop — so ROADMAP item 2's
predictive planner lands on instrumented ground instead of hope. Three
instruments share one audited surface:

- **Burn-rate alerting** (:class:`BurnRateMonitor`): per-(deployment,
  qos_class) SLO error budgets consumed from the EXISTING attainment /
  shed counters (``class_stats()`` — misses = violations + stale +
  dropped, the ``sim/report.slo_attainment`` formula), graded over two
  burn windows (fast ~5 m / slow ~1 h) built as rotated cumulative-
  counter epochs — the RollingSketch discipline applied to counters.
  ``burn = miss_fraction_over_window / (1 - slo_target)``: 1.0 means
  the budget spends exactly at the sustainable rate; paging requires
  BOTH windows above ``page_burn`` (the multi-window rule — a fast
  spike alone is noise, a slow burn alone is history). Verdicts drive
  a flap-proof hysteresis machine ``ok -> warning -> page -> resolved``
  (GrayHealthMonitor's streak discipline; a window with too little
  traffic is UNGRADED and holds state — never paged, and never resolved,
  by absence of data).
- **Forecast scoring** (:class:`ForecastScorer`): each tick it asks
  ``RateRegistry`` for a short-horizon arrival forecast per model
  (``RateTracker.forecast_rps`` — EWMA level+trend over the integer-
  second buckets; refuses below ``min_span_s``, the cold-window rule),
  holds the prediction, and when the horizon elapses grades it against
  what ACTUALLY arrived. Errors land in per-model quantile sketches
  (``rdb_forecast_error``) so a planner can gate on "forecast p95
  error < X" instead of trusting an untested predictor. Refusals and
  expired windows are COUNTED, never silent.
- **Fidelity drift** (:class:`FidelityMonitor`): a bounded in-process
  ring of recent real arrivals; every ``replay_every_ticks`` ticks it
  replays them through the installed cost model (``price(model) ->
  {hop: expected_ms}`` — the sim prices from the planner's profile
  rows) into predicted per-hop sketches and grades them against the
  LIVE hop sketches with the existing ``sim/report.hop_drift_report``
  machinery. A drifting hop is NAMED in a ``fidelity_drift`` audit
  record; a hop the cost model cannot price, or with sub-floor
  latencies, or without live samples, is listed ``ungraded`` with its
  reason — never silently skipped.

The whole module is the PR-3/PR-9 shared-component pattern: the SAME
:class:`SLOObservatory` instance shape is ticked by
``ServeController._control_step`` (wall clock) and ``SimScheduler``
(virtual clock) — everything clock-injected, no wall-clock reads, no
unseeded randomness (the ``sim-determinism`` lint walks this file).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_dynamic_batching_tpu.engine.rates import RateRegistry
from ray_dynamic_batching_tpu.utils.concurrency import OrderedLock
from ray_dynamic_batching_tpu.utils.logging import get_logger
from ray_dynamic_batching_tpu.utils import metrics as m
from ray_dynamic_batching_tpu.utils.sketch import QuantileSketch
from ray_dynamic_batching_tpu.utils.tracing import tracer

logger = get_logger("observatory")

ALERT_STATES = ("ok", "warning", "page", "resolved")

SLO_BURN_RATE = m.Gauge(
    "rdb_slo_burn_rate",
    "SLO error-budget burn rate per (deployment, qos, window); 1.0 = "
    "spending the budget exactly at the sustainable rate",
    tag_keys=("deployment", "qos", "window"),
    bounded_tags={"deployment": 8, "qos": 8},
)
SLO_ALERT_STATE = m.Gauge(
    "rdb_slo_alert_state",
    "Burn-rate alert state per (deployment, qos): "
    "0=ok 1=warning 2=page 3=resolved",
    tag_keys=("deployment", "qos"),
    bounded_tags={"deployment": 8, "qos": 8},
)
FORECAST_ERROR = m.Sketch(
    "rdb_forecast_error",
    "Absolute arrival-forecast error (rps) per model, scored when each "
    "prediction's horizon elapses",
    tag_keys=("model",),
    bounded_tags={"model": 8},
)
FIDELITY_DRIFT = m.Gauge(
    "rdb_fidelity_drift",
    "Worst relative drift between the cost model's predicted and the "
    "live per-hop latency sketches, per (hop, model)",
    tag_keys=("hop", "model"),
    bounded_tags={"model": 8},
)


@dataclass(frozen=True)
class ObservatoryPolicy:
    """Knobs for all three instruments. Window lengths are SECONDS of
    the injected clock, so a sim scenario shrinks them onto its virtual
    horizon while live deployments keep the SRE-classic 5 m / 1 h pair.

    The page rule is deliberately two-window (fast AND slow above
    ``page_burn``): the slow window stops a single boundary-straddling
    burst from paging, the fast window stops a long-resolved incident
    from paging forever. ``min_accounted`` is the grading floor — burn
    over three requests is noise, and an UNGRADED tick holds state
    exactly like a gray-health tick without samples."""

    slo_target: float = 0.99        # budget = 1 - slo_target
    fast_window_s: float = 300.0    # ~5 m
    slow_window_s: float = 3600.0   # ~1 h
    epochs_per_window: int = 6      # rotated counter epochs per window
    warn_burn: float = 2.0          # fast burn >= this -> warn-level
    page_burn: float = 10.0         # fast AND slow >= this -> page-level
    min_accounted: int = 10         # window delta needed to grade at all
    warn_after: int = 1             # consecutive warn ticks ok -> warning
    page_after: int = 2             # consecutive page ticks -> page
    resolve_after: int = 2          # consecutive clear ticks -> resolved/ok
    resolved_hold_ticks: int = 2    # quiet resolved ticks -> back to ok
    # --- forecast scoring -------------------------------------------------
    forecast_horizon_s: float = 5.0
    forecast_alpha: float = 0.5
    forecast_beta: float = 0.2
    forecast_min_span_s: float = 3.0  # refuse (not extrapolate) below this
    # --- fidelity drift ---------------------------------------------------
    replay_every_ticks: int = 4
    drift_tolerance: float = 0.5
    drift_min_count: int = 5
    drift_min_abs_ms: float = 1.0   # both sides sub-floor -> ungraded
    arrival_ring: int = 4096


def budget_counters(counters: Dict[str, float]) -> Tuple[float, float]:
    """(misses, accounted) from one cumulative ``class_stats()`` slice —
    the ``sim/report.slo_attainment`` accounting, shared verbatim so the
    burn a live tick grades equals the attainment the report prints."""
    accounted = (counters.get("completed", 0.0)
                 + counters.get("stale", 0.0)
                 + counters.get("dropped", 0.0))
    misses = (counters.get("violations", 0.0)
              + counters.get("stale", 0.0)
              + counters.get("dropped", 0.0))
    return misses, accounted


class BurnWindow:
    """One burn horizon as rotated epochs of CUMULATIVE counter
    snapshots. An epoch closes every ``window_s / epochs`` seconds; the
    window's burn is the delta against the oldest retained snapshot, so
    an incident ages out exactly one epoch at a time and is fully gone
    once the whole window has rotated past it — no decay math, no
    resettable counters, same recency discipline as RollingSketch."""

    def __init__(self, window_s: float, epochs: int, clock) -> None:
        self.window_s = float(window_s)
        self.epoch_s = float(window_s) / max(1, int(epochs))
        self._clock = clock
        # (closed_at_s, misses, accounted); maxlen keeps the oldest
        # baseline ~window_s old.
        self._snaps: deque = deque(maxlen=max(1, int(epochs)) + 1)

    def observe(self, misses: float, accounted: float) -> None:
        now = self._clock()
        if not self._snaps or now - self._snaps[-1][0] >= self.epoch_s:
            self._snaps.append((now, misses, accounted))

    def burn(self, misses: float, accounted: float, budget: float,
             min_accounted: int) -> Optional[float]:
        """Burn rate over the window, or None when the window's delta
        carries too little traffic to grade (never guilty — or clear —
        by absence of data)."""
        if not self._snaps:
            return None
        _, m0, a0 = self._snaps[0]
        d_acc = accounted - a0
        if d_acc < min_accounted:
            return None
        d_miss = max(0.0, misses - m0)
        return (d_miss / d_acc) / max(budget, 1e-9)


@dataclass
class _AlertState:
    fast: BurnWindow
    slow: BurnWindow
    state: str = "ok"
    warn_streak: int = 0
    page_streak: int = 0
    clear_streak: int = 0
    quiet_ticks: int = 0
    since: float = 0.0
    fast_burn: Optional[float] = None
    slow_burn: Optional[float] = None


class BurnRateMonitor:
    """Per-(key, qos_class) burn-rate alert machine over cumulative
    ``class_stats()`` counters. Thread-safe; the injected ``clock``
    keeps the sim deterministic while live callers default to
    ``time.monotonic``."""

    def __init__(self, scope: str, policy: ObservatoryPolicy,
                 clock=time.monotonic) -> None:
        self.scope = scope
        self.policy = policy
        self._clock = clock
        self._lock = OrderedLock("observatory")
        self._states: Dict[Tuple[str, str], _AlertState] = {}
        self.audit = None
        # Bounded ring (GrayHealthMonitor's cap): a flapping deployment
        # must not grow a long-lived monitor without limit.
        self.transitions: deque = deque(maxlen=4096)

    def _st(self, key: Tuple[str, str]) -> _AlertState:
        st = self._states.get(key)
        if st is None:
            p = self.policy
            st = self._states[key] = _AlertState(
                fast=BurnWindow(p.fast_window_s, p.epochs_per_window,
                                self._clock),
                slow=BurnWindow(p.slow_window_s, p.epochs_per_window,
                                self._clock),
                since=self._clock(),
            )
        return st

    def tick(
        self, class_counters: Dict[str, Dict[str, Dict[str, float]]]
    ) -> List[Dict[str, Any]]:
        """Advance every (key, qos) machine one tick from cumulative
        counters (key -> qos -> class_stats slice). Returns the
        transitions this tick caused (also ringed and audited)."""
        p = self.policy
        budget = max(1e-9, 1.0 - p.slo_target)
        fired: List[Dict[str, Any]] = []
        with self._lock:
            for key, per_qos in sorted(class_counters.items()):
                for qos, counters in sorted(per_qos.items()):
                    misses, accounted = budget_counters(counters)
                    st = self._st((key, qos))
                    st.fast.observe(misses, accounted)
                    st.slow.observe(misses, accounted)
                    fast = st.fast.burn(misses, accounted, budget,
                                        p.min_accounted)
                    slow = st.slow.burn(misses, accounted, budget,
                                        p.min_accounted)
                    st.fast_burn, st.slow_burn = fast, slow
                    SLO_BURN_RATE.set(
                        0.0 if fast is None else fast,
                        tags={"deployment": key, "qos": qos,
                              "window": "fast"},
                    )
                    SLO_BURN_RATE.set(
                        0.0 if slow is None else slow,
                        tags={"deployment": key, "qos": qos,
                              "window": "slow"},
                    )
                    if fast is None:
                        # Ungraded tick: hold state, hold streaks.
                        continue
                    page_level = (fast >= p.page_burn
                                  and slow is not None
                                  and slow >= p.page_burn)
                    warn_level = fast >= p.warn_burn
                    if page_level:
                        st.page_streak += 1
                    else:
                        st.page_streak = 0
                    if warn_level:
                        st.warn_streak += 1
                        st.clear_streak = 0
                        st.quiet_ticks = 0
                    else:
                        st.clear_streak += 1
                        st.warn_streak = 0
                        st.quiet_ticks += 1
                    new_state = self._next_state_locked(st)
                    if new_state is not None:
                        fired.append(self._transition_locked(
                            key, qos, st, new_state
                        ))
                    SLO_ALERT_STATE.set(
                        float(ALERT_STATES.index(st.state)),
                        tags={"deployment": key, "qos": qos},
                    )
        for t in fired:
            self._publish(t)
        return fired

    def _next_state_locked(self, st: _AlertState) -> Optional[str]:
        p = self.policy
        if st.state == "ok":
            if st.warn_streak >= p.warn_after:
                return "warning"
        elif st.state == "warning":
            if st.page_streak >= p.page_after:
                return "page"
            if st.clear_streak >= p.resolve_after:
                return "ok"
        elif st.state == "page":
            if st.clear_streak >= p.resolve_after:
                return "resolved"
        elif st.state == "resolved":
            if st.warn_streak >= p.warn_after:
                return "warning"
            if st.quiet_ticks >= p.resolved_hold_ticks:
                return "ok"
        return None

    def _transition_locked(
        self, key: str, qos: str, st: _AlertState, new_state: str
    ) -> Dict[str, Any]:
        record = {
            "at": self._clock(),
            "key": key,
            "qos": qos,
            "from": st.state,
            "to": new_state,
            "fast_burn": (None if st.fast_burn is None
                          else round(st.fast_burn, 3)),
            "slow_burn": (None if st.slow_burn is None
                          else round(st.slow_burn, 3)),
        }
        st.state = new_state
        st.warn_streak = 0
        st.page_streak = 0
        st.clear_streak = 0
        st.quiet_ticks = 0
        st.since = record["at"]
        self.transitions.append(record)
        return record

    def _publish(self, t: Dict[str, Any]) -> None:
        log = logger.warning if t["to"] in ("warning", "page") \
            else logger.info
        log(
            "%s: %s/%s slo-burn %s -> %s (fast=%s slow=%s)",
            self.scope, t["key"], t["qos"], t["from"], t["to"],
            t["fast_burn"], t["slow_burn"],
        )
        if self.audit is not None:
            self.audit.record(
                f"slo_{t['to']}",
                key=t["key"],
                observed={"qos": t["qos"], "fast_burn": t["fast_burn"],
                          "slow_burn": t["slow_burn"]},
                before={"state": t["from"]},
                after={"state": t["to"]},
                diff={"alert": f"{t['from']}->{t['to']}"},
            )
        # Flight record: a zero-length marker span so dump_trace
        # --alerts renders the alert timeline next to the hop ledger
        # (no-op unless an exporter is installed — sim runs stay pure).
        tracer().record_span(
            "observatory.alert", component="observatory",
            deployment=t["key"], qos=t["qos"],
            alert_from=t["from"], alert_to=t["to"],
            fast_burn=t["fast_burn"], slow_burn=t["slow_burn"],
            at_s=t["at"],
        )

    def states(self) -> Dict[str, Dict[str, str]]:
        with self._lock:
            out: Dict[str, Dict[str, str]] = {}
            for (key, qos), st in self._states.items():
                out.setdefault(key, {})[qos] = st.state
            return out

    def snapshot(self, key: Optional[str] = None) -> Dict[str, Any]:
        with self._lock:
            states = {
                f"{k}/{qos}": {
                    "state": st.state,
                    "fast_burn": st.fast_burn,
                    "slow_burn": st.slow_burn,
                    "since": st.since,
                }
                for (k, qos), st in sorted(self._states.items())
                if key is None or k == key
            }
            transitions = [t for t in self.transitions
                           if key is None or t["key"] == key]
            return {"states": states, "transitions": transitions[-20:]}


class ForecastScorer:
    """Holds each model's outstanding arrival forecast and grades it
    when the horizon elapses. Refusals (cold window) and expirations
    (the rate window rotated past the prediction's span before a tick
    could score it) are counted, never silent."""

    def __init__(self, policy: ObservatoryPolicy,
                 clock=time.monotonic) -> None:
        self.policy = policy
        self._clock = clock
        self._lock = OrderedLock("observatory")
        # model -> (made_at_s, predicted_rps)
        self._pending: Dict[str, Tuple[float, float]] = {}
        self._sketches: Dict[str, QuantileSketch] = {}
        self._scored: Dict[str, int] = {}
        self._refused: Dict[str, int] = {}
        self._expired: Dict[str, int] = {}
        self._last: Dict[str, Dict[str, float]] = {}

    def tick(self, rates: RateRegistry) -> None:
        p = self.policy
        now = self._clock()
        with self._lock:
            # 1. Grade predictions whose horizon elapsed.
            for model in sorted(self._pending):
                made_at, predicted = self._pending[model]
                if now - made_at < p.forecast_horizon_s:
                    continue
                del self._pending[model]
                n = rates.tracker(model).count_between(
                    made_at, made_at + p.forecast_horizon_s
                )
                if n is None:
                    # The sliding window rotated past the prediction
                    # span (a stalled control loop): the truth is gone,
                    # so the score would be fiction — count it instead.
                    self._expired[model] = self._expired.get(model, 0) + 1
                    continue
                actual = n / p.forecast_horizon_s
                err = abs(predicted - actual)
                sk = self._sketches.setdefault(model, QuantileSketch())
                sk.observe(err)
                FORECAST_ERROR.observe(err, tags={"model": model})
                self._scored[model] = self._scored.get(model, 0) + 1
                self._last[model] = {
                    "predicted_rps": predicted, "actual_rps": actual,
                }
            # 2. Make the next round of predictions.
            forecasts = rates.forecasts(
                p.forecast_horizon_s,
                alpha=p.forecast_alpha, beta=p.forecast_beta,
                min_span_s=p.forecast_min_span_s,
            )
            for model in sorted(forecasts):
                if model in self._pending:
                    continue
                predicted = forecasts[model]
                if predicted is None:
                    # Cold window: the forecast REFUSES rather than
                    # extrapolating a partial bucket (the PR-3 cold-
                    # window under-read foot-gun).
                    self._refused[model] = self._refused.get(model, 0) + 1
                    continue
                self._pending[model] = (now, predicted)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            models = (set(self._sketches) | set(self._refused)
                      | set(self._expired) | set(self._pending))
            out: Dict[str, Any] = {}
            for model in sorted(models):
                sk = self._sketches.get(model)
                out[model] = {
                    "scored": self._scored.get(model, 0),
                    "refused": self._refused.get(model, 0),
                    "expired": self._expired.get(model, 0),
                    "p50_abs_err_rps": (None if sk is None or not sk.count
                                        else sk.quantile(0.5)),
                    "p95_abs_err_rps": (None if sk is None or not sk.count
                                        else sk.quantile(0.95)),
                    **({"last": dict(self._last[model])}
                       if model in self._last else {}),
                }
            return out


# price(model) -> {hop: expected_ms} or None when the cost model has no
# belief about the model (not yet planned / unknown).
PriceFn = Callable[[str], Optional[Dict[str, float]]]


class FidelityMonitor:
    """Online sim-vs-live drift: ring-buffer real arrivals, replay them
    through the installed cost model every N ticks, and grade predicted
    vs live per-hop sketches with ``sim/report.hop_drift_report`` —
    PR 8's guilty-hop CI sentinel promoted to a continuously-running
    instrument. Contract: a hop is GRADED only when the cost model
    prices it AND both sides carry enough super-floor samples;
    everything else is listed under ``ungraded`` with its counts —
    never silently skipped.

    Each arrival is stamped with the cost model's price AT ARRIVAL
    TIME, so the predicted sketch is the same *mixture* the live hop
    sketch accumulates: a replan that re-sizes batches changes the
    price for future arrivals without retroactively indicting (or
    absolving) requests the old plan served. Grading current price
    against cumulative history would flag every replan as drift."""

    def __init__(self, scope: str, policy: ObservatoryPolicy,
                 clock=time.monotonic,
                 price: Optional[PriceFn] = None) -> None:
        self.scope = scope
        self.policy = policy
        self._clock = clock
        self.price = price
        self.audit = None
        self._lock = OrderedLock("observatory")
        # (t_s, model, price-at-arrival) ring — the PR-3 WorkloadDriver
        # recording path, in-process and bounded.
        self._ring: deque = deque(maxlen=policy.arrival_ring)
        self._ticks = 0
        self.replays = 0
        self._last: Dict[str, Any] = {}
        # model -> last drifting-hop tuple (audit on CHANGE, not every
        # replay — a steady drift is one record, not a record per tick).
        self._last_drifting: Dict[str, tuple] = {}

    def note_arrivals(self, model: str, n: int = 1) -> None:
        if n <= 0:
            return
        now = self._clock()
        prices = self.price(model) if self.price is not None else None
        with self._lock:
            for _ in range(min(int(n), self._ring.maxlen)):
                self._ring.append((now, model, prices))

    def tick(self, live_hops: Dict[str, Dict[str, Any]]) -> None:
        """``live_hops``: model -> hop -> sketch-like (``.count`` +
        ``.quantile``). Replays only every ``replay_every_ticks`` ticks;
        intermediate ticks just advance the cadence counter."""
        with self._lock:
            self._ticks += 1
            if self._ticks % max(1, self.policy.replay_every_ticks):
                return
            window = list(self._ring)
        self._replay(window, live_hops)

    def _replay(self, window: List[Tuple[float, str, Any]],
                live_hops: Dict[str, Dict[str, Any]]) -> None:
        from ray_dynamic_batching_tpu.sim.report import hop_drift_report

        p = self.policy
        self.replays += 1
        arrivals_by_model: Dict[str, int] = {}
        priced_by_model: Dict[str, int] = {}
        predicted_by_model: Dict[str, Dict[str, QuantileSketch]] = {}
        for _, model, prices in window:
            arrivals_by_model[model] = arrivals_by_model.get(model, 0) + 1
            if not prices:
                continue
            priced_by_model[model] = priced_by_model.get(model, 0) + 1
            sketches = predicted_by_model.setdefault(model, {})
            for hop, ms in prices.items():
                if hop not in sketches:
                    sketches[hop] = QuantileSketch()
                sketches[hop].observe(float(ms))
        reports: Dict[str, Any] = {}
        drift_changes: List[Dict[str, Any]] = []
        for model in sorted(set(arrivals_by_model) | set(live_hops)):
            report = hop_drift_report(
                live_hops.get(model, {}),
                predicted_by_model.get(model, {}),
                tolerance=p.drift_tolerance,
                min_count=p.drift_min_count,
            )
            for hop, entry in report["ungraded"].items():
                # Never-silent: say WHY each ungraded hop went ungraded.
                entry["reason"] = (
                    "not-priced" if entry["sim_count"] == 0
                    else "no-live-samples" if entry["live_count"] == 0
                    else "insufficient-samples"
                )
            self._apply_floor(report)
            if not priced_by_model.get(model):
                report["ungraded_reason"] = "unpriced: no cost model"
            reports[model] = report
            for hop, entry in report["hops"].items():
                FIDELITY_DRIFT.set(entry["worst_drift"],
                                   tags={"hop": hop, "model": model})
            drifting = tuple(report["drifting_hops"])
            if drifting != self._last_drifting.get(model, ()):
                drift_changes.append({
                    "at": self._clock(),
                    "model": model,
                    "drifting_hops": list(drifting),
                    "was": list(self._last_drifting.get(model, ())),
                    "hops": {
                        hop: round(entry["worst_drift"], 4)
                        for hop, entry in report["hops"].items()
                    },
                })
                self._last_drifting[model] = drifting
        with self._lock:
            self._last = {
                "at": self._clock(),
                "arrivals_replayed": len(window),
                "models": reports,
            }
        for change in drift_changes:
            self._publish(change)

    def _apply_floor(self, report: Dict[str, Any]) -> None:
        """Move graded hops where BOTH sides sit under the latency floor
        into ``ungraded``: a 0.2 ms live wait vs a 0 ms prediction is a
        relative drift of 1.0 and a lie — sub-floor hops carry no
        pricing signal either way."""
        floor = self.policy.drift_min_abs_ms
        for hop in list(report["hops"]):
            entry = report["hops"][hop]
            sides = [q["live_ms"] for k, q in entry.items()
                     if isinstance(q, dict)]
            sides += [q["sim_ms"] for k, q in entry.items()
                      if isinstance(q, dict)]
            if sides and max(sides) < floor:
                del report["hops"][hop]
                report["ungraded"][hop] = {
                    "live_count": entry["live_count"],
                    "sim_count": entry["sim_count"],
                    "reason": "sub-floor",
                }
                if hop in report["drifting_hops"]:
                    report["drifting_hops"].remove(hop)
        report["ok"] = not report["drifting_hops"]

    def _publish(self, change: Dict[str, Any]) -> None:
        drifting = change["drifting_hops"]
        if drifting:
            logger.warning(
                "%s: fidelity drift on %s — mispriced hop(s) %s (%s)",
                self.scope, change["model"], drifting, change["hops"],
            )
        else:
            logger.info("%s: fidelity drift on %s cleared",
                        self.scope, change["model"])
        if self.audit is not None:
            self.audit.record(
                "fidelity_drift" if drifting else "fidelity_clean",
                key=change["model"],
                observed={"hops": change["hops"]},
                before={"drifting_hops": change["was"]},
                after={"drifting_hops": drifting},
                diff={"mispriced": drifting},
            )
        tracer().record_span(
            "observatory.drift", component="observatory",
            model=change["model"],
            drifting_hops=",".join(drifting),
            at_s=change["at"],
        )

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "replays": self.replays,
                "ring_depth": len(self._ring),
                "last": dict(self._last),
            }


class SLOObservatory:
    """The three instruments behind one tick and one audited snapshot —
    constructed once, ticked by ``ServeController._control_step`` live
    and by ``SimScheduler._on_monitor`` at virtual time (the same
    classes, no re-expression)."""

    def __init__(self, scope: str,
                 policy: Optional[ObservatoryPolicy] = None,
                 clock=time.monotonic,
                 price: Optional[PriceFn] = None) -> None:
        self.scope = scope
        self.policy = policy or ObservatoryPolicy()
        self._clock = clock
        self.burn = BurnRateMonitor(scope, self.policy, clock=clock)
        self.forecast = ForecastScorer(self.policy, clock=clock)
        self.fidelity = FidelityMonitor(scope, self.policy, clock=clock,
                                        price=price)

    @property
    def audit(self):
        return self.burn.audit

    @audit.setter
    def audit(self, log) -> None:
        self.burn.audit = log
        self.fidelity.audit = log

    def note_arrivals(self, model: str, n: int = 1) -> None:
        """Feed the fidelity replay ring (the host also records the same
        arrivals into its RateRegistry — demand is counted once per
        consumer, at the same door)."""
        self.fidelity.note_arrivals(model, n)

    def tick(
        self,
        class_counters: Dict[str, Dict[str, Dict[str, float]]],
        rates: RateRegistry,
        live_hops: Optional[Dict[str, Dict[str, Any]]] = None,
    ) -> List[Dict[str, Any]]:
        """One observatory tick: grade burn, score/refresh forecasts,
        advance the fidelity replay cadence. Returns the burn-alert
        transitions this tick fired."""
        fired = self.burn.tick(class_counters)
        self.forecast.tick(rates)
        self.fidelity.tick(live_hops or {})
        return fired

    def snapshot(self, key: Optional[str] = None) -> Dict[str, Any]:
        """JSON-clean block shared by controller ``status()`` and the
        sim report (``key`` filters the burn view to one deployment;
        forecast/fidelity are per-model already)."""
        return {
            "alerts": self.burn.snapshot(key=key),
            "forecast": self.forecast.snapshot(),
            "fidelity": self.fidelity.snapshot(),
        }
