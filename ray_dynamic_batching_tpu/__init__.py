"""ray_dynamic_batching_tpu — a TPU-native dynamic-batching inference-serving framework.

A ground-up re-design (NOT a port) of the capabilities of
milind7777/ray-dynamic-batching: SLO-aware, profile-driven multi-model serving
("squishy bin packing", Nexus §6.1) plus the distributed substrate it rides on —
rebuilt idiomatically for TPU on JAX/XLA/pjit/Pallas:

- compiled, shape-bucketed ``jax.jit`` steps instead of eager torch forwards
- HBM budgets + compile-cost amortization instead of CUDA-OOM backoff
- ``jax.sharding.Mesh`` + XLA collectives over ICI instead of NCCL groups
- a thin asyncio actor runtime + native C++ hot-path helpers instead of Ray core

Layer map (mirrors SURVEY.md section 7):

  utils/      config, metrics, logging, tracing            (ref: src/ray/common, util)
  profiles/   offline batch profiler + profile tables      (ref: 293-project/profiling)
  models/     flax model zoo with logical-axis shardings   (ref: torchvision registry)
  ops/        pallas TPU kernels (attention etc.)          (new, TPU-first)
  parallel/   mesh manager, TP/DP/SP shardings, ring attn  (ref: ray.util.collective)
  engine/     queues, batching policies, replica engine    (ref: 293-project/src/scheduler.py)
  scheduler/  squishy bin packing + live control loop      (ref: 293-project/src/nexus.py)
  serve/      HTTP ingress, router, deployments, autoscale (ref: python/ray/serve)
  runtime/    asyncio actors, KV store, health, chaos      (ref: src/ray/{gcs,raylet,core_worker})
"""

__version__ = "0.1.0"

from ray_dynamic_batching_tpu.utils.config import RDBConfig, get_config  # noqa: F401
